"""Sampling CLI — reference ``sample.py`` equivalent
(``/root/reference/sample.py:23-76``): load the last checkpoint, rebuild the
model from its stored config, decode with a prime, print.  Decoding runs
the cached scan sampler instead of O(L) full forwards.
"""

import click

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()


@click.command()
@click.option("--seed", default=42)
@click.option("--checkpoint_path", default="./ckpts")
@click.option("--prime", default="")
@click.option("--top_k", default=25)
@click.option("--temperature", default=1.0)
@click.option("--num_samples", default=1, help="decode N sequences in one batch")
@click.option("--seq_len", default=None, type=int,
              help="decode length (reference sample.py flag); defaults to "
                   "the model's trained seq_len, capped there (the learned "
                   "gMLP weights have no rows past it). Short decodes are "
                   "cheap: caches and the scan are sized to this length.")
@click.option("--mesh", "mesh_spec", default=None,
              help="mesh axis sizes data,fsdp,tensor,seq (-1 = remaining); "
                   "restores the params SHARDED over the mesh and decodes "
                   "SPMD — required when the model does not fit one chip")
@click.option("--strategies", default="fsdp",
              help="comma list of sharding strategies for --mesh restores")
@click.option("--serve", is_flag=True,
              help="decode through the continuous-batching engine instead of "
                   "the batch-synchronous sampler: primes (split --prime on "
                   "'|', or --num_samples copies) become queued requests, "
                   "prefilled in one parallel forward and decoded in early-"
                   "exit chunks (docs/SERVING.md)")
@click.option("--embed", "embed_mode", is_flag=True,
              help="with --serve: embeddings workload — one-pass prefill "
                   "forward per prime, mean-pooled final-layer hidden "
                   "state; prints the (D,) vector stats instead of decoded "
                   "tokens (docs/SERVING.md §8)")
@click.option("--infill", default=None, metavar="TEMPLATE",
              help="with --serve: constrained span-infilling — plain "
                   "characters are frozen scaffold positions, '?' a free "
                   "design position, '[ILV]' a position restricted to that "
                   "set; the engine decodes under the scaffold's per-"
                   "position logit mask so constrained positions can ONLY "
                   "emit allowed tokens (docs/SERVING.md §8)")
@click.option("--slots", default=8, help="engine: max concurrent requests")
@click.option("--chunk", default=32, help="engine: decode steps per device "
                                          "program between refill points")
@click.option("--paged", is_flag=True,
              help="engine: paged SGU gate cache — global page pool + "
                   "per-request page tables instead of per-slot max_len "
                   "slabs (docs/SERVING.md); greedy outputs are "
                   "bit-identical to the fixed-slot engine")
@click.option("--page_size", default=16, help="engine: token rows per page "
                                              "(with --paged)")
@click.option("--quantize", "quantize_mode", default=None,
              type=click.Choice(["weights", "weights+pages"]),
              help="engine: opt-in int8 serving — 'weights' re-types dense "
                   "kernels and SGU spatial weights to int8 (f32 per-channel "
                   "scales); 'weights+pages' additionally stores the paged "
                   "SGU gate cache as 8-bit pages (requires --paged).  Full "
                   "precision stays the default; accuracy is gated by "
                   "bench_serving --verify (docs/SERVING.md §12)")
@click.option("--serve_attempts", default=3,
              help="engine: total tries of the serve loop — a transient "
                   "failure snapshots the host-side request state, rebuilds "
                   "the engine and REPLAYS the in-flight requests (per-"
                   "request seed determinism makes the replay token-"
                   "identical; 1 = fail fast)")
@click.option("--snapshot_path", default=None, metavar="FILE",
              help="engine: where crash snapshots are persisted (JSON, "
                   "host-side request state only; default: not written "
                   "to disk)")
@click.option("--aot_warmup", is_flag=True,
              help="engine: AOT-compile every (prefill bucket, decode "
                   "chunk) program via jit(...).lower().compile() before "
                   "accepting traffic, so no request pays a JIT pause")
@click.option("--spec", is_flag=True,
              help="engine: speculative decoding — a draft model proposes "
                   "--spec_k tokens per round, verified in one target step; "
                   "greedy output is bit-identical to non-spec decode "
                   "(docs/SERVING.md)")
@click.option("--spec_k", default=4, help="engine: draft tokens proposed per "
                                          "speculation round (with --spec)")
@click.option("--disagg", is_flag=True,
              help="engine: disaggregated serving — prefill runs in a "
                   "separate worker program whose cache handles are merged "
                   "into decode slots via a bounded handoff queue, so long "
                   "prefills no longer stall in-flight decode "
                   "(docs/SERVING.md)")
@click.option("--serve_procs", is_flag=True,
              help="with --serve: multi-process serving — spawn prefill "
                   "worker and decode replica SUBPROCESSES (each its own "
                   "JAX runtime) behind a router; cache handles cross "
                   "processes as CRC-framed zero-copy frames "
                   "(docs/SERVING.md §7). Workers rebuild the model from "
                   "this checkpoint, so output is token-identical to the "
                   "in-process engine")
@click.option("--prefill_procs", default=1,
              help="prefill worker processes (with --serve_procs)")
@click.option("--replicas", default=1,
              help="decode replica processes (with --serve_procs)")
@click.option("--autoscale", is_flag=True,
              help="with --serve_procs: run the elastic control plane — "
                   "scale the fleet between the min/max bounds on SLO "
                   "burn rate and queue depth; decisions are journaled "
                   "and printed (docs/SERVING.md §9)")
@click.option("--min_prefill", default=None, type=int,
              help="autoscale floor for prefill workers "
                   "(default: --prefill_procs)")
@click.option("--max_prefill", default=None, type=int,
              help="autoscale ceiling for prefill workers "
                   "(default: --prefill_procs + 2)")
@click.option("--min_replicas", default=None, type=int,
              help="autoscale floor for decode replicas "
                   "(default: --replicas)")
@click.option("--max_replicas", default=None, type=int,
              help="autoscale ceiling for decode replicas "
                   "(default: --replicas + 2)")
@click.option("--swap_at", default=None, type=int,
              help="with --serve_procs: after N completions, hot-swap "
                   "weights with a zero-downtime rolling worker upgrade "
                   "(new generation of the same checkpoint) — no request "
                   "is dropped; completions report their generation")
@click.option("--watchdog_timeout", default=None, type=float,
              help="engine: seconds without a completed serve step before "
                   "the watchdog dumps all-thread stacks to CWD and exits "
                   "nonzero (unset = off); compiles are exempt")
@click.option("--statusz", is_flag=True,
              help="with --serve_procs: serve live /healthz /statusz "
                   "/metricsz in every process (driver + workers) on "
                   "ephemeral loopback ports, printed at startup; "
                   "zero-perturbation (docs/OBSERVABILITY.md)")
@click.option("--trace", is_flag=True,
              help="record request spans in every serving process and "
                   "merge them into one Perfetto trace.json under "
                   "--trace_out (docs/OBSERVABILITY.md)")
@click.option("--trace_out", default="trace_out", metavar="DIR",
              help="directory for per-process trace dumps and the merged "
                   "trace.json (with --trace)")
@click.option("--xprof_dir", default=None, metavar="DIR",
              help="record an xprof/TensorBoard profile of the decode "
                   "into this directory (view with tensorboard)")
@click.option("--compile_cache", default=None, metavar="DIR",
              help="JAX persistent compilation cache directory ('0' "
                   "disables); overrides PROGEN_COMPILE_CACHE, default "
                   "~/.cache/progen_tpu/xla")
def main(seed, checkpoint_path, prime, top_k, temperature, num_samples,
         seq_len, mesh_spec, strategies, serve, embed_mode, infill, slots,
         chunk, paged, page_size, quantize_mode, serve_attempts,
         snapshot_path, aot_warmup,
         spec, spec_k, disagg, serve_procs, prefill_procs, replicas,
         autoscale, min_prefill, max_prefill, min_replicas, max_replicas,
         swap_at, watchdog_timeout, statusz, trace, trace_out, xprof_dir,
         compile_cache):
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from progen_tpu.core.cache import enable_compilation_cache

    if compile_cache is not None:
        os.environ["PROGEN_COMPILE_CACHE"] = compile_cache
    enable_compilation_cache()  # the decode scan is minutes of compile

    from progen_tpu.checkpoint import CheckpointStore, abstract_params_like
    from progen_tpu.core.precision import make_policy
    from progen_tpu.core.rng import KeySeq
    from progen_tpu.data import decode_tokens, encode_tokens
    from progen_tpu.decode import make_sampler
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.observe import profile_trace
    from progen_tpu.observe.trace import (
        configure_tracing,
        get_tracer,
        merge_trace_dir,
        trace_dump_path,
    )

    if trace:
        os.makedirs(trace_out, exist_ok=True)
        configure_tracing(enabled=True, process="driver")

    store = CheckpointStore(checkpoint_path)
    meta = store.restore_meta()
    if meta is None:
        raise SystemExit(f"no checkpoints found at {checkpoint_path}")

    model_config = ProGenConfig.from_dict(meta["model_config"])
    policy = make_policy(True)
    model = ProGen(config=model_config, policy=policy)
    sample_tokens = jnp.zeros((1, model_config.seq_len), jnp.int32)

    mesh = None
    strategy_list = tuple(strategies.split(","))
    param_sh = None
    if mesh_spec is not None:
        from progen_tpu.core.mesh import MeshConfig, make_mesh
        from progen_tpu.parallel.sharding import param_shardings

        try:
            mesh = make_mesh(MeshConfig.parse(mesh_spec))
        except ValueError as e:
            raise click.BadParameter(str(e), param_hint="--mesh")
        # restore each shard straight to its device — no host ever holds
        # the full state (the whole point for >1-chip models)
        param_sh = param_shardings(
            model, sample_tokens, mesh, strategy_list)["params"]
    params = store.restore_params(
        abstract_params_like(model, sample_tokens, shardings=param_sh))
    store.close()

    num_params = sum(x.size for x in jax.tree.leaves(params))
    if seq_len is None:
        seq_len = model_config.seq_len
    elif seq_len > model_config.seq_len:
        print(f"capping --seq_len {seq_len} to the model's trained "
              f"seq_len {model_config.seq_len}")
        seq_len = model_config.seq_len
    print(f"params: {num_params:,}")
    print(f"sequence length: {seq_len}")
    print(f"trained for {max(meta['next_seq_index'], 0)} sequences")

    if (embed_mode or infill) and not serve:
        raise click.BadParameter(
            "--embed/--infill are serving workloads; add --serve",
            param_hint="--serve")
    if embed_mode and infill:
        raise click.BadParameter("pick ONE of --embed / --infill",
                                 param_hint="--embed")

    if serve:
        from progen_tpu.decode import Request, ServingEngine, run_with_restarts
        from progen_tpu.resilience import Watchdog

        primes = prime.split("|") if "|" in prime else [prime] * num_samples
        requests = []
        if infill is not None:
            from progen_tpu.workloads import ScaffoldSpec

            def template_entry(seg):
                if seg == "?":
                    return None
                if len(seg) > 1:  # bracket set [ILV]
                    return tuple(encode_tokens(c)[0] for c in seg)
                return encode_tokens(seg)[0]

            segs, i = [], 0
            while i < len(infill):
                if infill[i] == "[":
                    j = infill.index("]", i)
                    segs.append(infill[i + 1:j])
                    i = j + 1
                else:
                    segs.append(infill[i])
                    i += 1
            scaffold = ScaffoldSpec(
                template=[0] + [template_entry(s) for s in segs],
                vocab=model_config.num_tokens)
            primes = [infill] * num_samples
            kw = scaffold.request_kwargs()
            requests = [Request(uid=i, top_k=top_k, temperature=temperature,
                                seed=seed + i, workload="infill", **kw)
                        for i in range(num_samples)]
        else:
            for i, p in enumerate(primes):
                toks = [0] + encode_tokens(p)  # BOS-prefixed, like add_bos
                requests.append(Request(
                    uid=i, tokens=toks, max_new_tokens=seq_len - len(toks),
                    top_k=top_k, temperature=temperature, seed=seed + i,
                    workload="embed" if embed_mode else "generate"))

        def print_embedding(comp):
            v = np.asarray(comp.embedding)
            print(f"\n {primes[comp.uid]} \n", "*" * 40,
                  f"[embed, dim={v.shape[0]}, "
                  f"norm={float(np.linalg.norm(v)):.4f}, "
                  f"{comp.latency:.2f}s]\n", np.round(v[:8], 4).tolist())

        if serve_procs:
            if mesh_spec is not None:
                raise click.BadParameter(
                    "--mesh shards ONE process's decode over devices; "
                    "--serve_procs spawns single-device worker processes — "
                    "pick one", param_hint="--serve_procs")
            from progen_tpu.serve import ServeCluster, make_spec

            # workers rebuild bit-identical params by restoring this same
            # checkpoint, so cluster output matches the in-process engine
            wspec = make_spec(
                model_config, mixed_precision=True,
                checkpoint_path=os.path.abspath(checkpoint_path),
                engine=dict(num_slots=slots, chunk_size=chunk,
                            max_len=seq_len, paged=paged,
                            page_size=page_size, spec=spec, spec_k=spec_k,
                            quantize=quantize_mode),
                trace=({"dir": os.path.abspath(trace_out)}
                       if trace else None),
                statusz=statusz)
            cluster = ServeCluster(wspec, prefill_procs=prefill_procs,
                                   replicas=replicas)
            control = None
            if autoscale or swap_at is not None:
                from progen_tpu.serve import BurnRatePolicy, ControlPlane

                control = ControlPlane(cluster, BurnRatePolicy(
                    min_prefill=min_prefill or prefill_procs,
                    max_prefill=max_prefill or prefill_procs + 2,
                    min_replicas=min_replicas or replicas,
                    max_replicas=max_replicas or replicas + 2))
            if statusz:
                ports = cluster.stats().get("statusz_ports", {})
                for who, p in sorted(ports.items()):
                    print(f"statusz[{who}]: http://127.0.0.1:{p}")
            try:
                with profile_trace(xprof_dir):
                    for r in requests:
                        if embed_mode:
                            cluster.submit_embed(r)
                        else:
                            cluster.submit(r)
                    if control is None:
                        completions = cluster.drain()
                    else:
                        # drive loop with control ticks between polls:
                        # the autoscaler acts on live burn/queue signals
                        # and --swap_at rolls the fleet mid-stream
                        completions = []
                        swapped = False
                        while cluster.pending:
                            completions.extend(cluster.poll(timeout=0.2))
                            if (swap_at is not None and not swapped
                                    and len(completions) >= swap_at):
                                swapped = True
                                gen = control.swap_weights()
                                print(f"swap: rolled fleet to "
                                      f"generation {gen}")
                            if autoscale:
                                control.tick()
            finally:
                if control is not None:
                    for e in control.journal:
                        if e["event"] in ("scale_up", "scale_down"):
                            print(f"autoscale: {e['event']} {e['role']} "
                                  f"(cause={e['cause']}, "
                                  f"observed={e['observed']})")
                cluster.shutdown()
            if trace:
                merged = merge_trace_dir(trace_out)
                if merged:
                    print(f"trace: {merged}")
            for comp in sorted(completions, key=lambda c: c.uid):
                if comp.embedding is not None:
                    print_embedding(comp)
                    continue
                print(f"\n {primes[comp.uid]} \n", "*" * 40,
                      f"[{comp.finish_reason}, {len(comp.tokens)} tokens, "
                      f"{comp.latency:.2f}s]\n", decode_tokens(comp.tokens))
            return

        watchdog = None
        if watchdog_timeout:
            watchdog = Watchdog(watchdog_timeout, out_dir=".",
                                label="serve")
            watchdog.start()

        def engine_factory():
            eng = ServingEngine(
                model_config, {"params": params}, policy=policy,
                num_slots=slots, chunk_size=chunk, max_len=seq_len,
                paged=paged, page_size=page_size, quantize=quantize_mode,
                spec=spec, spec_k=spec_k, disagg=disagg,
                mesh=mesh, strategies=strategy_list,
                params_shardings=param_sh, watchdog=watchdog)
            if aot_warmup:
                stats = eng.aot_warmup(embed=embed_mode)
                print(f"aot warmup: {stats['programs']} programs in "
                      f"{stats['seconds']:.1f}s")
            return eng

        try:
            with profile_trace(xprof_dir):
                if embed_mode:
                    eng = engine_factory()
                    for r in requests:
                        eng.submit_embed(r)
                    completions = eng.run_until_idle()
                else:
                    completions = run_with_restarts(
                        engine_factory, requests, attempts=serve_attempts,
                        snapshot_path=snapshot_path)
        finally:
            if watchdog is not None:
                watchdog.stop()
        if trace:
            get_tracer().dump(trace_dump_path(trace_out, "driver"))
            merged = merge_trace_dir(trace_out)
            if merged:
                print(f"trace: {merged}")
        for comp in sorted(completions, key=lambda c: c.uid):
            if comp.embedding is not None:
                print_embedding(comp)
                continue
            print(f"\n {primes[comp.uid]} \n", "*" * 40,
                  f"[{comp.finish_reason}, {len(comp.tokens)} tokens, "
                  f"{comp.latency:.2f}s]\n", decode_tokens(comp.tokens))
        return

    prime_tokens = encode_tokens(prime)
    prime_length = len(prime_tokens) + 1  # + BOS
    batch = jnp.tile(jnp.asarray(prime_tokens, jnp.int32)[None, :]
                     if prime_tokens else jnp.zeros((1, 0), jnp.int32),
                     (num_samples, 1))

    sampler = make_sampler(model_config, policy, mesh=mesh,
                           strategies=strategy_list, params_shardings=param_sh)
    keys = KeySeq(seed)
    # add_bos handles empty primes too (a lone BOS column primes the model)
    with profile_trace(xprof_dir):
        if batch.shape[1] == 0:
            batch = jnp.zeros((num_samples, 1), jnp.int32)
            sampled = sampler({"params": params}, next(keys), batch,
                              length=seq_len, top_k=top_k,
                              temperature=temperature)
            prime_length = 1
        else:
            sampled = sampler({"params": params}, next(keys), batch,
                              length=seq_len, top_k=top_k, add_bos=True,
                              temperature=temperature)

    for row in np.asarray(sampled):
        print("\n", prime, "\n", "*" * 40, "\n",
              decode_tokens(row[prime_length:]))


if __name__ == "__main__":
    main()
