"""Model-FLOPs accounting and MFU estimation.

BASELINE.md's headline metric is tokens/sec/chip, which is meaningless
across model scales; MFU (model FLOPs utilization) normalizes it against
the chip's peak so throughput claims stay honest (the reference publishes
no numbers at all — SURVEY.md §6).  Shared by ``bench.py`` and the
training loop's live metrics.
"""

from __future__ import annotations

import jax

# bf16 peak by jax device_kind; extend as new generations appear.
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def model_flops_per_token(cfg, num_params: int) -> float:
    """Training FLOPs (fwd+bwd) per token: the standard 6N for every dense
    parameter (the SGU spatial weights are parameters, so 6N covers them)
    plus the windowed-attention score/value matmuls, which touch 2*wsz keys
    per query: fwd 8*wsz*inner FLOPs/token/layer, x3 with the backward."""
    inner = cfg.heads * cfg.dim_head
    attn = 24.0 * cfg.window_size * inner * cfg.depth
    return 6.0 * num_params + attn


def peak_flops_per_chip(device=None) -> float | None:
    """Peak bf16 FLOP/s of the local accelerator, or None off-TPU /
    unknown kind (callers skip MFU then)."""
    device = device or jax.devices()[0]
    tflops = PEAK_BF16_TFLOPS.get(device.device_kind)
    return None if tflops is None else tflops * 1e12


def mfu(tokens_per_sec_per_chip: float, flops_per_token: float,
        peak: float | None) -> float | None:
    if peak is None or peak <= 0:
        return None
    return flops_per_token * tokens_per_sec_per_chip / peak
