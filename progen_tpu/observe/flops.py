"""Model-FLOPs accounting and MFU estimation.

BASELINE.md's headline metric is tokens/sec/chip, which is meaningless
across model scales; MFU (model FLOPs utilization) normalizes it against
the chip's peak so throughput claims stay honest (the reference publishes
no numbers at all — SURVEY.md §6).  Shared by ``bench.py`` and the
training loop's live metrics.
"""

from __future__ import annotations

import jax

# bf16 peak by jax device_kind; extend as new generations appear.
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,
    "TPU v5e": 197.0,
    "TPU v5p": 459.0,
    "TPU v6 lite": 918.0,
    "TPU v6e": 918.0,
}


def model_flops_per_token(cfg, num_params: int,
                          sgu_impl: str = "xla") -> float:
    """Training FLOPs (fwd+bwd) per token: the standard 6N for every dense
    parameter plus the windowed-attention score/value matmuls, which touch
    2*wsz keys per query: fwd 8*wsz*inner FLOPs/token/layer, x3 with the
    backward.

    The SGU spatial ``(n, n)`` weights are parameters but their matmul
    contracts over TOKENS, not features — 6N would charge 6·n² per token
    where the real cost is 6·n·(d_ff/2) per token (dense) — so they are
    pulled out of 6N and charged by the matmul actually executed:
    ``2·n²·(d_ff/2)`` per sequence forward for the dense xla einsum, half
    that for the blocked-causal pallas kernel (upper-triangle blocks are
    skipped; ``ops/pallas_sgu.py``), x3 with the backward.
    """
    inner = cfg.heads * cfg.dim_head
    attn = 24.0 * cfg.window_size * inner * cfg.depth
    n_gmlp = min(cfg.global_mlp_depth, cfg.depth)
    n = cfg.seq_len
    d_half = cfg.dim * cfg.ff_mult // 2
    spatial_params = n_gmlp * (n * n + n)  # weights + biases per gmlp layer
    causal = 0.5 if sgu_impl == "pallas" else 1.0
    sgu = 6.0 * n * d_half * causal * n_gmlp  # 3 x fwd 2·n·d_half per token
    return 6.0 * (num_params - spatial_params) + attn + sgu


def peak_flops_per_chip(device=None) -> float | None:
    """Peak bf16 FLOP/s of the local accelerator, or None off-TPU /
    unknown kind (callers skip MFU then)."""
    device = device or jax.devices()[0]
    tflops = PEAK_BF16_TFLOPS.get(device.device_kind)
    return None if tflops is None else tflops * 1e12


def mfu(tokens_per_sec_per_chip: float, flops_per_token: float,
        peak: float | None) -> float | None:
    if peak is None or peak <= 0:
        return None
    return flops_per_token * tokens_per_sec_per_chip / peak
