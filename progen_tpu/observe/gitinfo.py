"""Resolve the repo's git SHA for benchmark record attribution.

Benchmark JSON records are only comparable across time when each one says
which commit produced it; ``git_sha()`` is best-effort (returns ``None``
outside a work tree or without git on PATH) so benchmarks never fail on
account of provenance.
"""

from __future__ import annotations

import functools
import subprocess
from pathlib import Path

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent


@functools.lru_cache(maxsize=1)
def git_sha(short: bool = False) -> str | None:
    """Current HEAD commit (``None`` when unresolvable). Cached per process."""
    cmd = ["git", "rev-parse", "--short" if short else "--verify", "HEAD"]
    try:
        out = subprocess.run(
            cmd,
            cwd=_REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=5,
            check=False,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    sha = out.stdout.strip()
    return sha if out.returncode == 0 and sha else None
