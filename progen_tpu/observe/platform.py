"""Platform capability probing shared by every benchmark entrypoint.

Four benchmark drivers (``bench.py``, ``benchmarks/bench_serving.py``,
``benchmarks/bench_sgu.py``, ``benchmarks/bench_superstep.py``) need the
same two things before touching an accelerator:

* :func:`probe_backend` — verify the backend actually comes up, in a
  SUBPROCESS: TPU runtime init can fail transiently (libtpu UNAVAILABLE
  when another process briefly holds the chips) or HANG outright in its
  metadata fetches while holding the GIL, so an in-process thread
  timeout can never fire.  Attempts retry via the resilience layer
  (``PROGEN_BENCH_RETRY_*`` env knobs).
* :func:`emit_error_record` — when the backend (or the run itself) is
  beyond saving, print ONE parseable JSON error line with a platform
  stamp and keep rc 0, so the capture driver ingests a structured record
  instead of a raw traceback.

Historically these lived in ``bench.py`` and the other drivers imported
the root script — a working-directory trap and a circular layering smell.
This module is the shared home (ROADMAP item 4's cleanup).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

from progen_tpu.observe.gitinfo import git_sha

# last wall_time stamped by this process — records within one process are
# guaranteed strictly increasing even if the wall clock steps backwards
# (NTP slew mid-benchmark), so tools/benchdiff.py can order same-sha
# records by wall_time alone
_last_wall = 0.0


def stamp_record(record: dict | None = None, **extra) -> dict:
    """The one door every benchmark JSON record leaves through.

    Merges ``extra`` into a copy of ``record`` and guarantees the
    ``git_sha`` and ``wall_time`` stamps, so a record can always be
    traced back to the code that produced it and ordered against other
    records of the same metric (``tools/benchdiff.py`` picks the latest
    per file by ``wall_time``).  ``wall_time`` is monotonic-safe within
    a process; callers on a traced path pass ``wall_time=...`` captured
    outside the timed region rather than letting this function read the
    clock.  Callers pass their fields and never touch
    :func:`~progen_tpu.observe.gitinfo.git_sha` directly —
    ``tests/test_observe.py`` sweeps the bench sources to keep it that
    way."""
    global _last_wall
    import time

    out = dict(record or {})
    out.update(extra)
    out.setdefault("git_sha", git_sha())
    wall = out.get("wall_time")
    if not isinstance(wall, (int, float)):
        wall = time.time()
    wall = max(float(wall), _last_wall + 1e-3) if _last_wall else float(wall)
    _last_wall = wall
    out["wall_time"] = round(wall, 3)
    return out


def emit_error_record(e: BaseException, **extra) -> None:
    """One parseable JSON error line (stdout, rc stays 0) with a platform
    stamp — the driver ingests this instead of a traceback.  ``extra``
    keys are merged into the record (e.g. the benchmark's knob values)."""
    import platform

    import jax

    print(json.dumps(stamp_record({
        "error": f"{type(e).__name__}: {e}",
        "metric": None,
        "jax_platforms": os.environ.get("JAX_PLATFORMS", ""),
        "jax_version": jax.__version__,
        "python": platform.python_version(),
    }, **extra)), flush=True)


def probe_backend(**extra) -> bool:
    """Check the accelerator backend comes up, retrying transient failures.

    Runs ``jax.devices()`` in a subprocess per attempt (see module
    docstring for why), retried under ``PROGEN_BENCH_RETRY_*``.  On
    definitive failure, emits the structured error record (merging
    ``extra``) and returns False — callers ``return`` without touching
    the backend.
    """
    import subprocess

    from progen_tpu.resilience.retry import (
        AttemptTimeout, RetryPolicy, retry_call,
    )

    policy = RetryPolicy.from_env("PROGEN_BENCH_RETRY")
    per_try = policy.attempt_timeout or 60.0
    # the subprocess enforces the per-attempt bound itself — don't stack
    # the thread-based attempt timeout on top
    policy = dataclasses.replace(policy, attempt_timeout=None)

    def probe():
        try:
            proc = subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                capture_output=True, text=True, timeout=per_try,
            )
        except subprocess.TimeoutExpired:
            raise AttemptTimeout(
                f"backend init exceeded {per_try:.0f}s") from None
        if proc.returncode != 0:
            tail = (proc.stderr or "").strip().splitlines()[-8:]
            raise RuntimeError("backend init failed: " + " | ".join(tail))

    try:
        retry_call(probe, policy=policy, label="backend-init")
        return True
    except Exception as e:  # RetryError or fatal init error: report, don't raise
        emit_error_record(e, **extra)
        return False
