"""Declarative SLOs evaluated as error-budget burn rates over registry
histograms — ONE code path for offline bench math and the live fleet.

An :class:`SLOSpec` states an objective ("95% of requests complete
within 2s", "99% of admitted requests are served, not shed").  Against a
registry snapshot it yields the achieved good fraction and the **burn
rate**: ``(1 - frac_good) / (1 - target)`` — the rate the error budget
is being spent at (1.0 = exactly on target, >1 = burning faster than
the objective allows, the standard SRE multi-window alert signal).

Latency objectives are evaluated from histogram bucket counts (the same
sparse buckets that ride heartbeat frames and merge fleet-wide), so the
live driver, a worker's own /statusz, and ``bench_serving.py --slo``
all agree bucket-for-bucket.  Ratio objectives divide two counters
(goodput vs shed).

:class:`BurnRateTracker` adds the *multi-window* part: it keeps a ring
of timed cumulative snapshots and evaluates each spec over trailing
windows by diffing cumulative counts (monotone, so diffs are exact),
publishing ``slo.<name>.burn_<w>s`` gauges for /metricsz and a JSON
block for /statusz.  Pure stdlib; never touches a device value.
"""

from __future__ import annotations

import bisect
import dataclasses
import math
from collections import deque

from progen_tpu.observe import metrics as _metrics

__all__ = [
    "SLOSpec",
    "BurnRateTracker",
    "burn_rate",
    "evaluate",
    "frac_within",
    "frac_within_values",
]


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One objective.

    ``kind="latency"``: ``frac_good`` is the fraction of ``metric``'s
    (histogram) observations at or under ``threshold_s``.
    ``kind="ratio"``: ``frac_good = good / (good + bad)`` over the two
    named counters (e.g. served vs shed — a goodput objective).
    ``target`` is the objective fraction in (0, 1)."""

    name: str
    target: float
    kind: str = "latency"
    metric: str = "cluster.latency_s"
    threshold_s: float = 1.0
    good: str = "cluster.completions_ok"
    bad: str = "cluster.completions_shed"

    def __post_init__(self):
        if self.kind not in ("latency", "ratio"):
            raise ValueError(f"kind {self.kind!r}: want 'latency' or 'ratio'")
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1), got {self.target}")


def _full_counts(snap, bounds):
    counts = [0] * (len(bounds) + 1)
    for i, c in snap.get("buckets", ()):
        counts[i] += c
    return counts


def frac_within(snap, threshold_s: float) -> float | None:
    """Fraction of a histogram snapshot's observations <= ``threshold_s``
    — the cumulative-bucket walk with linear interpolation inside the
    straddling bucket (the same estimate family as ``percentile``),
    clamped by the observed min/max when the snapshot carries them.
    ``None`` when the histogram is empty."""
    count = snap.get("count", 0)
    if not count:
        return None
    mn = snap.get("min")
    mx = snap.get("max")
    if mx is not None and threshold_s >= mx:
        return 1.0
    if mn is not None and threshold_s < mn:
        return 0.0
    bounds = _metrics.snapshot_bounds(snap)
    counts = _full_counts(snap, bounds)
    j = bisect.bisect_left(bounds, threshold_s)
    within = sum(counts[:j])
    if j < len(counts) and counts[j]:
        lo = bounds[j - 1] if j > 0 else min(
            mn if mn is not None else 0.0, 0.0)
        hi = bounds[j] if j < len(bounds) else (
            mx if mx is not None else threshold_s)
        if hi > lo:
            within += counts[j] * min(1.0, (threshold_s - lo) / (hi - lo))
        else:
            within += counts[j]
    return min(1.0, within / count)


def frac_within_values(values, threshold_s: float,
                       name: str = "slo.eval_latency_s") -> float:
    """Offline form: rate raw latencies through a registry histogram and
    evaluate THAT — so a bench's ``within_slo_frac`` goes through the
    identical bucket math as the live fleet's burn rates."""
    h = _metrics.get_registry().histogram(name)
    h.reset()
    for v in values:
        h.observe(v)
    out = frac_within(h.snapshot(), threshold_s)
    return 1.0 if out is None else out


def burn_rate(frac_good: float | None, target: float) -> float | None:
    """Error-budget burn: ``(1 - frac_good) / (1 - target)``.  None in =
    None out (no data is not a burning budget)."""
    if frac_good is None:
        return None
    bad = max(0.0, 1.0 - frac_good)
    budget = 1.0 - target
    if budget <= 0.0:
        return math.inf if bad > 0 else 0.0
    return bad / budget


def evaluate(spec: SLOSpec, snapshot: dict) -> dict:
    """One spec against one registry snapshot -> JSON-safe result."""
    if spec.kind == "latency":
        snap = snapshot.get(spec.metric, {})
        frac = frac_within(snap, spec.threshold_s)
        count = snap.get("count", 0)
    else:
        good = snapshot.get(spec.good, {}).get("value", 0)
        bad = snapshot.get(spec.bad, {}).get("value", 0)
        count = good + bad
        frac = (good / count) if count else None
    rate = burn_rate(frac, spec.target)
    return {
        "name": spec.name,
        "kind": spec.kind,
        "target": spec.target,
        "count": count,
        "frac_good": None if frac is None else round(frac, 6),
        "burn_rate": None if rate is None else (
            round(rate, 4) if rate != math.inf else "inf"),
    }


def _diff_metric(new: dict, old: dict | None) -> dict:
    """Windowed view of a cumulative metric: new minus old.  Counts are
    monotone so the diff is exact; a window diff has no meaningful
    min/max (raw values are gone), so those fields are dropped and
    ``frac_within`` falls back to pure bucket math."""
    if old is None:
        return new
    if new.get("type") == "counter":
        return {"type": "counter",
                "value": max(0, new.get("value", 0) - old.get("value", 0))}
    if new.get("type") != "histogram":
        return new
    bounds = _metrics.snapshot_bounds(new)
    counts = _full_counts(new, bounds)
    for i, c in old.get("buckets", ()):
        counts[i] -= c
    counts = [max(0, c) for c in counts]
    out = {"type": "histogram",
           "count": max(0, new.get("count", 0) - old.get("count", 0)),
           "sum": new.get("sum", 0.0) - old.get("sum", 0.0),
           "buckets": [[i, c] for i, c in enumerate(counts) if c]}
    if "bounds" in new:
        out["bounds"] = new["bounds"]
    return out


class BurnRateTracker:
    """Multi-window burn rates over a ring of timed registry snapshots.

    Call :meth:`sample` with a monotonic ``now`` and the current
    (cumulative) snapshot — on the driver that is the fleet-merged view,
    in a worker its own registry.  :meth:`evaluate` computes every spec
    over every trailing window by diffing the newest sample against the
    oldest sample inside the window, publishes ``slo.*`` gauges into the
    registry, and returns the JSON block /statusz embeds."""

    def __init__(self, specs, *, windows=(60.0, 300.0, 900.0),
                 registry=None):
        self.specs = tuple(specs)
        self.windows = tuple(sorted(windows))
        self._registry = registry
        self._samples: deque = deque()

    def sample(self, now: float, snapshot: dict) -> None:
        self._samples.append((now, snapshot))
        horizon = now - (self.windows[-1] if self.windows else 0.0) - 1.0
        while len(self._samples) > 2 and self._samples[1][0] < horizon:
            self._samples.popleft()

    def evaluate(self, now: float | None = None) -> list[dict]:
        if not self._samples:
            return [evaluate(s, {}) | {"windows": {}} for s in self.specs]
        t_new, newest = self._samples[-1]
        if now is None:
            now = t_new
        registry = self._registry or _metrics.get_registry()
        out = []
        for spec in self.specs:
            res = evaluate(spec, newest)  # lifetime view
            res["windows"] = {}
            for w in self.windows:
                old = None
                t_old = None
                for t, snap in self._samples:
                    if t >= now - w:
                        break
                    old, t_old = snap, t
                names = ([spec.metric] if spec.kind == "latency"
                         else [spec.good, spec.bad])
                windowed = {n: _diff_metric(newest.get(n, {}),
                                            None if old is None
                                            else old.get(n))
                            for n in names}
                wres = evaluate(spec, windowed)
                span = round(now - (t_old if t_old is not None
                                    else self._samples[0][0]), 3)
                res["windows"][f"{w:g}s"] = {
                    "span_s": span,
                    "count": wres["count"],
                    "frac_good": wres["frac_good"],
                    "burn_rate": wres["burn_rate"],
                }
                rate = wres["burn_rate"]
                g = registry.gauge(f"slo.{spec.name}.burn_{w:g}s")
                g.set(-1.0 if rate is None
                      else (math.inf if rate == "inf" else rate))
            frac = res["frac_good"]
            registry.gauge(f"slo.{spec.name}.frac_good").set(
                -1.0 if frac is None else frac)
            out.append(res)
        return out
