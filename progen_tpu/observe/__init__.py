from progen_tpu.observe.meter import ThroughputMeter, profile_trace
from progen_tpu.observe.tracker import Tracker

__all__ = ["ThroughputMeter", "profile_trace", "Tracker"]
