from progen_tpu.observe.flops import (
    PEAK_BF16_TFLOPS,
    mfu,
    model_flops_per_token,
    peak_flops_per_chip,
)
from progen_tpu.observe.gitinfo import git_sha
from progen_tpu.observe.meter import ThroughputMeter, profile_trace
from progen_tpu.observe.metrics import (
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    labeled,
    latency_buckets,
    latency_percentiles,
    merge_snapshots,
    split_labeled,
)
from progen_tpu.observe.platform import emit_error_record, probe_backend
from progen_tpu.observe.robustness import RobustnessCounters
from progen_tpu.observe.slo import BurnRateTracker, SLOSpec
from progen_tpu.observe.statusz import StatuszServer, render_prometheus
from progen_tpu.observe.trace import (
    Tracer,
    chrome_trace,
    configure_tracing,
    get_tracer,
    merge_trace_dir,
    spans_for,
    trace_dump_path,
)
from progen_tpu.observe.tracker import Tracker

__all__ = [
    "PEAK_BF16_TFLOPS",
    "RobustnessCounters",
    "emit_error_record",
    "git_sha",
    "probe_backend",
    "mfu",
    "model_flops_per_token",
    "peak_flops_per_chip",
    "ThroughputMeter",
    "profile_trace",
    "Tracker",
    # tracing (observe.trace)
    "Tracer",
    "chrome_trace",
    "configure_tracing",
    "get_tracer",
    "merge_trace_dir",
    "spans_for",
    "trace_dump_path",
    # metrics (observe.metrics)
    "LATENCY_BUCKETS",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "labeled",
    "latency_buckets",
    "latency_percentiles",
    "merge_snapshots",
    "split_labeled",
    # live introspection plane (observe.statusz / observe.slo)
    "StatuszServer",
    "render_prometheus",
    "SLOSpec",
    "BurnRateTracker",
]
