"""Robustness counters for the serving tier.

One small host-side dataclass shared by the engine, the benchmarks and
the tests: every shed, contained fault and kernel-fallback activation is
counted HERE, so a chaos run's record (``benchmarks/chaos.jsonl``) and a
test's assertions read the same numbers the engine acted on.  Counters
are plain ints mutated between device dispatches — no locks needed, the
engine is single-threaded by construction.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class RobustnessCounters:
    """Serving-engine robustness tallies.

    ``sheds_queue_full``/``sheds_deadline``: requests turned away as
    typed completions (never raised).  ``failed_faults``: requests shed
    because a non-transient fault fired on their path.
    ``faults_contained``: transient faults absorbed by an in-place retry
    of the failed phase.  ``fallback_activations``: Pallas paged-kernel
    failures degraded to the bit-identical XLA path.  ``preemptions``:
    in-flight requests cancelled for a higher priority class and
    re-queued for bit-exact replay (docs/SERVING.md §10).
    """

    sheds_queue_full: int = 0
    sheds_deadline: int = 0
    failed_faults: int = 0
    faults_contained: int = 0
    fallback_activations: int = 0
    preemptions: int = 0

    def as_dict(self) -> dict[str, int]:
        return dataclasses.asdict(self)

    @property
    def sheds(self) -> int:
        return (self.sheds_queue_full + self.sheds_deadline
                + self.failed_faults)
