"""Transport counters for the multi-process serving runtime.

Every process in the serving topology (router, prefill workers, decode
replicas — docs/SERVING.md §7) keeps one :class:`TransportCounters` per
socket direction pair and ships a snapshot home in its final ``stats``
message, so bench records can report frames/bytes/serialization seconds
per stage without a second instrumentation layer.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass
class TransportCounters:
    """Host-side tallies of the handle/message transport.

    ``ser_s``/``de_s`` are wall seconds spent inside
    ``serialize_handle``/``deserialize_handle`` (device_get / device_put
    included — the transport thread is ALLOWED to sync; the admission
    path is not).  ``crc_failures`` counts frames whose payload checksum
    failed but whose header survived (targeted replay); ``desyncs``
    counts unrecoverable stream errors (bad magic, mid-frame EOF) that
    poison the connection.
    """

    frames_out: int = 0
    frames_in: int = 0
    bytes_out: int = 0
    bytes_in: int = 0
    ser_s: float = 0.0
    de_s: float = 0.0
    crc_failures: int = 0
    desyncs: int = 0

    def sent(self, nbytes: int) -> None:
        self.frames_out += 1
        self.bytes_out += nbytes

    def received(self, nbytes: int) -> None:
        self.frames_in += 1
        self.bytes_in += nbytes

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def merge(self, other: "TransportCounters | dict") -> None:
        """Fold another process's snapshot into this one (aggregation in
        the router when building the bench record)."""
        d = other.as_dict() if isinstance(other, TransportCounters) else other
        self.frames_out += int(d.get("frames_out", 0))
        self.frames_in += int(d.get("frames_in", 0))
        self.bytes_out += int(d.get("bytes_out", 0))
        self.bytes_in += int(d.get("bytes_in", 0))
        self.ser_s += float(d.get("ser_s", 0.0))
        self.de_s += float(d.get("de_s", 0.0))
        self.crc_failures += int(d.get("crc_failures", 0))
        self.desyncs += int(d.get("desyncs", 0))
