"""Live per-process introspection plane: /healthz /statusz /metricsz
/tracez /flightz on a loopback port.

Every serving/training process (driver, prefill worker, decode replica,
trainer) can run one :class:`StatuszServer` — a stdlib ``http.server``
on ``127.0.0.1``, served from a daemon thread, constructed ONLY when the
operator asks for it (``--statusz``), so the disabled path costs
nothing: no socket, no thread, no import-time work beyond this module.

The hard invariant is zero perturbation: an enabled run is
token-identical to a disabled one.  That holds because every handler
reads host-side bookkeeping only — engine ``status()`` (host dicts),
registry snapshots (host floats), the tracer ring, flight-recorder
events.  Nothing here may ever call ``jax.device_get`` or touch a device
array (``ServingEngine.spec_counters`` is deliberately NOT surfaced: it
costs a device fetch).  Handlers run on the HTTP thread concurrently
with the serving loop; they read via provider callables and a racy read
of a mutating dict is answered with a 503 the client retries, never a
crash and never a lock the hot path could contend on.

Endpoints:

- ``/healthz``  — JSON liveness: role/index plus whatever the host
  process's ``health`` provider reports (heartbeat ages, credit window,
  restart budget, build phase).
- ``/statusz``  — JSON deep state from the ``status`` provider (engine
  slots/queues/in-flight uids/robustness counters/stage seconds; on the
  driver: the fleet-wide view with merged histograms).
- ``/metricsz`` — Prometheus text exposition (counters, gauges,
  cumulative histogram buckets ending in ``+Inf``) rendered from the
  ``metrics`` provider's registry snapshot.
- ``/tracez``   — recent span ring (JSON), ``/flightz`` — flight
  recorder events (JSON).
- ``/controlz`` — elastic control-plane journal (JSON): every
  scale/swap/retire decision with its cause signal, plus policy config
  and live fleet state.  Served only when a control plane registered
  its ``control`` provider (``serve/control.py``); 404 otherwise.
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from progen_tpu.observe import metrics as _metrics

__all__ = ["StatuszServer", "render_prometheus"]

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Metric name -> valid Prometheus name (dots and dashes become
    underscores; a leading digit gets a prefix)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt(v) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _sample(base: str, labels: str, extra: str, value) -> str:
    inner = ",".join(p for p in (labels, extra) if p)
    lab = "{" + inner + "}" if inner else ""
    return f"{base}{lab} {_fmt(value)}"


def render_prometheus(snapshot: dict) -> str:
    """Registry snapshot (possibly fleet-merged) -> Prometheus text
    exposition.  Labeled registry names (``metrics.labeled``) become real
    label sets; histograms emit cumulative ``_bucket`` series ending in
    the ``+Inf`` terminal bucket plus ``_sum``/``_count``."""
    lines = []
    typed: dict[str, str] = {}
    for name in sorted(snapshot):
        m = snapshot[name]
        raw_base, labels = _metrics.split_labeled(name)
        base = _prom_name(raw_base)
        mtype = m.get("type", "gauge")
        prev = typed.get(base)
        if prev is None:
            typed[base] = mtype
            lines.append(f"# TYPE {base} "
                         f"{'histogram' if mtype == 'histogram' else mtype}")
        elif prev != mtype:
            raise ValueError(
                f"metric family {base!r} mixes types {prev} and {mtype}")
        if mtype in ("counter", "gauge"):
            lines.append(_sample(base, labels, "", m.get("value", 0)))
            continue
        bounds = _metrics.snapshot_bounds(m)
        counts = [0] * (len(bounds) + 1)
        for i, c in m.get("buckets", ()):
            counts[i] += c
        cum = 0
        for i, bound in enumerate(bounds):
            cum += counts[i]
            lines.append(_sample(f"{base}_bucket", labels,
                                 f'le="{bound:.6g}"', cum))
        lines.append(_sample(f"{base}_bucket", labels, 'le="+Inf"',
                             m.get("count", 0)))
        lines.append(_sample(f"{base}_sum", labels, "", m.get("sum", 0.0)))
        lines.append(_sample(f"{base}_count", labels, "", m.get("count", 0)))
    return "\n".join(lines) + "\n"


class StatuszServer:
    """One loopback debug server per process.

    ``providers`` maps endpoint roles to zero-argument callables returning
    JSON-safe host data:

    - ``health``  -> dict merged into the /healthz body
    - ``status``  -> dict for /statusz
    - ``metrics`` -> registry snapshot for /metricsz (default: this
      process's ``get_registry().snapshot()``)
    - ``tracer``  -> the Tracer whose ring /tracez serves (default: the
      process tracer)
    - ``flight``  -> list of flight-recorder events for /flightz

    Call :meth:`start` to bind (port 0 = ephemeral; the bound port is in
    ``self.port``) and :meth:`stop` to shut down.  The serve thread and
    the per-request handler threads are daemons: a hung scrape can never
    block process exit."""

    def __init__(self, *, role: str, index: int | None = None,
                 port: int = 0, providers: dict | None = None):
        self.role = role
        self.index = index
        # held by REFERENCE: the owner may register providers after
        # start() (the serving control plane adds "control" when it
        # attaches to a running cluster)
        self.providers = providers if providers is not None else {}
        self._want_port = port
        self.port: int | None = None
        self._httpd = None
        self._thread = None

    # ------------------------------------------------------------- lifecycle

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silent: stderr is the worker log
                pass

            def do_GET(self):
                try:
                    body, ctype = server._render(self.path)
                except KeyError:
                    self._reply(404, b"not found\n", "text/plain")
                    return
                except Exception as e:  # racy host-dict read: retryable
                    self._reply(503, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}
                    ).encode() + b"\n", "application/json")
                    return
                self._reply(200, body, ctype)

            def _reply(self, code, body, ctype):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self._httpd = ThreadingHTTPServer(("127.0.0.1", self._want_port),
                                          Handler)
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name=f"statusz-{self.role}")
        self._thread.start()
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    # ------------------------------------------------------------- endpoints

    def _call(self, key, default):
        fn = self.providers.get(key)
        return fn() if fn is not None else default

    def _render(self, path: str) -> tuple[bytes, str]:
        path = path.split("?", 1)[0].rstrip("/") or "/healthz"
        if path == "/healthz":
            body = {"status": "ok", "role": self.role}
            if self.index is not None:
                body["index"] = self.index
            body.update(self._call("health", {}))
            return self._json(body)
        if path == "/statusz":
            return self._json(self._call("status", {}))
        if path == "/metricsz":
            fn = self.providers.get("metrics")
            snap = fn() if fn is not None else (
                _metrics.get_registry().snapshot())
            return (render_prometheus(snap).encode(),
                    "text/plain; version=0.0.4; charset=utf-8")
        if path == "/tracez":
            tracer = self.providers.get("tracer")
            if tracer is None:
                from progen_tpu.observe.trace import get_tracer
                tracer = get_tracer()
            return self._json({"process": tracer.process,
                               "enabled": tracer.enabled,
                               "spans": tracer.ring()[-512:]})
        if path == "/flightz":
            return self._json({"events": self._call("flight", [])})
        if path == "/controlz":
            # elastic control plane: journal of scale/swap/retire
            # decisions + policy config + live fleet (serve/control.py);
            # 404 when no control plane is attached
            if "control" not in self.providers:
                raise KeyError(path)
            return self._json(self._call("control", {}))
        raise KeyError(path)

    @staticmethod
    def _json(obj) -> tuple[bytes, str]:
        return (json.dumps(obj, indent=1, sort_keys=True).encode() + b"\n",
                "application/json")
