"""Typed metrics registry: Counter / Gauge / Histogram with fixed
log-spaced latency buckets.

One registry per process (``get_registry()``).  Serving workers publish
``registry.snapshot()`` on every heartbeat frame and again in their final
stats flush, so ``ServeCluster.stats()`` reports live numbers from every
process; ``bench_serving.py`` computes its p50/p95 fields through the same
``Histogram`` code path (``latency_percentiles``) instead of a private
``np.percentile`` call.

Pure stdlib (bisect + math): safe to import from anywhere, including the
engine hot path and the stdlib-only watchdog."""

import bisect
import math

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "get_registry",
    "labeled",
    "latency_buckets",
    "LATENCY_BUCKETS",
    "latency_percentiles",
    "merge_snapshots",
    "percentile_from_counts",
    "split_labeled",
]


def latency_buckets(lo=1e-4, hi=100.0, n=64):
    """Fixed log-spaced bucket upper bounds: ``n`` bounds from ``lo`` to
    ``hi`` seconds with constant ratio, so relative quantile error is
    bounded by one bucket ratio (~24% at the defaults) at every scale from
    100 us to 100 s."""
    ratio = (hi / lo) ** (1.0 / (n - 1))
    return tuple(lo * ratio ** i for i in range(n))


LATENCY_BUCKETS = latency_buckets()


def percentile_from_counts(bounds, counts, count, mn, mx, p):
    """The one quantile walk: cumulative counts with linear interpolation
    inside the target bucket, clamped to the observed min/max.  Shared by
    live :class:`Histogram` objects and merged fleet snapshots (which only
    have bucket counts, not raw values)."""
    if count == 0:
        return None
    rank = (p / 100.0) * count
    cum = 0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        nxt = cum + c
        if nxt >= rank:
            lo = bounds[i - 1] if i > 0 else min(mn, 0.0)
            hi = bounds[i] if i < len(bounds) else mx
            frac = (rank - cum) / c
            est = lo + (hi - lo) * frac
            return min(max(est, mn), mx)
        cum = nxt
    return mx


class Counter:
    """Monotonic count."""

    __slots__ = ("name", "_value")

    def __init__(self, name):
        self.name = name
        self._value = 0

    def inc(self, n=1):
        self._value += n

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return {"type": "counter", "value": self._value}


class Gauge:
    """Last-set value (e.g. queue depth, tokens/sec)."""

    __slots__ = ("name", "_value")

    def __init__(self, name):
        self.name = name
        self._value = 0.0

    def set(self, v):
        self._value = v

    @property
    def value(self):
        return self._value

    def snapshot(self):
        return {"type": "gauge", "value": self._value}


class Histogram:
    """Fixed-bucket histogram with quantile estimation.

    ``observe`` is a bisect into the (log-spaced) bounds; ``percentile``
    walks the cumulative counts and linearly interpolates inside the
    target bucket, clamped to the observed min/max so exact extremes are
    never overshot."""

    __slots__ = ("name", "bounds", "counts", "count", "sum", "min", "max")

    def __init__(self, name, buckets=LATENCY_BUCKETS):
        self.name = name
        self.bounds = tuple(buckets)
        self.reset()

    def reset(self):
        # one overflow bucket past the last bound
        self.counts = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v):
        # callers pass host floats by contract (the zone enforces it)
        v = float(v)  # graftcheck: disable=host-sync
        self.counts[bisect.bisect_left(self.bounds, v)] += 1
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v

    @property
    def mean(self):
        return self.sum / self.count if self.count else None

    def percentile(self, p):
        """Estimate the p-th percentile (p in [0, 100])."""
        return percentile_from_counts(self.bounds, self.counts, self.count,
                                      self.min, self.max, p)

    def snapshot(self):
        snap = {"type": "histogram", "count": self.count,
                "sum": round(self.sum, 6)}
        if self.count:
            snap["min"] = self.min
            snap["max"] = self.max
            snap["p50"] = self.percentile(50)
            snap["p95"] = self.percentile(95)
            snap["p99"] = self.percentile(99)
            # sparse bucket counts ([index, count] pairs, JSON-safe) so
            # fleet merges and Prometheus exposition can reconstruct the
            # full distribution from a heartbeat snapshot
            snap["buckets"] = [[i, c] for i, c in enumerate(self.counts)
                               if c]
        if self.bounds != LATENCY_BUCKETS:
            snap["bounds"] = list(self.bounds)
        return snap


class MetricsRegistry:
    """Get-or-create registry keyed by metric name.

    Re-requesting a name returns the same object; re-requesting it as a
    different type is a bug and raises."""

    def __init__(self):
        self._metrics = {}

    def _get(self, cls, name, *args):
        m = self._metrics.get(name)
        if m is None:
            m = cls(name, *args)
            self._metrics[name] = m
        elif not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as "
                f"{type(m).__name__}, requested {cls.__name__}")
        return m

    def counter(self, name):
        return self._get(Counter, name)

    def gauge(self, name):
        return self._get(Gauge, name)

    def histogram(self, name, buckets=LATENCY_BUCKETS):
        return self._get(Histogram, name, buckets)

    def snapshot(self):
        return {name: m.snapshot() for name, m in
                sorted(self._metrics.items())}

    def clear(self):
        self._metrics.clear()


_REGISTRY = MetricsRegistry()


def get_registry():
    """The process-wide metrics registry."""
    return _REGISTRY


def labeled(name, **labels):
    """Embed Prometheus-style labels in a metric name:
    ``labeled("cluster.up", role="prefill", idx=0)`` ->
    ``cluster.up{idx="0",role="prefill"}``.  Labels are sorted so the same
    label set always produces the same registry key; values are escaped at
    construction so :func:`split_labeled` and the exposition renderer can
    pass them through verbatim."""
    inner = ",".join(
        '{}="{}"'.format(k, str(v).replace("\\", r"\\")
                         .replace('"', r'\"').replace("\n", r"\n"))
        for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}" if inner else name


def split_labeled(name):
    """``name{a="b"}`` -> ``("name", 'a="b"')``; unlabeled -> ``(name, "")``."""
    i = name.find("{")
    if i < 0:
        return name, ""
    return name[:i], name[i + 1:].rstrip("}")


def snapshot_bounds(snap):
    """Bucket upper bounds a histogram snapshot was taken against (custom
    bounds ride the snapshot; the default set is implied)."""
    return tuple(snap.get("bounds", LATENCY_BUCKETS))


def _merge_histogram(out, snap):
    bounds = snapshot_bounds(snap)
    if snapshot_bounds(out) != bounds:
        raise ValueError(
            f"cannot merge histogram snapshots with different bounds "
            f"({len(snapshot_bounds(out))} vs {len(bounds)} buckets)")
    counts = [0] * (len(bounds) + 1)
    for i, c in out.get("buckets", ()):
        counts[i] += c
    for i, c in snap.get("buckets", ()):
        counts[i] += c
    out["count"] = out.get("count", 0) + snap.get("count", 0)
    out["sum"] = round(out.get("sum", 0.0) + snap.get("sum", 0.0), 6)
    if out["count"]:
        out["min"] = min(out.get("min", math.inf),
                         snap.get("min", math.inf))
        out["max"] = max(out.get("max", -math.inf),
                         snap.get("max", -math.inf))
        out["buckets"] = [[i, c] for i, c in enumerate(counts) if c]
        for p, key in ((50, "p50"), (95, "p95"), (99, "p99")):
            out[key] = percentile_from_counts(
                bounds, counts, out["count"], out["min"], out["max"], p)
    return out


def merge_snapshots(snaps):
    """Merge registry snapshots from several processes into one fleet
    view: counters and gauges sum (fleet totals — per-process values that
    must stay distinct use :func:`labeled` names, which never collide),
    histograms merge bucket-for-bucket with percentiles recomputed from
    the merged counts.  Type conflicts across processes raise."""
    out = {}
    for snap in snaps:
        for name, m in snap.items():
            cur = out.get(name)
            if cur is None:
                out[name] = dict(m)
                continue
            if cur.get("type") != m.get("type"):
                raise ValueError(
                    f"metric {name!r} is {cur.get('type')} in one process "
                    f"and {m.get('type')} in another")
            if m.get("type") == "histogram":
                _merge_histogram(cur, m)
            else:
                cur["value"] = cur.get("value", 0) + m.get("value", 0)
    return {name: out[name] for name in sorted(out)}


def latency_percentiles(values, ps=(50.0, 95.0), name="bench.latency_s"):
    """Percentiles of ``values`` via the shared registry histogram — the
    single latency-quantile code path for benches and the cluster.  Resets
    the named histogram first so each call rates exactly its inputs."""
    h = get_registry().histogram(name)
    h.reset()
    for v in values:
        h.observe(v)
    return tuple(h.percentile(p) for p in ps)
