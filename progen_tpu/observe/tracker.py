"""Experiment tracking: offline-first JSONL with optional wandb mirroring.

The reference logs scalars and HTML-rendered samples to wandb only
(``/root/reference/train.py:143-152,199,217,228``) and supports
resume-by-run-id from the checkpoint.  TPU pods often run with no egress,
so here the primary sink is a local (or GCS-staged) JSONL stream that
always works; wandb is mirrored to when the package is importable and not
disabled.  The run-id resume contract is preserved (the id round-trips
through the checkpoint metadata).

Only process 0 of a multi-host job writes (the reference is single-process
and has no such concern).
"""

from __future__ import annotations

import json
import time
import uuid
from pathlib import Path
from typing import Any

import jax


def _wandb_or_none():
    try:
        import wandb  # type: ignore

        return wandb
    except Exception:
        return None


class Tracker:
    def __init__(
        self,
        project: str = "progen-tpu",
        out_dir: str = "./runs",
        run_id: str | None = None,
        disabled: bool = False,
        use_wandb: bool = True,
        config: dict[str, Any] | None = None,
    ):
        self.disabled = disabled or jax.process_index() != 0
        self.run_id = run_id or uuid.uuid4().hex[:12]
        self._wandb_run = None
        self._file = None
        if self.disabled:
            return

        self._dir = Path(out_dir) / self.run_id
        self._dir.mkdir(parents=True, exist_ok=True)
        self._file = open(self._dir / "metrics.jsonl", "a", buffering=1)
        if config:
            (self._dir / "config.json").write_text(json.dumps(config, indent=2))

        wandb = _wandb_or_none() if use_wandb else None
        if wandb is not None:
            kwargs = {"project": project, "config": config or {}}
            if run_id is not None:
                kwargs.update(id=run_id, resume="allow")
            try:
                self._wandb_run = wandb.init(**kwargs)
            except Exception:
                self._wandb_run = None

    def log(self, metrics: dict[str, Any], step: int) -> None:
        if self.disabled:
            return
        row = {"step": int(step), "time": time.time()}
        row.update({k: float(v) for k, v in metrics.items()})
        self._file.write(json.dumps(row) + "\n")
        if self._wandb_run is not None:
            self._wandb_run.log(metrics, step=step)

    def log_sample(self, prime: str, sampled: str, step: int) -> None:
        """Generation samples: HTML fragment file (the reference's Jinja2
        template, ``train.py:28``, reduced to an f-string) + wandb.Html."""
        if self.disabled:
            return
        html = (
            f"<i>{prime}</i><br/><br/>"
            f'<div style="overflow-wrap: break-word;">{sampled}</div>'
        )
        with open(self._dir / "samples.html", "a") as f:
            f.write(f"<h4>step {step}</h4>{html}\n")
        if self._wandb_run is not None:
            import wandb  # type: ignore

            self._wandb_run.log({"samples": wandb.Html(html)}, step=step)

    def finish(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._wandb_run is not None:
            self._wandb_run.finish()
            self._wandb_run = None
