"""Throughput metering + profiler hooks.

The reference has no profiling or throughput reporting (SURVEY.md §5.1);
BASELINE.md's metric is Uniref50 tokens/sec/chip, so the meter is a
first-class subsystem here.  ``jax.profiler`` traces can be toggled around
any step window for xprof/tensorboard analysis.
"""

from __future__ import annotations

import contextlib
import time
from collections import deque

import jax


class ThroughputMeter:
    """Tokens/sec (global and per-chip) over a sliding window of SYNC
    points.

    Call ``tick(tokens)`` only at host-sync boundaries (after blocking on a
    fetched metric), passing the number of tokens processed SINCE THE
    PREVIOUS TICK.  Ticking per async-dispatched step times the enqueue,
    not the execution — observed 1.1M "tokens/sec" on a tunneled TPU that
    really does 78k.
    """

    def __init__(self, window: int = 50):
        self._window = window
        self._anchor: float | None = None
        # (duration, tokens, steps) per sync interval — durations are
        # stored, not absolute times, so rebase() can cut hook time out of
        # the middle of the window; the deque's maxlen IS the window
        self._intervals: deque[tuple[float, int, int]] = deque(maxlen=window)

    def tick(self, tokens: int, steps: int = 0) -> None:
        """Close the current interval: ``tokens`` (and optionally ``steps``
        — optimizer steps, for the superstep loop where one sync covers K
        of them) processed since the previous tick."""
        now = time.perf_counter()
        if self._anchor is not None:
            self._intervals.append((now - self._anchor, tokens, steps))
        # the first-ever tick only opens the clock: its tokens include
        # compile time and are never rated
        self._anchor = now

    def rebase(self) -> None:
        """Restart the current interval's clock, excluding the time since
        the last tick.  Call after non-training work (validation, sampling,
        checkpoint writes): the meter reports TRAIN-step throughput — the
        BASELINE.md metric — not wall-clock including hooks."""
        self._anchor = time.perf_counter()

    @property
    def tokens_per_sec(self) -> float | None:
        if not self._intervals:
            return None
        dt = sum(d for d, _, _ in self._intervals)
        toks = sum(t for _, t, _ in self._intervals)
        return toks / dt if dt > 0 else None

    @property
    def steps_per_sec(self) -> float | None:
        """Optimizer steps/sec over the window; None until a tick has
        carried a step count (the per-step loop rates tokens only)."""
        if not self._intervals:
            return None
        dt = sum(d for d, _, _ in self._intervals)
        steps = sum(s for _, _, s in self._intervals)
        if dt <= 0 or steps == 0:
            return None
        return steps / dt

    @property
    def tokens_per_sec_per_chip(self) -> float | None:
        tps = self.tokens_per_sec
        return None if tps is None else tps / jax.device_count()

    def snapshot(self) -> dict:
        """Flat dict of the current rates, for publishing into the metrics
        registry (``observe.metrics``) or a log record."""
        return {
            "tokens_per_sec": self.tokens_per_sec,
            "steps_per_sec": self.steps_per_sec,
            "tokens_per_sec_per_chip": self.tokens_per_sec_per_chip,
            "window": self._window,
            "intervals": len(self._intervals),
        }

    def publish(self, registry) -> None:
        """Set ``meter.*`` gauges on a ``MetricsRegistry`` from the current
        snapshot (None rates are skipped, not zeroed)."""
        for key, val in self.snapshot().items():
            if val is not None:
                registry.gauge(f"meter.{key}").set(val)


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """``with profile_trace('/tmp/trace'):`` records an xprof trace of the
    enclosed steps; no-op when logdir is None."""
    if logdir is None:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
