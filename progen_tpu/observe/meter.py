"""Throughput metering + profiler hooks.

The reference has no profiling or throughput reporting (SURVEY.md §5.1);
BASELINE.md's metric is Uniref50 tokens/sec/chip, so the meter is a
first-class subsystem here.  ``jax.profiler`` traces can be toggled around
any step window for xprof/tensorboard analysis.
"""

from __future__ import annotations

import contextlib
import time

import jax


class ThroughputMeter:
    """Tokens/sec (global and per-chip) over a sliding window of steps.

    Call ``tick(tokens)`` once per optimizer step AFTER the step's result is
    known to be materialized (the trainer blocks on the loss periodically —
    async dispatch otherwise makes per-step walltime meaningless).
    """

    def __init__(self, window: int = 50):
        self._window = window
        self._times: list[float] = []
        self._tokens: list[int] = []

    def tick(self, tokens: int) -> None:
        self._times.append(time.perf_counter())
        self._tokens.append(tokens)
        if len(self._times) > self._window + 1:
            self._times.pop(0)
            self._tokens.pop(0)

    @property
    def tokens_per_sec(self) -> float | None:
        if len(self._times) < 2:
            return None
        dt = self._times[-1] - self._times[0]
        toks = sum(self._tokens[1:])  # tokens of steps 1..n (intervals)
        return toks / dt if dt > 0 else None

    @property
    def tokens_per_sec_per_chip(self) -> float | None:
        tps = self.tokens_per_sec
        return None if tps is None else tps / jax.device_count()


@contextlib.contextmanager
def profile_trace(logdir: str | None):
    """``with profile_trace('/tmp/trace'):`` records an xprof trace of the
    enclosed steps; no-op when logdir is None."""
    if logdir is None:
        yield
        return
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
