"""Cross-process request tracing: monotonic-clock spans with per-request
trace ids, a bounded ring buffer per process, and a zero-cost no-op path
when disabled.

A request's life spans three OS processes (driver/router -> prefill worker
-> decode replica).  Each process records spans into its own bounded ring
(`Tracer`), stamped with ``time.perf_counter()`` instants.  Workers echo
their own clock in hello/heartbeat frames so the driver can estimate a
per-process clock offset (driver_now - worker_clock, minimised over
samples); ``merge_dumps`` applies those offsets to place every process's
spans on the driver's timeline, and ``chrome_trace`` emits a single
Perfetto / chrome://tracing ``trace_event`` JSON.

Trace ids are the request uids: a span either carries ``trace=<uid>``
(per-request work) or ``uids=[...]`` in its args (batch-level work such as
a prefill round).  ``spans_for`` finds both.

Disabled (the default) costs one attribute check per call: ``span()``
returns a shared no-op context manager and ``add()``/``event()`` return
before allocating the record.  This file is deliberately pure stdlib —
``resilience/watchdog.py`` dumps the ring on a trip and must not pull in
jax to do it.
"""

import json
import os
import time
from collections import deque

__all__ = [
    "Tracer",
    "get_tracer",
    "configure_tracing",
    "trace_dump_path",
    "load_dump",
    "merge_dumps",
    "chrome_trace",
    "write_chrome_trace",
    "merge_trace_dir",
    "spans_for",
]

DEFAULT_CAPACITY = 4096


class _NoopSpan:
    """Shared do-nothing context manager returned by ``Tracer.span`` when
    tracing is disabled, so the hot path allocates nothing."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NOOP_SPAN = _NoopSpan()


class _Span:
    """Live timing context: stamps perf_counter on enter, records on exit."""

    __slots__ = ("_tracer", "_name", "_trace", "_args", "_t0")

    def __init__(self, tracer, name, trace, args):
        self._tracer = tracer
        self._name = name
        self._trace = trace
        self._args = args

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dur = time.perf_counter() - self._t0
        self._tracer.add(self._name, self._t0, dur, trace=self._trace,
                         **self._args)
        return False


class Tracer:
    """Per-process span recorder: a bounded ring of completed spans.

    Spans are plain dicts ``{"name", "ts", "dur", "trace"?, "args"?}`` with
    ``ts``/``dur`` in perf_counter seconds.  The ring is a
    ``deque(maxlen=capacity)`` so a long-lived server can trace forever and
    keep only the recent window — exactly what a watchdog trip wants."""

    def __init__(self, *, enabled=False, capacity=DEFAULT_CAPACITY,
                 process="main"):
        self.enabled = enabled
        self.capacity = capacity
        self.process = process
        self._ring = deque(maxlen=capacity)
        self._meta = {}

    # -- recording ---------------------------------------------------------

    def span(self, name, trace=None, **args):
        """Context manager timing a block; no-op singleton when disabled."""
        if not self.enabled:
            return _NOOP_SPAN
        return _Span(self, name, trace, args)

    def add(self, name, t0, dur, trace=None, **args):
        """Record an already-timed span (t0 from ``time.perf_counter()``).

        This is the form used at the engine's existing stage-timing sites:
        the ``t0 = time.perf_counter()`` deltas that feed ``stage_seconds``
        become spans for free."""
        if not self.enabled:
            return
        rec = {"name": name, "ts": t0, "dur": dur}
        if trace is not None:
            rec["trace"] = trace
        if args:
            rec["args"] = args
        self._ring.append(rec)

    def event(self, name, trace=None, **args):
        """Instant (zero-duration) marker."""
        if not self.enabled:
            return
        self.add(name, time.perf_counter(), 0.0, trace=trace, **args)

    def set_meta(self, **kw):
        """Attach metadata (e.g. the driver's per-worker clock offsets) to
        this process's dump."""
        self._meta.update(kw)

    # -- inspection / export ----------------------------------------------

    def ring(self):
        return list(self._ring)

    def clear(self):
        self._ring.clear()
        self._meta.clear()

    def dump_obj(self):
        return {
            "process": self.process,
            "pid": os.getpid(),
            "clock": time.perf_counter(),
            "wall": time.time(),
            "meta": dict(self._meta),
            "spans": list(self._ring),
        }

    def dump(self, path):
        """Write this process's raw span dump (NOT yet a Chrome trace —
        ``merge_dumps``/``chrome_trace`` turn a set of these into one)."""
        tmp = f"{path}.tmp"
        with open(tmp, "w") as fh:
            json.dump(self.dump_obj(), fh)
        os.replace(tmp, path)
        return path


_TRACER = Tracer()


def get_tracer():
    """The process-wide tracer.  Mutated in place by ``configure_tracing``
    so objects that stashed the reference at construction see the flip."""
    return _TRACER


def configure_tracing(*, enabled=True, capacity=None, process=None):
    """Enable/disable the process-wide tracer in place."""
    if capacity is not None and capacity != _TRACER.capacity:
        _TRACER.capacity = capacity
        _TRACER._ring = deque(_TRACER._ring, maxlen=capacity)
    if process is not None:
        _TRACER.process = process
    _TRACER.enabled = enabled
    return _TRACER


def trace_dump_path(trace_dir, process):
    """Canonical per-process dump filename inside a trace directory."""
    return os.path.join(trace_dir, f"trace_{process.replace(':', '_')}.json")


# -- merge / export --------------------------------------------------------


def load_dump(path):
    with open(path) as fh:
        return json.load(fh)


def merge_dumps(dumps):
    """Offset-correct and time-sort spans from several process dumps.

    Any dump may carry ``meta.offsets`` mapping process name -> seconds to
    ADD to that process's timestamps (the driver records these from worker
    hello/heartbeat clock echoes).  Returns a flat span list on one clock,
    each span annotated with its source ``process``/``pid``."""
    offsets = {}
    for d in dumps:
        offsets.update(d.get("meta", {}).get("offsets", {}))
    merged = []
    for d in dumps:
        proc = d.get("process", "main")
        off = float(offsets.get(proc, 0.0))
        pid = d.get("pid", 0)
        for s in d.get("spans", ()):
            s = dict(s)
            s["ts"] = float(s["ts"]) + off
            s["process"] = proc
            s["pid"] = pid
            merged.append(s)
    merged.sort(key=lambda s: s["ts"])
    return merged


def chrome_trace(dumps):
    """Build a Chrome/Perfetto ``trace_event`` JSON object from raw dumps:
    complete ("X") events in microseconds plus process_name metadata."""
    spans = merge_dumps(dumps)
    pids = {}
    events = []
    for d in dumps:
        proc = d.get("process", "main")
        if proc not in pids:
            pids[proc] = d.get("pid") or (len(pids) + 1)
            events.append({"name": "process_name", "ph": "M",
                           "pid": pids[proc], "tid": 0,
                           "args": {"name": proc}})
    for s in spans:
        ev = {"name": s["name"], "ph": "X", "cat": "serve",
              "ts": round(s["ts"] * 1e6, 3),
              "dur": round(s["dur"] * 1e6, 3),
              "pid": pids.get(s["process"], 0), "tid": 0}
        args = dict(s.get("args", ()))
        if "trace" in s:
            args["trace"] = s["trace"]
        if args:
            ev["args"] = args
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(out_path, dumps):
    tmp = f"{out_path}.tmp"
    with open(tmp, "w") as fh:
        json.dump(chrome_trace(dumps), fh)
    os.replace(tmp, out_path)
    return out_path


def merge_trace_dir(trace_dir, out_path=None):
    """Merge every ``trace_*.json`` raw dump in ``trace_dir`` into one
    Perfetto-loadable ``trace.json`` (returns its path, or None if the
    directory holds no dumps)."""
    names = sorted(f for f in os.listdir(trace_dir)
                   if f.startswith("trace_") and f.endswith(".json"))
    if not names:
        return None
    dumps = [load_dump(os.path.join(trace_dir, f)) for f in names]
    out_path = out_path or os.path.join(trace_dir, "trace.json")
    return write_chrome_trace(out_path, dumps)


def spans_for(spans, uid):
    """Spans belonging to one request: tagged ``trace=uid`` directly, or a
    batch span whose args list the uid."""
    out = []
    for s in spans:
        if s.get("trace") == uid:
            out.append(s)
            continue
        uids = s.get("args", {}).get("uids")
        if uids and uid in uids:
            out.append(s)
    return out
