"""Parallelism as rule tables: logical axis name -> mesh axis.

The reference's one strategy is single-host ``pmap`` data parallelism
(``/root/reference/progen_transformer/utils.py:69-91``).  Here every
strategy is a mapping from the model's LOGICAL axis names (declared in
``progen_tpu/models/progen.py`` via ``nn.with_logical_partitioning``) onto
the four mesh axes from ``progen_tpu/core/mesh.py``:

* ``dp``    — batch over ('data','fsdp'); params replicated.
* ``fsdp``  — batch over ('data','fsdp'); every weight matrix sharded on its
              'embed' (or row) axis over 'fsdp' (ZeRO-3: params, grads and
              optimizer state all sharded; XLA all-gathers weights per layer).
* ``tp``    — megatron-style: qkv/mlp column-parallel, out/proj row-parallel
              over 'tensor'; activations sharded on heads/mlp.
* ``sp``    — activations sharded along the sequence over 'seq'
              (context parallelism).  The model forward routes sequence
              mixing through the explicit halo-exchange ops
              (``progen_tpu/parallel/context.py``, shard_map + ppermute)
              whenever the mesh's seq axis is >1 — GSPMD never invents
              collectives for the window structure.  The SGU spatial
              weights shard row-wise.

Strategies compose: rules are merged left-to-right (first occurrence of a
logical axis wins), with ONE exception — ``sp`` is always merged first,
because the context-parallel shard_map ops require the SGU spatial
weights row-sharded over 'seq' regardless of caller order (see
:func:`logical_rules`).  ``("fsdp", "tp")`` gives 2D sharding.  Unlisted
logical axes are replicated.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

# Each rule set: logical axis -> mesh axis (or tuple of mesh axes, or None).
RULE_SETS: dict[str, list[tuple[str, Any]]] = {
    "dp": [
        ("act_batch", ("data", "fsdp")),
    ],
    "fsdp": [
        ("act_batch", ("data", "fsdp")),
        ("embed", "fsdp"),
        ("vocab", "fsdp"),
        ("spatial_row", "fsdp"),
        # the SGU projection's input axis — without this the one kernel
        # whose axes are (mlp_in, mlp) dodged ZeRO-3 entirely (caught by
        # the scale proof's per-device byte audit at base scale)
        ("mlp_in", "fsdp"),
    ],
    "tp": [
        ("act_batch", ("data", "fsdp")),
        ("qkv", "tensor"),
        ("mlp", "tensor"),
        ("act_heads", "tensor"),
        ("act_mlp", "tensor"),
    ],
    "sp": [
        ("act_batch", ("data", "fsdp")),
        ("act_seq", "seq"),
        ("spatial_row", "seq"),
    ],
}


def logical_rules(strategies: Sequence[str] = ("dp",)) -> list[tuple[str, Any]]:
    """Merge rule sets; first occurrence of a logical axis wins (matching
    flax rule semantics where the first matching rule applies).

    ``sp`` is merged FIRST regardless of position: the context-parallel ops
    (``parallel/context.py``) require the SGU spatial weights row-sharded
    over 'seq' (their shard_map in_specs), so sp's ``spatial_row -> seq``
    must beat fsdp's ``spatial_row -> fsdp`` whenever both are requested."""
    ordered = [s for s in strategies if s == "sp"]
    ordered += [s for s in strategies if s != "sp"]
    merged: list[tuple[str, Any]] = []
    seen: set[str] = set()
    for s in ordered:
        for name, axis in RULE_SETS[s]:
            if name not in seen:
                merged.append((name, axis))
                seen.add(name)
    return merged


def validate_tp_divisibility(model_config, tensor_size: int,
                             strategies: Sequence[str] = ("tp",)) -> None:
    """Fail BEFORE jit when a tensor axis cannot divide the model dims.

    The tp rule set column-shards the qkv/mlp kernels and the heads/mlp
    activations; a tensor size that doesn't divide those dims makes GSPMD
    fall back to padded/replicated layouts at best and abort deep inside
    partitioning at worst — neither error names the actual mistake.  This
    check turns it into one actionable message at Trainer/engine build
    time.  No-op when tp isn't requested or the axis is trivial."""
    if "tp" not in strategies or tensor_size <= 1:
        return
    cfg = model_config
    dims: list[tuple[str, int]] = [
        ("heads", cfg.heads),
        ("attention inner dim (heads*dim_head)", cfg.heads * cfg.dim_head),
    ]
    seen_hidden: set[int] = set()
    for i in range(cfg.depth):
        gmlp = cfg.layer_uses_gmlp(i)
        hidden = cfg.dim * cfg.ff_mult * (1 if gmlp or not cfg.ff_glu else 2)
        if hidden not in seen_hidden:
            seen_hidden.add(hidden)
            dims.append((f"ff hidden dim (layer {i})", hidden))
        if gmlp:
            half = (cfg.dim * cfg.ff_mult) // 2
            if half not in seen_hidden:
                seen_hidden.add(half)
                dims.append((f"sgu half dim (layer {i})", half))
    bad = [(name, size) for name, size in dims if size % tensor_size]
    if bad:
        details = ", ".join(f"{name}={size}" for name, size in bad)
        raise ValueError(
            f"tensor axis size {tensor_size} does not divide the model's "
            f"tp-sharded dims: {details}. Pick a tensor size that divides "
            "all of them (or drop 'tp' from strategies)."
        )


def unbox(tree):
    """Strip flax logical-partitioning metadata boxes -> plain arrays."""
    return nn.meta.unbox(tree)


def boxed_abstract_params(model, sample_tokens):
    """Shape-only init (no FLOPs) keeping the logical-axis boxes."""
    return jax.eval_shape(model.init, jax.random.key(0), sample_tokens)


def param_logical_specs(model, sample_tokens):
    """Pytree of logical PartitionSpecs for every parameter."""
    return nn.get_partition_spec(boxed_abstract_params(model, sample_tokens))


def param_shardings(model, sample_tokens, mesh: Mesh,
                    strategies: Sequence[str] = ("dp",)):
    """Pytree of NamedShardings for params under the given strategy mix."""
    rules = logical_rules(strategies)
    logical = param_logical_specs(model, sample_tokens)
    return nn.logical_to_mesh_sharding(logical, mesh, rules)


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Global batch layout: batch dim split over ('data','fsdp')."""
    return NamedSharding(mesh, PartitionSpec(("data", "fsdp"), None))


def superbatch_sharding(mesh: Mesh) -> NamedSharding:
    """Staged superbatch layout ``(K, accum, B, L)``: the scan axes K and
    accum replicate (every chip walks the same step sequence); the batch
    dim shards exactly like :func:`batch_sharding` so each scanned slice
    is already laid out for the step body."""
    return NamedSharding(mesh, PartitionSpec(None, None, ("data", "fsdp"),
                                             None))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
