"""Context (sequence) parallelism: explicit halo exchange over the mesh's
``seq`` axis.

The model's two sequence-mixing structures (SURVEY.md §5.7) and their CP
communication patterns:

* **Local windowed attention** (``ops/local_attention.py``): each query
  window needs only ``[previous window ‖ own window]`` keys, so a sequence
  shard needs exactly ONE window of halo from its left neighbour — a
  single ``ppermute`` hop per layer, O(B·H·window·D) bytes over ICI,
  instead of the generic all-to-all GSPMD falls back to.  Device 0's
  missing left neighbour is the reference's phantom zero-pad window
  (``progen.py:90-95``), which ``ppermute`` provides for free: slots with
  no source are filled with zeros.
* **SGU/gMLP spatial matmul** (``ops/sgu.py``): output row m mixes ALL
  gate rows n <= m, so the gate tensor is all-gathered along ``seq``
  (O(B·L·D/shards) per device per layer — the standard sequence-parallel
  dense-mixing cost) while the learned ``(L, L)`` weights stay row-sharded;
  causal masking uses GLOBAL row indices derived from the shard index.

Both functions are drop-in equivalents of their single-device ops — the
tests assert exact agreement — and run under PARTIAL-MANUAL ``shard_map``:
only the ``seq`` mesh axis is manual (``axis_names={seq}``), so batch/fsdp/
tensor shardings on the same tensors keep flowing through GSPMD and the
ops compose with the dp/fsdp/tp rule sets.  They are called from inside
the model forward (``progen_tpu/models/progen.py``) whenever the model is
built with a mesh whose ``seq`` axis is >1.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P


def _shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
               check_vma=True):
    """``jax.shard_map`` compat: older jax only ships the experimental API,
    which spells partial-manual as ``auto`` (the complement of
    ``axis_names``) and ``check_vma`` as ``check_rep``."""
    if hasattr(jax, "shard_map"):
        kw = {"check_vma": check_vma}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _sm

    auto = (frozenset(mesh.axis_names) - frozenset(axis_names)
            if axis_names is not None else frozenset())
    mapped = _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                 check_rep=check_vma and not auto, auto=auto)
    # the experimental impl rule rejects eager partial-manual calls
    # (``if auto: raise NotImplementedError``); staging through jit lowers
    # them via GSPMD exactly as the modern API does
    return jax.jit(mapped) if auto else mapped


def _axis_size(axis_name: str) -> int:
    """Static mesh-axis size from inside shard_map; ``jax.lax.axis_size``
    only exists on newer jax, but ``psum`` of a unit constant folds to the
    same static int everywhere."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def _left_halo(t, axis_name: str):
    """Send each shard's LAST window right; receive the left neighbour's
    (zeros at the leftmost shard).  ``t``: (..., W_local, wsz, D) ->
    (..., 1, wsz, D) halo window."""
    n = _axis_size(axis_name)
    last = t[..., -1:, :, :]
    if n == 1:
        return jnp.zeros_like(last)
    return jax.lax.ppermute(
        last, axis_name, perm=[(i, i + 1) for i in range(n - 1)]
    )


def _haloed_windows(k_loc, v_loc, window_size: int, seq_axis: str):
    """Shared per-shard halo assembly for both CP attention paths.

    Reshapes the local k/v ``(B, H, L_loc, D)`` into windows, fetches the
    left neighbour's last window, and returns ``(kw, vw, k_halo, v_halo)``
    with ``kw/vw (B, H, W_loc, wsz, D)`` and halos ``(B, H, 1, wsz, D)``.
    """
    b, h, n_loc, d = k_loc.shape
    wsz = window_size
    if n_loc % wsz != 0:
        raise ValueError(
            f"local sequence {n_loc} must be divisible by window {wsz}; "
            "choose a seq-axis size that keeps whole windows per shard"
        )
    w_loc = n_loc // wsz
    kw = k_loc.reshape(b, h, w_loc, wsz, d)
    vw = v_loc.reshape(b, h, w_loc, wsz, d)
    return kw, vw, _left_halo(kw, seq_axis), _left_halo(vw, seq_axis)


def cp_local_attention(
    q, k, v, *, mesh: Mesh, window_size: int, scale: float | None = None,
    seq_axis: str = "seq",
):
    """Sequence-sharded windowed attention: ``(B, H, L, D)`` global tensors,
    L sharded over ``mesh[seq_axis]``; one ppermute halo per call.

    Requires ``L_local % window_size == 0`` (shard boundaries align to
    windows — the natural layout for this model).
    """
    from progen_tpu.ops.local_attention import local_attention

    def inner(q_loc, k_loc, v_loc):
        wsz = window_size
        kw, vw, k_halo, v_halo = _haloed_windows(k_loc, v_loc, wsz, seq_axis)
        # previous window of window j: [halo, own windows 0..W-2][j]
        k_prev = jnp.concatenate([k_halo, kw[..., :-1, :, :]], axis=-3)
        v_prev = jnp.concatenate([v_halo, vw[..., :-1, :, :]], axis=-3)
        k2 = jnp.concatenate([k_prev, kw], axis=-2)  # (b,h,W,2wsz,d)
        v2 = jnp.concatenate([v_prev, vw], axis=-2)

        return local_attention(q_loc, k2, v2, window_size=wsz, scale=scale)

    spec = P(None, None, seq_axis, None)
    return _shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        axis_names=frozenset({seq_axis}), check_vma=True,
    )(q, k, v)


def sharded_pallas_local_attention(
    q, k, v, *, mesh: Mesh, window_size: int, scale: float | None = None,
    seq_axis: str = "seq", batch_axes=("data", "fsdp"), head_axis: str = "tensor",
):
    """The Pallas windowed-attention kernel under a sharded mesh.

    ``pl.pallas_call`` has no GSPMD partitioning rule, so the kernel must
    see per-device arrays: this wrapper runs it inside a FULL-manual
    shard_map — batch over ``batch_axes``, heads over ``head_axis``,
    sequence over ``seq_axis``.  The halo exchange happens on the way in:
    each shard receives its left neighbour's last k/v window by
    ``ppermute`` (zeros on the leftmost shard — the reference's phantom
    window) and hands the kernel EXTENDED k/v, so one code path covers
    every mesh from single-chip (all axes size 1) to dp x tp x sp.

    Requires exact divisibility: ``B % prod(batch_axes)``,
    ``H % head_axis``, ``L/seq_axis % window_size`` — the model's standard
    shapes satisfy all three.
    """
    from progen_tpu.ops.pallas_attention import pallas_local_attention_ext

    d = q.shape[-1]
    scale_v = d ** -0.5 if scale is None else scale
    interp = mesh.devices.flat[0].platform != "tpu"

    def inner(q_loc, k_loc, v_loc):
        b, h, n_loc, dd = q_loc.shape
        wsz = window_size
        kw, vw, k_halo, v_halo = _haloed_windows(k_loc, v_loc, wsz, seq_axis)
        k_ext = jnp.concatenate([k_halo, kw], axis=-3).reshape(
            b, h, n_loc + wsz, dd)
        v_ext = jnp.concatenate([v_halo, vw], axis=-3).reshape(
            b, h, n_loc + wsz, dd)
        return pallas_local_attention_ext(q_loc, k_ext, v_ext, wsz, scale_v,
                                          interp)

    spec = P(batch_axes, head_axis, seq_axis, None)
    # check_vma=False: pallas_call's out_shape carries no varying-mesh-axes
    # metadata, which the vma checker requires; this shard_map is full-manual
    # so there is nothing for the checker to catch anyway.
    return _shard_map(
        inner, mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False,
    )(q, k, v)


def sharded_pallas_spatial_gate(
    res, gate, weights, biases, *, mesh: Mesh, seq_axis: str = "seq",
    batch_axes=("data", "fsdp"), d_axis: str = "tensor",
):
    """The blocked-causal Pallas SGU kernel under a sharded mesh.

    Like :func:`sharded_pallas_local_attention`, ``pl.pallas_call`` has no
    GSPMD partitioning rule, so the kernel runs inside a FULL-manual
    shard_map: batch over ``batch_axes``, the hidden ``d`` over ``d_axis``,
    weights/biases REPLICATED (every device runs the full ``(n, n)``
    triangle against its batch/d slice — the spatial matmul contracts over
    sequence, so the seq axis cannot shard it; fsdp's row-sharding of the
    stored params is re-gathered by ZeRO-3 before apply anyway).

    Sequence parallelism is NOT supported here: ``cp_spatial_gate`` owns
    the op when the mesh's seq axis is >1 (the model falls back to it) —
    this wrapper raises rather than silently mis-sharding.

    Weight/bias gradients: shard_map's transpose inserts the psum over all
    mesh axes for replicated (``P()``) inputs itself — verified empirically
    for this jax version, including with a custom_vjp inside — so the
    kernel's ``reduce_axes`` stays empty (an explicit psum would double
    count).
    """
    from progen_tpu.ops.pallas_sgu import pallas_spatial_gate

    if mesh.shape[seq_axis] != 1:
        raise ValueError(
            f"pallas SGU cannot run under sequence parallelism (mesh "
            f"{seq_axis!r} axis has size {mesh.shape[seq_axis]}); use "
            "sgu_impl='xla' so cp_spatial_gate owns the op"
        )
    interp = mesh.devices.flat[0].platform != "tpu"

    def inner(res_loc, gate_loc, w, b):
        return pallas_spatial_gate(res_loc, gate_loc, w, b, interpret=interp)

    spec = P(batch_axes, None, d_axis)
    # check_vma=False for the same reason as sharded_pallas_local_attention:
    # pallas_call outputs carry no varying-mesh-axes metadata.
    return _shard_map(
        inner, mesh=mesh,
        in_specs=(spec, spec, P(), P()),
        out_specs=spec,
        check_vma=False,
    )(res, gate, weights, biases)


def cp_spatial_gate(
    gate, weights, biases, *, mesh: Mesh, seq_axis: str = "seq"
):
    """Sequence-sharded SGU mixing: ``gate (B, L, D)`` sharded on L,
    ``weights (L, L)``/``biases (L, 1)`` row-sharded; all-gather the gate,
    keep rows local, mask causally by GLOBAL row index."""
    n_total = weights.shape[0]
    # XLA's CPU backend crashes ("Invalid binary instruction opcode copy" in
    # AllReducePromotion) when promoting the bf16 reduce-scatter that is the
    # backward of a bf16 all_gather; gather in f32 there. TPU keeps the
    # narrow dtype on the wire.
    on_cpu = mesh.devices.flat[0].platform == "cpu"

    def inner(gate_loc, w_loc, b_loc):
        n_loc = w_loc.shape[0]
        idx = jax.lax.axis_index(seq_axis)
        # gather full gate along the sequence: (B, L, D)
        if on_cpu and gate_loc.dtype == jnp.bfloat16:
            gate_full = jax.lax.all_gather(
                gate_loc.astype(jnp.float32), seq_axis, axis=1, tiled=True
            ).astype(gate_loc.dtype)
        else:
            gate_full = jax.lax.all_gather(
                gate_loc, seq_axis, axis=1, tiled=True
            )
        rows = idx * n_loc + jnp.arange(n_loc)          # global row ids
        mask = (jnp.arange(n_total)[None, :] <= rows[:, None]).astype(w_loc.dtype)
        w = w_loc * mask
        mixed = jnp.einsum("bnd,mn->bmd", gate_full, w,
                           preferred_element_type=jnp.float32)
        return (mixed + b_loc).astype(gate_loc.dtype)

    return _shard_map(
        inner,
        mesh=mesh,
        in_specs=(P(None, seq_axis, None), P(seq_axis, None), P(seq_axis, None)),
        out_specs=P(None, seq_axis, None),
        axis_names=frozenset({seq_axis}),
        check_vma=True,
    )(gate, weights, biases)
