from progen_tpu.parallel.sharding import (
    RULE_SETS,
    batch_sharding,
    logical_rules,
    param_shardings,
    replicated,
    unbox,
)

__all__ = [
    "RULE_SETS",
    "batch_sharding",
    "logical_rules",
    "param_shardings",
    "replicated",
    "unbox",
]
