from progen_tpu.compat.reference import (
    convert_reference_checkpoint,
    convert_reference_params,
    reference_key_map,
)

__all__ = [
    "convert_reference_checkpoint",
    "convert_reference_params",
    "reference_key_map",
]
