"""Reference-checkpoint migration: Haiku pickles -> this framework.

The reference stores cloudpickled packages ``{next_seq_index, params,
optim_state, model_config, run_id}`` (``/root/reference/train.py:202-208``,
``checkpoint.py:30-31``) with Haiku-named parameters.  A reference user
switching to this framework keeps their trained weights: this module maps
every Haiku parameter onto the flax tree and writes a native (orbax)
checkpoint.

Haiku naming, verified against REAL dm-haiku 0.0.16 by
``tests/test_haiku_naming.py`` (which reconstructs the reference's module
topology in fresh hk code and asserts ``hk.transform(...).init`` emits
exactly these paths/shapes).  The ``/~/`` separators come from haiku's
naming of submodules constructed in a parent's ``__init__`` — the
reference builds everything there (``progen.py:50-233``):

=============================================  ===========================
reference (module | param)                     this framework
=============================================  ===========================
pro_gen_base/~/embed | embeddings              embed/embedding
.../attn{i}/~/layer_norm | scale               attn{i}/norm/scale
.../attn{i}/~/linear | w                       attn{i}/to_qkv/kernel
.../attn{i}/~/linear_1 | w, b                  attn{i}/to_out/kernel, bias
.../ff{i}/~/layer_norm | scale                 ff{i}/norm/scale
.../ff{i}/~/linear | w, b                      ff{i}/proj_in/kernel, bias
.../ff{i}/~/linear_1 | w, b                    ff{i}/proj_out/kernel, bias
.../ff{i}/~/sgu | spatial_weights, _biases     ff{i}/sgu/spatial_weights, _biases
.../ff{i}/~/sgu/~/layer_norm | scale           ff{i}/sgu/norm/scale
.../ff{i}/~/sgu/~/linear | w, b                ff{i}/sgu/proj_out/kernel, bias
pro_gen_base/~/layer_norm | scale              norm_out/scale
pro_gen_base/~/linear | w, b                   to_logits/kernel, bias
=============================================  ===========================

No transposes anywhere: Haiku ``Linear.w`` and flax ``Dense.kernel`` are
both ``(in, out)``; embeddings are both ``(vocab, dim)``; the SGU spatial
weights use the same ``einsum('n d, m n -> m d')`` convention (oracle-
tested on both sides).

The reference's optimizer state (an old-optax ``apply_every`` chain) is
NOT portable and is not converted; resuming re-initializes Adam moments.
``next_seq_index`` and ``run_id`` carry over.

Loss-curve equivalence argument (BASELINE.md's "loss matching single-GPU
baseline"; the reference stack — jax 0.2.20 + haiku 0.0.4 — cannot run
in this environment, so the match is established by composition instead
of a side-by-side run):

1. every op's numerics are pinned to the reference's documented
   semantics by float64 loop-oracle tests written from SURVEY.md §2.a
   (rotary incl. v, token shift, window mask/phantom window, SGU init
   and einsum convention, scale-only LayerNorm, EOS-from-pad loss);
2. the parameter mapping is verified against REAL dm-haiku auto-naming
   and shapes (``tests/test_haiku_naming.py``), and conversion is
   total + shape-checked (this module);
3. converted weights produce logits IDENTICAL to the source tree
   through this framework's forward at f32 (rtol 1e-6,
   ``tests/test_compat.py::test_converted_pickle_drives_model_and_sampler``);
4. the remaining deltas are conscious, each with an exact-mode escape:
   bf16 MXU compute (vs the reference GPU f16 policy) — disable with
   ``mixed_precision=False`` for f32 end to end; threaded-key RNG
   replacing the ``lax.rng_uniform`` monkeypatch — affects init/sampling
   draws, not the loss landscape; ``optax.MultiSteps`` accumulation
   (mathematically the documented intent of ``apply_every``).

Same weights + same data order + same loss function + f32 => the same
curve up to update-order float noise; no component is unverified.
"""

from __future__ import annotations

import pickle
from typing import Any, Mapping

import numpy as np

_REF_ROOT = "pro_gen_base"


def reference_key_map(config) -> dict[tuple[str, str], tuple[str, ...]]:
    """``(haiku_module, haiku_param) -> flax path`` for every parameter of
    ``config`` (a ProGenConfig)."""
    m: dict[tuple[str, str], tuple[str, ...]] = {
        (f"{_REF_ROOT}/~/embed", "embeddings"): ("embed", "embedding"),
        (f"{_REF_ROOT}/~/layer_norm", "scale"): ("norm_out", "scale"),
        (f"{_REF_ROOT}/~/linear", "w"): ("to_logits", "kernel"),
        (f"{_REF_ROOT}/~/linear", "b"): ("to_logits", "bias"),
    }
    for i in range(config.depth):
        a = f"{_REF_ROOT}/~/attn{i}/~"
        f = f"{_REF_ROOT}/~/ff{i}/~"
        m[(f"{a}/layer_norm", "scale")] = (f"attn{i}", "norm", "scale")
        m[(f"{a}/linear", "w")] = (f"attn{i}", "to_qkv", "kernel")
        m[(f"{a}/linear_1", "w")] = (f"attn{i}", "to_out", "kernel")
        m[(f"{a}/linear_1", "b")] = (f"attn{i}", "to_out", "bias")
        m[(f"{f}/layer_norm", "scale")] = (f"ff{i}", "norm", "scale")
        m[(f"{f}/linear", "w")] = (f"ff{i}", "proj_in", "kernel")
        m[(f"{f}/linear", "b")] = (f"ff{i}", "proj_in", "bias")
        m[(f"{f}/linear_1", "w")] = (f"ff{i}", "proj_out", "kernel")
        m[(f"{f}/linear_1", "b")] = (f"ff{i}", "proj_out", "bias")
        if config.layer_uses_gmlp(i):
            sgu = f"{_REF_ROOT}/~/ff{i}/~/sgu"
            m[(sgu, "spatial_weights")] = (f"ff{i}", "sgu", "spatial_weights")
            m[(sgu, "spatial_biases")] = (f"ff{i}", "sgu", "spatial_biases")
            m[(f"{sgu}/~/layer_norm", "scale")] = (
                f"ff{i}", "sgu", "norm", "scale")
            m[(f"{sgu}/~/linear", "w")] = (f"ff{i}", "sgu", "proj_out", "kernel")
            m[(f"{sgu}/~/linear", "b")] = (f"ff{i}", "sgu", "proj_out", "bias")
    return m


def expected_param_shapes(config) -> dict[tuple[str, ...], tuple[int, ...]]:
    """``flax path -> shape`` for every parameter of ``config``, from
    ``jax.eval_shape`` of the model init (zero FLOPs; shares the tracing
    recipe with :func:`progen_tpu.checkpoint.abstract_params_like`)."""
    import jax
    import jax.numpy as jnp

    from progen_tpu.checkpoint import abstract_params_like
    from progen_tpu.core.precision import make_policy
    from progen_tpu.models import ProGen

    model = ProGen(config=config, policy=make_policy())
    tokens = jnp.zeros((1, config.seq_len), jnp.int32)
    abstract = abstract_params_like(model, tokens)
    flat, _ = jax.tree_util.tree_flatten_with_path(abstract)
    return {
        tuple(k.key for k in path): tuple(leaf.shape) for path, leaf in flat
    }


def convert_reference_params(ref_params: Mapping[str, Mapping[str, Any]],
                             config) -> dict:
    """Haiku two-level param dict -> nested flax ``params`` tree (f32).

    Raises on any missing, unexpected or WRONG-SHAPED reference parameter
    so silent partial/corrupt conversions cannot happen (a pickle whose
    weights disagree with its embedded model_config must fail here, at
    conversion time, not later at restore).
    """
    key_map = reference_key_map(config)
    flat_ref = {
        (mod, name): np.asarray(v, dtype=np.float32)
        for mod, sub in ref_params.items()
        for name, v in sub.items()
    }
    missing = set(key_map) - set(flat_ref)
    extra = set(flat_ref) - set(key_map)
    if missing or extra:
        raise ValueError(
            "reference params do not match the config's parameter set:\n"
            f"  missing from pickle: {sorted(missing)}\n"
            f"  unexpected in pickle: {sorted(extra)}"
        )

    expected = expected_param_shapes(config)
    bad = [
        (ref_key, flat_ref[ref_key].shape, expected[path])
        for ref_key, path in key_map.items()
        if tuple(flat_ref[ref_key].shape) != expected[path]
    ]
    if bad:
        lines = "\n".join(
            f"  {mod} | {name}: pickle {got}, config wants {want}"
            for (mod, name), got, want in sorted(bad)
        )
        raise ValueError(
            "reference param shapes disagree with the embedded model_config "
            f"(corrupt or truncated pickle?):\n{lines}"
        )

    out: dict = {}
    for ref_key, path in key_map.items():
        node = out
        for part in path[:-1]:
            node = node.setdefault(part, {})
        node[path[-1]] = flat_ref[ref_key]
    return out


def convert_reference_checkpoint(pkl_path: str, checkpoint_path: str) -> dict:
    """Convert a reference ``ckpt_{time}.pkl`` into a native checkpoint
    store at ``checkpoint_path``.  Returns the written metadata.

    The optimizer state is freshly initialized (see module docstring);
    training resumes at the stored ``next_seq_index`` with step 0.
    """
    import jax
    import jax.numpy as jnp

    from progen_tpu.checkpoint import CheckpointStore
    from progen_tpu.models import ProGenConfig
    from progen_tpu.train.optimizer import make_optimizer
    from progen_tpu.train.step import TrainState

    with open(pkl_path, "rb") as fh:
        package = pickle.load(fh)

    config = ProGenConfig.from_dict(package["model_config"])
    params = convert_reference_params(package["params"], config)
    params = jax.tree.map(jnp.asarray, params)
    opt_state = make_optimizer().init(params)
    state = TrainState(step=jnp.zeros((), jnp.int32), params=params,
                       opt_state=opt_state)

    store = CheckpointStore(checkpoint_path)
    # overwrite: re-converting an updated pickle into the same store must
    # replace step 0, not silently keep the stale weights
    store.save(
        0, state,
        next_seq_index=int(package.get("next_seq_index", 0)),
        model_config=config.to_dict(),
        run_id=package.get("run_id"),
        overwrite=True,
    )
    store.close()
    return {
        "model_config": config.to_dict(),
        "next_seq_index": int(package.get("next_seq_index", 0)),
        "run_id": package.get("run_id"),
        "num_params": sum(x.size for x in jax.tree.leaves(params)),
    }
