"""ProGen model core — flax linen, natively batched, sharding-annotated.

Behavior parity with the reference Haiku model
(``/root/reference/progen_transformer/progen.py``), re-designed TPU-first:

* natively batched ``(B, L) -> (B, L, num_tokens)`` (the reference is
  unbatched ``(L,)`` and relies on an outer ``vmap``, ``progen.py:224-233``;
  we keep its logits semantics, drop the shape contract);
* explicit precision policy (bf16 MXU compute / f32 params+output) instead
  of a class-wide jmp monkeypatch (``progen.py:235-241``);
* every parameter and key activation carries a LOGICAL axis name
  (t5x/maxtext convention) so DP/FSDP/TP/SP are pure rule tables over one
  mesh — see ``progen_tpu/parallel/sharding.py``;
* rotary tables are computed once per forward and shared by all layers
  (same as reference ``progen.py:227``).

Numerics contract implemented here (SURVEY.md §2.a):
scale-only LayerNorm (eps 1e-5, Haiku default); rotary on q, k AND v;
token-shift at the top of both blocks; windowed attention with
previous-window visibility; GEGLU feed-forward; the LAST
``global_mlp_depth`` layers swap GLU for the SGU/gMLP spatial gate; bare
residual adds; LN+Linear head, no weight tying.

The reference accepts dead kwargs ``clamp_gate``/``attn_dim``
(``progen.py:201-202`` — never used); ``ProGenConfig.from_dict`` accepts and
drops them for TOML/checkpoint config compatibility.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Mapping

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import Mesh

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.ops.local_attention import local_attention
from progen_tpu.ops.quant import QuantDense
from progen_tpu.ops.rotary import apply_rotary_pos_emb, fixed_pos_embedding
from progen_tpu.ops.sgu import spatial_gate
from progen_tpu.ops.shift import shift_tokens


def _cp_active(mesh: Mesh | None, axis: str = "seq") -> bool:
    """True when the model should route sequence mixing through the explicit
    halo-exchange / all-gather context-parallel ops
    (``progen_tpu/parallel/context.py``) instead of the single-device ops."""
    return mesh is not None and mesh.shape.get(axis, 1) > 1

# kwargs the reference accepts but never reads (progen.py:201-202) plus
# driver-level kwargs that are not model architecture.
_IGNORED_CONFIG_KEYS = ("clamp_gate", "attn_dim", "mixed_precision")


@dataclasses.dataclass(frozen=True)
class ProGenConfig:
    num_tokens: int = 256
    dim: int = 512
    seq_len: int = 1024
    depth: int = 12
    window_size: int = 256
    global_mlp_depth: int = 2
    heads: int = 8
    dim_head: int = 64
    ff_mult: int = 4
    ff_glu: bool = True
    shift_tokens: bool = True

    @classmethod
    def from_dict(cls, d: Mapping[str, Any]) -> "ProGenConfig":
        clean = {k: v for k, v in d.items() if k not in _IGNORED_CONFIG_KEYS}
        return cls(**clean)

    def to_dict(self) -> dict[str, Any]:
        return dataclasses.asdict(self)

    def layer_uses_gmlp(self, i: int) -> bool:
        """Layer i (0-based) uses the SGU/gMLP feed-forward iff it is among
        the last ``global_mlp_depth`` layers (reference ``progen.py:211``)."""
        return (self.depth - i) <= self.global_mlp_depth


def lora_delta(x, site, tenant):
    """Batched multi-tenant LoRA delta for one adapter site.

    ``x``: the dense layer's input ``(B, ..., Din)``; ``site``: stacked
    per-tenant factors ``{"a": (T, Din, r), "b": (T, r, Dout)}`` (any
    scale/alpha already folded into ``b`` by the converter); ``tenant``:
    ``(B,)`` int32 tenant ids.  Each batch row gathers ITS tenant's
    factors, so one decode step serves every tenant in the batch — the
    einsum contracts over the rank dim per row, no per-tenant dispatch.
    """
    a = jnp.take(site["a"], tenant, axis=0).astype(x.dtype)
    b = jnp.take(site["b"], tenant, axis=0).astype(x.dtype)
    h = jnp.einsum("b...d,bdr->b...r", x, a)
    return jnp.einsum("b...r,bro->b...o", h, b)


def apply_lora(base, x, site, tenant):
    """``base + lora_delta`` for rows with ``tenant > 0``; rows with
    tenant 0 return ``base`` BIT-identically.  The guard is a ``where`` on
    the output, not a zero delta: ``base + 0.0`` flips ``-0.0`` outputs to
    ``+0.0``, which would break the zero-adapter == base-model identity."""
    delta = lora_delta(x, site, tenant)
    live = (tenant > 0).reshape((-1,) + (1,) * (base.ndim - 1))
    return jnp.where(live, base + delta, base)


def _norm(policy: Policy, name: str | None = None) -> nn.LayerNorm:
    # Scale-only LayerNorm, eps matching Haiku's default (reference
    # ``progen.py:22``: create_scale=True, create_offset=False).
    return nn.LayerNorm(
        use_scale=True,
        use_bias=False,
        epsilon=1e-5,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        scale_init=nn.with_logical_partitioning(nn.initializers.ones, ("norm",)),
        name=name,
    )


def _dense(features: int, *, use_bias: bool, axes: tuple[str, str],
           policy: Policy, name: str | None = None,
           weights: str = "bf16") -> nn.Module:
    # weights="int8": the serving-only quantized path — an int8 kernel
    # with the SAME param names ("kernel"/"bias") and its f32 scale in a
    # parallel "qscale" collection (ops/quant.py).  "bf16" (the default)
    # is the unchanged full-precision layer.
    if weights == "int8":
        return QuantDense(features, use_bias=use_bias, axes=axes,
                          policy=policy, name=name)
    if weights != "bf16":
        raise ValueError(f"unknown weights mode {weights!r}; "
                         "use 'bf16' or 'int8'")
    bias_axes = (axes[-1],)
    return nn.Dense(
        features,
        use_bias=use_bias,
        dtype=policy.compute_dtype,
        param_dtype=policy.param_dtype,
        kernel_init=nn.with_logical_partitioning(
            nn.initializers.lecun_normal(), axes
        ),
        bias_init=nn.with_logical_partitioning(nn.initializers.zeros, bias_axes),
        name=name,
    )


class LocalAttention(nn.Module):
    """Pre-LN windowed attention block (reference ``progen.py:50-103``).

    QKV fused into one bias-free projection (reference ``progen.py:70``),
    output projection with bias (``progen.py:71``).
    """

    dim: int
    window_size: int
    heads: int
    dim_head: int
    shift: bool
    policy: Policy
    attn_impl: str = "xla"  # "xla" | "pallas"
    mesh: Mesh | None = None  # seq axis >1 -> context-parallel halo path
    sow_caches: bool = True  # False: skip decode-carry sows (embeddings path)
    weights: str = "bf16"  # "int8": quantized projections (ops/quant.py)

    @nn.compact
    def __call__(self, x, sin, cos, adapters=None, tenant=None):
        b, n, _ = x.shape
        h, d = self.heads, self.dim_head
        inner = h * d

        x = _norm(self.policy, name="norm")(x)
        # post-norm PRE-shift activations: the decode token-shift carry
        # (harvested by decode/prefill.py when the "cache" collection is
        # mutable; a no-op otherwise, and skipped at init so the variable
        # tree stays params-only)
        if self.sow_caches and not self.is_initializing():
            self.sow("cache", "prev", x)
        if self.shift:
            x = shift_tokens(x)

        qkv = _dense(inner * 3, use_bias=False, axes=("embed", "qkv"),
                     policy=self.policy, name="to_qkv",
                     weights=self.weights)(x)
        if adapters is not None:
            qkv = apply_lora(qkv, x, adapters["qkv"], tenant)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # (B, L, H*D) -> (B, H, L, D)
        q, k, v = (
            t.reshape(b, n, h, d).transpose(0, 2, 1, 3) for t in (q, k, v)
        )
        # rotary on q, k AND v — reference progen.py:87
        q, k, v = (apply_rotary_pos_emb(t, sin, cos) for t in (q, k, v))
        # names for the 'attn' remat policy (save_only_these_names): the
        # post-rotary q/k/v feed the attention backward directly, so
        # saving them skips the norm->qkv->rotary replay
        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")
        q = nn.with_logical_constraint(q, ("act_batch", "act_heads", "act_seq", None))
        k = nn.with_logical_constraint(k, ("act_batch", "act_heads", "act_seq", None))
        v = nn.with_logical_constraint(v, ("act_batch", "act_heads", "act_seq", None))
        # post-rotary k/v per position: exactly what the decode ring buffers
        # hold (decode/incremental.py) — prefill harvests these
        if self.sow_caches and not self.is_initializing():
            self.sow("cache", "k", k)
            self.sow("cache", "v", v)

        if self.mesh is not None and self.attn_impl == "pallas":
            # pallas_call has no GSPMD rule — run it full-manual over the
            # mesh (halo exchange included); covers dp/fsdp/tp/sp meshes.
            from progen_tpu.parallel.context import (
                sharded_pallas_local_attention,
            )

            out = sharded_pallas_local_attention(
                q, k, v, mesh=self.mesh, window_size=self.window_size,
                scale=d ** -0.5,
            )
        elif _cp_active(self.mesh):
            from progen_tpu.parallel.context import cp_local_attention

            out = cp_local_attention(
                q, k, v, mesh=self.mesh, window_size=self.window_size,
                scale=d ** -0.5,
            )
        elif self.attn_impl == "pallas":
            from progen_tpu.ops.pallas_attention import pallas_local_attention

            out = pallas_local_attention(q, k, v, self.window_size, d ** -0.5)
        elif self.attn_impl == "xla":
            out = local_attention(q, k, v, window_size=self.window_size,
                                  scale=d ** -0.5)
        else:
            raise ValueError(
                f"unknown attn_impl {self.attn_impl!r}; use 'xla' or 'pallas'"
            )
        out = out.transpose(0, 2, 1, 3).reshape(b, n, inner)
        out = checkpoint_name(out, "attn_out")
        y = _dense(self.dim, use_bias=True, axes=("qkv", "embed"),
                   policy=self.policy, name="to_out",
                   weights=self.weights)(out)
        if adapters is not None:
            y = apply_lora(y, out, adapters["out"], tenant)
        return y


class SGU(nn.Module):
    """gMLP spatial gating unit (reference ``progen.py:151-185``).

    Learned causal ``(n, n)`` token-mixing weights init U(±eps/n) with
    eps=1e-3, biases init to ones; gate half LayerNormed; output projected
    to ``dim_out = hidden // 2``.
    """

    seq_len: int
    dim_out: int
    policy: Policy
    eps: float = 1e-3
    sgu_impl: str = "xla"  # "xla" | "pallas" (blocked-causal fused kernel)
    mesh: Mesh | None = None  # seq axis >1 -> sharded spatial matmul
    sow_caches: bool = True
    weights: str = "bf16"  # "int8": quantized spatial weights + proj_out

    @nn.compact
    def __call__(self, x, adapters=None, tenant=None):
        n = self.seq_len
        x, gate = jnp.split(x, 2, axis=-1)
        gate = _norm(self.policy, name="norm")(gate)
        # normed gate activations per position: the decode SGU gate cache
        # rows (decode/incremental.py SGUDecode) — prefill harvests these
        if self.sow_caches and not self.is_initializing():
            self.sow("cache", "gate", gate)

        init_scale = self.eps / n

        def symmetric_uniform(key, shape, dtype):
            return jax.random.uniform(
                key, shape, dtype, minval=-init_scale, maxval=init_scale
            )

        if self.weights == "int8":
            # int8 per-row spatial weights: same leaf name, re-typed; the
            # f32 row scale rides in "qscale" and is folded back here in
            # f32 (the mix contracts over COLUMNS, so one scale per row
            # is exact up to quantization rounding)
            weights_q = self.param(
                "spatial_weights",
                nn.with_logical_partitioning(
                    nn.initializers.zeros, ("spatial_row", "spatial_col")
                ),
                (n, n),
                jnp.int8,
            )
            w_scale = self.variable(
                "qscale", "spatial_weights_scale",
                lambda: jnp.ones((n,), jnp.float32)).value
            weights = weights_q.astype(jnp.float32) * w_scale[:, None]
        else:
            weights = self.param(
                "spatial_weights",
                nn.with_logical_partitioning(
                    symmetric_uniform, ("spatial_row", "spatial_col")
                ),
                (n, n),
                self.policy.param_dtype,
            )
        biases = self.param(
            "spatial_biases",
            nn.with_logical_partitioning(nn.initializers.ones, ("spatial_row", None)),
            (n, 1),
            self.policy.param_dtype,
        )

        if self.sgu_impl not in ("xla", "pallas"):
            raise ValueError(
                f"unknown sgu_impl {self.sgu_impl!r}; use 'xla' or 'pallas'"
            )

        # inputs shorter than seq_len (one-pass prefill of a prime) use the
        # leading L rows/cols of the learned causal weights — exact, since
        # row m only ever reads columns <= m < L
        L = gate.shape[-2]
        if _cp_active(self.mesh):
            # cp_spatial_gate owns the op under sequence parallelism (the
            # all-gather + row-sharded matmul IS the sp decomposition);
            # sgu_impl="pallas" deliberately falls back here rather than
            # mis-sharding the blocked kernel across the seq axis.
            from progen_tpu.parallel.context import cp_spatial_gate

            if L != n:
                raise ValueError(
                    f"context-parallel SGU requires the full seq_len {n}, "
                    f"got length {L}"
                )
            gate = cp_spatial_gate(
                gate,
                weights.astype(self.policy.compute_dtype),
                biases.astype(self.policy.compute_dtype),
                mesh=self.mesh,
            )
            x = x * gate
        else:
            w = weights[:L, :L] if L != n else weights
            b = biases[:L] if L != n else biases
            w = w.astype(self.policy.compute_dtype)
            b = b.astype(self.policy.compute_dtype)
            if self.sgu_impl == "pallas" and self.mesh is not None:
                # pallas_call has no GSPMD rule — run the fused kernel
                # full-manual over the mesh (weights replicated per device)
                from progen_tpu.parallel.context import (
                    sharded_pallas_spatial_gate,
                )

                x = sharded_pallas_spatial_gate(x, gate, w, b, mesh=self.mesh)
            elif self.sgu_impl == "pallas":
                # fused res * (tril(W) @ gate + b): the mixed tensor never
                # round-trips HBM and upper-triangle blocks are skipped
                from progen_tpu.ops.pallas_sgu import pallas_spatial_gate

                x = pallas_spatial_gate(x, gate, w, b)
            else:
                gate = spatial_gate(gate, w, b)
                x = x * gate
        y = _dense(self.dim_out, use_bias=True, axes=("mlp_in", "mlp"),
                   policy=self.policy, name="proj_out",
                   weights=self.weights)(x)
        if adapters is not None:
            y = apply_lora(y, x, adapters, tenant)
        return y


class FeedForward(nn.Module):
    """Pre-LN MLP with GEGLU or SGU variant (reference ``progen.py:105-149``).

    ``glu`` and ``spatial_gate`` are mutually exclusive (``progen.py:118``);
    the hidden dim doubles under GLU so the gated half matches ``dim*ff_mult``.
    """

    dim: int
    seq_len: int
    ff_mult: int
    glu: bool
    use_sgu: bool
    shift: bool
    policy: Policy
    sgu_impl: str = "xla"
    mesh: Mesh | None = None
    sow_caches: bool = True
    weights: str = "bf16"  # "int8": quantized channel projections

    @nn.compact
    def __call__(self, x, adapters=None, tenant=None):
        assert not (self.glu and self.use_sgu)
        hidden = self.dim * self.ff_mult * (2 if self.glu else 1)

        x = _norm(self.policy, name="norm")(x)
        if self.sow_caches and not self.is_initializing():
            self.sow("cache", "prev", x)
        if self.shift:
            x = shift_tokens(x)

        x = _dense(hidden, use_bias=True, axes=("embed", "mlp"),
                   policy=self.policy, name="proj_in",
                   weights=self.weights)(x)
        x = nn.with_logical_constraint(x, ("act_batch", "act_seq", "act_mlp"))

        if self.glu:
            x, gate = jnp.split(x, 2, axis=-1)
            x = x * nn.gelu(gate)
        else:
            x = nn.gelu(x)

        if self.use_sgu:
            x = SGU(seq_len=self.seq_len, dim_out=hidden // 2,
                    policy=self.policy, sgu_impl=self.sgu_impl,
                    mesh=self.mesh, sow_caches=self.sow_caches,
                    weights=self.weights, name="sgu")(
                        x,
                        None if adapters is None else adapters["sgu"],
                        tenant)

        return _dense(self.dim, use_bias=True, axes=("mlp", "embed"),
                      policy=self.policy, name="proj_out",
                      weights=self.weights)(x)


class ProGen(nn.Module):
    """Full model: embed -> depth x [LocalAttention, FeedForward] -> head.

    ``remat=True`` rematerializes each block in the backward pass
    (``jax.checkpoint`` per layer) — trades ~30% more FLOPs for O(depth)
    less activation memory, the standard TPU HBM trade for the larger
    configs.  ``remat_policy`` refines the trade:

    * ``"full"`` (default) — save only block boundaries; recompute
      EVERYTHING in the backward, including the attention and all matmuls;
    * ``"dots"`` — ``jax.checkpoint_policies.dots_with_no_batch_dims_saveable``:
      matmul outputs are saved, only the cheap elementwise/norm/softmax work
      is recomputed — most of full-remat's memory win at a fraction of its
      recompute FLOPs (the right setting when HBM is tight but not critical);
    * ``"attn"`` — save only the attention path (post-rotary q/k/v and the
      attention output, via ``checkpoint_name``/``save_only_these_names``):
      the backward replays the feed-forward matmuls but never the
      norm->qkv->rotary->windowed-attention chain.  Sits between ``full``
      (save 2 tensors/layer) and ``dots`` (save the fat ff hidden too):
      ~4x ``full``'s saved bytes, ~none of the attention recompute.
    """

    config: ProGenConfig
    policy: Policy = dataclasses.field(default_factory=make_policy)
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots"
    attn_impl: str = "xla"  # "xla" | "pallas" (TPU windowed flash kernel)
    sgu_impl: str = "xla"  # "xla" | "pallas" (blocked-causal fused SGU kernel)
    # With a mesh whose 'seq' axis is >1, sequence mixing (attention windows,
    # SGU spatial matmul) runs through the explicit context-parallel ops
    # (shard_map + ppermute/all_gather) instead of relying on GSPMD to invent
    # collectives for the window structure.
    mesh: Mesh | None = None
    # Embeddings-endpoint switch: sow ONLY the final post-norm hidden states
    # (collection "cache", name "final_hidden") and skip every per-layer
    # decode-carry sow, so the embed program materializes one (B, L, D)
    # tensor instead of full decode caches.  False (the default) is
    # byte-identical to the pre-switch model for all existing callers.
    sow_final_hidden: bool = False
    # "int8": serve quantized weights (ops/quant.py) — every block dense
    # and the SGU spatial weights re-typed int8 with f32 scales in the
    # "qscale" collection.  Embedding, norms and to_logits stay full
    # precision.  "bf16" (the default) is the unchanged model.
    weights: str = "bf16"

    @nn.compact
    def __call__(self, tokens, adapters=None, tenant=None):
        cfg = self.config
        if adapters is not None and tenant is None:
            raise ValueError("adapters require a (B,) tenant-id array")
        if tokens.ndim != 2:
            raise ValueError(
                f"ProGen takes batched (B, L) int tokens, got shape {tokens.shape}; "
                "the reference's unbatched (L,) contract was dropped — add a "
                "leading batch dim"
            )
        b, n = tokens.shape
        if cfg.global_mlp_depth > 0 and n > cfg.seq_len:
            raise ValueError(
                f"input length {n} > config.seq_len {cfg.seq_len}: the gMLP "
                "layers' learned (seq_len, seq_len) spatial weights have no "
                "rows past seq_len"
            )

        x = nn.Embed(
            cfg.num_tokens,
            cfg.dim,
            dtype=self.policy.compute_dtype,
            param_dtype=self.policy.param_dtype,
            embedding_init=nn.with_logical_partitioning(
                nn.initializers.variance_scaling(1.0, "fan_in", "normal", out_axis=0),
                ("vocab", "embed"),
            ),
            name="embed",
        )(tokens)
        x = nn.with_logical_constraint(x, ("act_batch", "act_seq", "act_embed"))

        # rotary tables computed once, shared by all layers (progen.py:227);
        # kept f32, cast inside apply.
        sin, cos = fixed_pos_embedding(n, cfg.dim_head)

        if self.remat:
            if self.remat_policy == "full":
                ckpt_policy = None
            elif self.remat_policy == "dots":
                ckpt_policy = (
                    jax.checkpoint_policies.dots_with_no_batch_dims_saveable
                )
            elif self.remat_policy == "attn":
                ckpt_policy = jax.checkpoint_policies.save_only_these_names(
                    "attn_q", "attn_k", "attn_v", "attn_out"
                )
            else:
                raise ValueError(
                    f"unknown remat_policy {self.remat_policy!r}; "
                    "use 'full', 'dots' or 'attn'"
                )
            attn_cls = nn.remat(LocalAttention, policy=ckpt_policy)
            ff_cls = nn.remat(FeedForward, policy=ckpt_policy)
        else:
            attn_cls = LocalAttention
            ff_cls = FeedForward

        sow_caches = not self.sow_final_hidden
        for i in range(cfg.depth):
            use_gmlp = cfg.layer_uses_gmlp(i)
            attn_ad = None if adapters is None else adapters.get(f"attn{i}")
            ff_ad = None if adapters is None else adapters.get(f"ff{i}")
            x = x + attn_cls(
                dim=cfg.dim,
                window_size=cfg.window_size,
                heads=cfg.heads,
                dim_head=cfg.dim_head,
                shift=cfg.shift_tokens,
                policy=self.policy,
                attn_impl=self.attn_impl,
                mesh=self.mesh,
                sow_caches=sow_caches,
                weights=self.weights,
                name=f"attn{i}",
            )(x, sin, cos, attn_ad, tenant)
            x = x + ff_cls(
                dim=cfg.dim,
                seq_len=cfg.seq_len,
                ff_mult=cfg.ff_mult,
                glu=(not use_gmlp) and cfg.ff_glu,
                use_sgu=use_gmlp,
                shift=cfg.shift_tokens,
                policy=self.policy,
                sgu_impl=self.sgu_impl,
                mesh=self.mesh,
                sow_caches=sow_caches,
                weights=self.weights,
                name=f"ff{i}",
            )(x, ff_ad, tenant)
            x = nn.with_logical_constraint(x, ("act_batch", "act_seq", "act_embed"))

        x = _norm(self.policy, name="norm_out")(x)
        if self.sow_final_hidden and not self.is_initializing():
            self.sow("cache", "final_hidden", x)
        logits = _dense(cfg.num_tokens, use_bias=True, axes=("embed", "vocab"),
                        policy=self.policy, name="to_logits")(x)
        return self.policy.cast_to_output(logits)
