from progen_tpu.models.configs import draft_config_for
from progen_tpu.models.progen import FeedForward, LocalAttention, ProGen, ProGenConfig, SGU

__all__ = ["FeedForward", "LocalAttention", "ProGen", "ProGenConfig", "SGU",
           "draft_config_for"]
