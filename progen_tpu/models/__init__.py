from progen_tpu.models.progen import FeedForward, LocalAttention, ProGen, ProGenConfig, SGU

__all__ = ["FeedForward", "LocalAttention", "ProGen", "ProGenConfig", "SGU"]
