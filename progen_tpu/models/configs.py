"""The five baseline model configs (BASELINE.md / driver BASELINE.json).

These are the committed equivalents of the reference's TOML model configs
(``/root/reference/configs/model/default.toml``), extended to the scale
ladder the TPU build targets.
"""

from __future__ import annotations

from progen_tpu.models.progen import ProGenConfig

# Reference repo's default toy config (configs/model/default.toml:1-9).
DEFAULT = ProGenConfig(
    num_tokens=256, dim=128, depth=3, heads=3, dim_head=32,
    window_size=512, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-tiny: README demo config (README.md:34-44).
TINY = ProGenConfig(
    num_tokens=256, dim=512, depth=12, heads=8, dim_head=64,
    window_size=256, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-small (~150M).
SMALL = ProGenConfig(
    num_tokens=256, dim=1024, depth=12, heads=8, dim_head=128,
    window_size=256, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-base (~760M).
BASE = ProGenConfig(
    num_tokens=256, dim=1536, depth=24, heads=12, dim_head=128,
    window_size=512, seq_len=2048, ff_glu=True, global_mlp_depth=2,
)

# ProGen-large (1.2B, paper config scale).
LARGE = ProGenConfig(
    num_tokens=256, dim=1536, depth=36, heads=12, dim_head=128,
    window_size=512, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-XL (~6B).
XL = ProGenConfig(
    num_tokens=256, dim=4096, depth=32, heads=32, dim_head=128,
    window_size=512, seq_len=4096, ff_glu=True, global_mlp_depth=2,
)

CONFIGS = {
    "default": DEFAULT,
    "tiny": TINY,
    "small": SMALL,
    "base": BASE,
    "large": LARGE,
    "xl": XL,
}
