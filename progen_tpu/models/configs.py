"""The five baseline model configs (BASELINE.md / driver BASELINE.json).

These are the committed equivalents of the reference's TOML model configs
(``/root/reference/configs/model/default.toml``), extended to the scale
ladder the TPU build targets.
"""

from __future__ import annotations

import dataclasses

from progen_tpu.models.progen import ProGenConfig

# Reference repo's default toy config (configs/model/default.toml:1-9).
DEFAULT = ProGenConfig(
    num_tokens=256, dim=128, depth=3, heads=3, dim_head=32,
    window_size=512, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-tiny: README demo config (README.md:34-44).
TINY = ProGenConfig(
    num_tokens=256, dim=512, depth=12, heads=8, dim_head=64,
    window_size=256, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-small (~150M).
SMALL = ProGenConfig(
    num_tokens=256, dim=1024, depth=12, heads=8, dim_head=128,
    window_size=256, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-base (~760M).
BASE = ProGenConfig(
    num_tokens=256, dim=1536, depth=24, heads=12, dim_head=128,
    window_size=512, seq_len=2048, ff_glu=True, global_mlp_depth=2,
)

# ProGen-large (1.2B, paper config scale).
LARGE = ProGenConfig(
    num_tokens=256, dim=1536, depth=36, heads=12, dim_head=128,
    window_size=512, seq_len=1024, ff_glu=True, global_mlp_depth=2,
)

# ProGen-XL (~6B).
XL = ProGenConfig(
    num_tokens=256, dim=4096, depth=32, heads=32, dim_head=128,
    window_size=512, seq_len=4096, ff_glu=True, global_mlp_depth=2,
)

CONFIGS = {
    "default": DEFAULT,
    "tiny": TINY,
    "small": SMALL,
    "base": BASE,
    "large": LARGE,
    "xl": XL,
}


def draft_config_for(target: ProGenConfig, *, dim: int | None = None,
                     depth: int | None = None, heads: int | None = None,
                     dim_head: int | None = None) -> ProGenConfig:
    """A tiny draft config for speculative decoding against ``target``.

    The draft MUST share ``num_tokens`` (proposals live in the target's
    vocabulary), ``window_size`` (the serving engine's prefill buckets are
    window-aligned, and one padded prime batch prefills both models) and
    ``seq_len`` (positions mean the same thing to both).  Everything that
    only affects capacity — width, depth, heads — shrinks; the default is
    a quarter-width, two-layer model with one gMLP layer.
    """
    depth = depth if depth is not None else min(2, target.depth)
    return dataclasses.replace(
        target,
        dim=dim if dim is not None else max(8, target.dim // 4),
        depth=depth,
        heads=heads if heads is not None else max(1, target.heads // 2),
        dim_head=dim_head if dim_head is not None else target.dim_head,
        global_mlp_depth=min(1, depth),
    )
