"""Rule ``mesh-axis``: PartitionSpec axis names must exist in the mesh.

GSPMD silently replicates a dimension whose PartitionSpec names an axis the
mesh does not declare (or errors late, deep inside pjit lowering).  Both
failure modes are expensive on real hardware, so the check is static: every
string literal inside a ``PartitionSpec(...)`` / ``P(...)`` call — including
nested tuples like ``P(("data", "fsdp"), None)`` — must be a member of the
vocabulary scraped from ``core/mesh.py``.  Non-literal axis expressions
(variables, ``*axes`` splats) are skipped: the rule only judges what it can
read.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import call_name

_SPEC_NAMES = frozenset({"P", "PartitionSpec", "jax.sharding.PartitionSpec"})


def _literal_axes(node: ast.AST):
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node.value, node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            yield from _literal_axes(elt)


@rule("mesh-axis")
def check(module: ParsedModule, ctx: RepoContext):
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        if call_name(node) not in _SPEC_NAMES:
            continue
        args = list(node.args) + [kw.value for kw in node.keywords]
        for arg in args:
            for axis, lit in _literal_axes(arg):
                if axis not in ctx.mesh_axes:
                    known = ", ".join(sorted(ctx.mesh_axes))
                    yield Finding(
                        rule="mesh-axis",
                        path=module.path,
                        line=lit.lineno,
                        col=lit.col_offset,
                        message=(
                            f"PartitionSpec axis '{axis}' is not a declared "
                            f"mesh axis (known: {known})"
                        ),
                    )
