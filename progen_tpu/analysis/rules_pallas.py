"""Rules ``pallas-indexmap`` and ``pallas-ref-write``: kernel hygiene.

``pallas-indexmap``: a ``BlockSpec`` index map runs at *trace* time to
build the block schedule — it may close over host-static ints (block
counts derived from shapes, annotated int params) but never over traced
arrays; a traced closure either fails deep in lowering or bakes in a stale
value.  Staticness of closed-over names is decided by
:class:`~progen_tpu.analysis.jaxgraph.StaticEnv` on the enclosing function.

``pallas-ref-write``: inside a kernel body, a plain ``ref[...] = value``
store in a ``for``/``while`` loop usually means the author wanted an
accumulation (``ref[...] += value``) or a ``pl.when``-guarded epilogue
write; each plain store clobbers the block written by the previous
iteration.  Stores outside loops, augmented stores, and read-modify-write
stores are the accepted idioms and pass.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import (
    StaticEnv,
    call_name,
    module_return_staticness,
    target_simple_name,
    walk_functions,
)

_BLOCKSPEC_NAMES = frozenset(
    {"pl.BlockSpec", "pltpu.BlockSpec", "BlockSpec", "pallas.BlockSpec"}
)
_PALLAS_CALL_NAMES = frozenset(
    {"pl.pallas_call", "pltpu.pallas_call", "pallas_call"}
)


def _uses_pallas(module: ParsedModule) -> bool:
    return "pallas" in module.source


def _lambda_free_names(lam: ast.Lambda) -> set[str]:
    bound = {a.arg for a in lam.args.args + lam.args.kwonlyargs}
    free: set[str] = set()
    for node in ast.walk(lam.body):
        if isinstance(node, ast.Name) and node.id not in bound:
            free.add(node.id)
    return free


@rule("pallas-indexmap")
def check_indexmap(module: ParsedModule, ctx: RepoContext):
    if not _uses_pallas(module):
        return
    returns = module_return_staticness(module.tree)
    for fn in walk_functions(module.tree):
        env = None  # built lazily, once per enclosing function
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _BLOCKSPEC_NAMES:
                continue
            lams = [a for a in node.args if isinstance(a, ast.Lambda)]
            lams += [
                kw.value
                for kw in node.keywords
                if kw.arg == "index_map" and isinstance(kw.value, ast.Lambda)
            ]
            for lam in lams:
                if env is None:
                    env = StaticEnv(fn, returns=returns)
                for name in sorted(_lambda_free_names(lam)):
                    if name in env.local and name not in env.static:
                        yield Finding(
                            rule="pallas-indexmap",
                            path=module.path,
                            line=lam.lineno,
                            col=lam.col_offset,
                            message=(
                                f"BlockSpec index_map closes over '{name}', "
                                "which is not provably host-static; index "
                                "maps may only capture shapes/ints known at "
                                "trace time"
                            ),
                        )


def _kernel_defs(module: ParsedModule) -> set[str]:
    """Names of functions passed (possibly via partial) to pallas_call."""
    kernels: set[str] = set()
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Call) and call_name(node) in _PALLAS_CALL_NAMES:
            if node.args:
                name = target_simple_name(node.args[0])
                if name:
                    kernels.add(name)
            for kw in node.keywords:
                if kw.arg in (None, "kernel", "f"):
                    name = target_simple_name(kw.value)
                    if name:
                        kernels.add(name)
    return kernels


@rule("pallas-ref-write")
def check_ref_writes(module: ParsedModule, ctx: RepoContext):
    if not _uses_pallas(module):
        return
    kernels = _kernel_defs(module)
    if not kernels:
        return
    for fn in walk_functions(module.tree):
        if fn.name not in kernels:
            continue
        params = {a.arg for a in fn.args.args}
        for loop in ast.walk(fn):
            if not isinstance(loop, (ast.For, ast.While)):
                continue
            for stmt in ast.walk(loop):
                if not isinstance(stmt, ast.Assign):
                    continue
                for t in stmt.targets:
                    if not isinstance(t, ast.Subscript):
                        continue
                    base = t.value
                    if not (
                        isinstance(base, ast.Name) and base.id in params
                    ):
                        continue
                    # read-modify-write of the same ref is an accumulation
                    reads_self = any(
                        isinstance(n, ast.Name) and n.id == base.id
                        for n in ast.walk(stmt.value)
                    )
                    if reads_self:
                        continue
                    yield Finding(
                        rule="pallas-ref-write",
                        path=module.path,
                        line=stmt.lineno,
                        col=stmt.col_offset,
                        message=(
                            f"plain store to kernel ref '{base.id}' inside "
                            "a loop clobbers previous iterations; use "
                            "'ref[...] += ...' or guard the epilogue write "
                            "with pl.when"
                        ),
                    )
