"""wire-*: serialize/deserialize schema consistency.

The handoff/transport wire protocol is a pile of dict-shaped frames
whose writers and readers live in different processes and different
files — nothing type-checks them against each other.  Three rules close
the loop statically:

* ``wire-dead-field`` — a field the writer emits that no paired reader
  ever looks at (dead payload bytes, or a reader someone forgot);
* ``wire-strict-read`` — a field the writer ELIDES at its default value
  (the "priority omitted when 0" pattern) but a reader indexes strictly
  (``d["priority"]``): works until the first default-valued message;
* ``wire-const-mismatch`` — a MAGIC/VERSION constant bound to
  conflicting values in one module, or pack/unpack struct format
  strings that drifted apart.

Pairs come from two places: a naming convention inside one module
(``X_to_wire``/``X_from_wire``, ``serialize_X``/``deserialize_X``,
``pack_X``/``unpack_X`` — first parameter is the message dict), and the
declarative :data:`WIRE_PAIRS` table for the real fleet protocol whose
writers and readers span files (the table wins where both apply).
Counterpart files are parsed through ``ctx.root`` — AST only, nothing is
imported.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import dotted, qualnames, walk_functions

# ---------------------------------------------------------------------------
# pair declarations
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class WirePair:
    """Writers: ``(relpath, func, scrape)`` where scrape is ``"dicts"``
    (message-dict literals + subscript stores in the function) or
    ``"kwarg:NAME"`` (dict literals passed as keyword ``NAME`` — how the
    prefill loop injects routing tags into ``serialize_handle``).
    Readers: ``(relpath, func, varname)`` — every subscript/.get on that
    variable in the function counts as a read."""

    name: str
    writers: tuple
    readers: tuple


WIRE_PAIRS: tuple[WirePair, ...] = (
    WirePair(
        "request",
        writers=(("progen_tpu/decode/handoff.py", "request_to_wire",
                  "dicts"),),
        readers=(("progen_tpu/decode/handoff.py", "request_from_wire",
                  "d"),),
    ),
    WirePair(
        "completion",
        writers=(("progen_tpu/serve/worker.py", "_completion_to_wire",
                  "dicts"),),
        readers=(
            ("progen_tpu/serve/cluster.py", "_completion_from_wire",
             "header"),
            ("progen_tpu/serve/cluster.py", "ServeCluster._handle_event",
             "header"),
        ),
    ),
    WirePair(
        "handle-header",
        writers=(
            ("progen_tpu/decode/handoff.py", "serialize_handle", "dicts"),
            ("progen_tpu/serve/worker.py", "_prefill_loop",
             "kwarg:extra_header"),
        ),
        readers=(
            ("progen_tpu/decode/handoff.py", "deserialize_handle", "header"),
            ("progen_tpu/serve/cluster.py", "ServeCluster._on_handle",
             "header"),
            ("progen_tpu/serve/cluster.py", "ServeCluster._handle_event",
             "header"),
            ("progen_tpu/serve/worker.py", "_decode_loop", "header"),
        ),
    ),
)

_CONVENTIONS = (
    (re.compile(r"(.+)_to_wire$"), "{}_from_wire"),
    (re.compile(r"serialize_(.+)$"), "deserialize_{}"),
    (re.compile(r"pack_(.+)$"), "unpack_{}"),
)

_TABLE_FUNCS = {
    (path, func.rsplit(".", 1)[-1])
    for pair in WIRE_PAIRS
    for (path, func, *_rest) in list(pair.writers) + list(pair.readers)
}


# ---------------------------------------------------------------------------
# scraping
# ---------------------------------------------------------------------------


def _nested_walk(fn):
    """Yield ``(node, conditional)`` — conditional means the node sits
    under a branch/loop/try, i.e. the write does not happen on every
    message."""

    def visit(stmts, cond):
        for stmt in stmts:
            yield stmt, cond
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub and isinstance(sub, list) and sub \
                        and isinstance(sub[0], ast.stmt):
                    inner = cond or not isinstance(stmt, (
                        ast.FunctionDef, ast.AsyncFunctionDef))
                    yield from visit(sub, inner)
            for h in getattr(stmt, "handlers", ()):
                yield from visit(h.body, True)

    yield from visit(fn.body, False)


def _dict_literal_keys(node: ast.Dict):
    for k in node.keys:
        if isinstance(k, ast.Constant) and isinstance(k.value, str):
            yield k.value, k.lineno, k.col_offset


def scrape_writer(fn, scrape: str = "dicts") -> dict:
    """``{field: (conditional, line, col)}`` the function writes."""
    fields: dict = {}

    def note(key, line, col, cond):
        prev = fields.get(key)
        if prev is None or (prev[0] and not cond):
            fields[key] = (cond, line, col)

    if scrape.startswith("kwarg:"):
        kwarg = scrape.split(":", 1)[1]
        for stmt, cond in _nested_walk(fn):
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call):
                    for kw in sub.keywords:
                        if kw.arg == kwarg and isinstance(kw.value, ast.Dict):
                            for key, ln, col in _dict_literal_keys(kw.value):
                                note(key, ln, col, cond)
        return fields

    dict_vars: set = set()
    for stmt, cond in _nested_walk(fn):
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Dict) \
                and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Name):
            keys = list(_dict_literal_keys(stmt.value))
            if keys:
                dict_vars.add(stmt.targets[0].id)
                for key, ln, col in keys:
                    note(key, ln, col, cond)
        elif isinstance(stmt, ast.Return) and isinstance(stmt.value,
                                                         ast.Dict):
            for key, ln, col in _dict_literal_keys(stmt.value):
                note(key, ln, col, cond)
    for stmt, cond in _nested_walk(fn):
        if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                and isinstance(stmt.targets[0], ast.Subscript):
            t = stmt.targets[0]
            if isinstance(t.value, ast.Name) and t.value.id in dict_vars \
                    and isinstance(t.slice, ast.Constant) \
                    and isinstance(t.slice.value, str):
                note(t.slice.value, stmt.lineno, stmt.col_offset, cond)
        for sub in ast.walk(stmt):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "update" \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.value.id in dict_vars \
                    and sub.args and isinstance(sub.args[0], ast.Dict):
                for key, ln, col in _dict_literal_keys(sub.args[0]):
                    note(key, ln, col, cond)
    return fields


def scrape_reader(fn, varnames) -> dict:
    """``{field: (strict, line, col)}`` read off the message variable(s).
    A strict read that is guarded anywhere in the function (``"k" in d``
    or ``d.get("k") is not None``) counts as tolerant."""
    varnames = set(varnames)
    reads: dict = {}
    guards: set = set()

    def note(key, strict, line, col):
        prev = reads.get(key)
        if prev is None or (strict and not prev[0]):
            reads[key] = (strict, line, col)

    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load) \
                and isinstance(node.value, ast.Name) \
                and node.value.id in varnames \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            note(node.slice.value, True, node.lineno, node.col_offset)
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            recv, attr = node.func.value, node.func.attr
            if isinstance(recv, ast.Name) and recv.id in varnames \
                    and node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                key = node.args[0].value
                if attr == "get":
                    note(key, False, node.lineno, node.col_offset)
                    guards.add(key)  # d.get("k") is a presence probe too
                elif attr == "pop":
                    note(key, len(node.args) < 2, node.lineno,
                         node.col_offset)
        if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str):
            comp = node.comparators[0]
            if isinstance(comp, ast.Name) and comp.id in varnames:
                guards.add(node.left.value)
                note(node.left.value, False, node.lineno, node.col_offset)
    return {
        k: (strict and k not in guards, line, col)
        for k, (strict, line, col) in reads.items()
    }


# ---------------------------------------------------------------------------
# counterpart resolution
# ---------------------------------------------------------------------------

_AST_CACHE: dict = {}


def _module_tree(ctx: RepoContext, relpath: str, current: ParsedModule):
    if current.path == relpath:
        return current.tree
    key = (str(ctx.root), relpath)
    if key not in _AST_CACHE:
        path = ctx.root / relpath
        tree = None
        if path.is_file():
            try:
                tree = ast.parse(path.read_text())
            except SyntaxError:
                tree = None
        _AST_CACHE[key] = tree
    return _AST_CACHE[key]


def _find_fn(tree, qual: str):
    if tree is None:
        return None
    quals = qualnames(tree)
    simple = qual.rsplit(".", 1)[-1]
    for fn, q in quals.items():
        if q == qual or (("." not in qual) and q.rsplit(".", 1)[-1] == simple
                         and "." not in q):
            return fn
    for fn, q in quals.items():
        if q.rsplit(".", 1)[-1] == simple:
            return fn
    return None


def _first_param(fn) -> str | None:
    args = [a.arg for a in fn.args.args if a.arg not in ("self", "cls")]
    return args[0] if args else None


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _pair_findings(module, pairname, written, read_union, here_written,
                   here_reads):
    """Findings anchored in the current module for one resolved pair.
    ``here_written`` holds only the fields whose write site is in this
    module — dead-field findings anchor at the write, so fields written
    by a counterpart file are reported when THAT file is checked."""
    out = []
    if read_union is not None:
        for key, (cond, line, col) in sorted(here_written.items()):
            if key not in read_union:
                out.append(Finding(
                    rule="wire-dead-field", path=module.path, line=line,
                    col=col,
                    message=f"wire field '{key}' ({pairname}) is written "
                            "but never read by any paired reader"))
    for key, (strict, line, col) in sorted(here_reads.items()):
        if strict and written.get(key, (False,))[0]:
            out.append(Finding(
                rule="wire-strict-read", path=module.path, line=line,
                col=col,
                message=f"wire field '{key}' ({pairname}) is elided by its "
                        "writer at the default value but read without a "
                        "fallback — use .get() with the elide default"))
    return out


def _resolve_pair(module, ctx, pair: WirePair):
    written: dict = {}
    here_written: dict = {}
    for relpath, func, scrape in pair.writers:
        tree = _module_tree(ctx, relpath, module)
        fn = _find_fn(tree, func)
        if fn is None:
            continue
        fields = scrape_writer(fn, scrape)
        for key, val in fields.items():
            prev = written.get(key)
            if prev is None or (prev[0] and not val[0]):
                written[key] = val
        if relpath == module.path:
            here_written.update(fields)
    if not written:
        return None
    read_union: set = set()
    readers_found = False
    here_reads: dict = {}
    for relpath, func, var in pair.readers:
        tree = _module_tree(ctx, relpath, module)
        fn = _find_fn(tree, func)
        if fn is None:
            continue
        readers_found = True
        reads = scrape_reader(fn, {var})
        read_union.update(reads)
        if relpath == module.path:
            for key, val in reads.items():
                prev = here_reads.get(key)
                if prev is None or (val[0] and not prev[0]):
                    here_reads[key] = val
    if not readers_found:
        return None
    if not here_written and not here_reads:
        return None
    return (pair.name, written, read_union, here_written, here_reads)


@rule("wire-dead-field")
def check_dead_fields(module: ParsedModule, ctx: RepoContext):
    yield from (f for f in _run_pairs(module, ctx)
                if f.rule == "wire-dead-field")


@rule("wire-strict-read")
def check_strict_reads(module: ParsedModule, ctx: RepoContext):
    yield from (f for f in _run_pairs(module, ctx)
                if f.rule == "wire-strict-read")


def _run_pairs(module: ParsedModule, ctx: RepoContext):
    out: list[Finding] = []
    seen_funcs: set = set()
    for pair in WIRE_PAIRS:
        involved = any(rel == module.path
                       for rel, *_r in list(pair.writers) + list(pair.readers))
        if not involved:
            continue
        resolved = _resolve_pair(module, ctx, pair)
        if resolved is None:
            continue
        name, written, read_union, here_written, here_reads = resolved
        out.extend(_pair_findings(module, name, written, read_union,
                                  here_written, here_reads))
        for rel, func, *_r in list(pair.writers) + list(pair.readers):
            if rel == module.path:
                seen_funcs.add(func.rsplit(".", 1)[-1])

    # same-module convention pairs (X_to_wire / X_from_wire, ...)
    fns = {f.name: f for f in walk_functions(module.tree)}
    for fname, fn in sorted(fns.items()):
        if fname in seen_funcs or (module.path, fname) in _TABLE_FUNCS:
            continue
        for pat, template in _CONVENTIONS:
            m = pat.match(fname)
            if not m:
                continue
            other = fns.get(template.format(m.group(1)))
            if other is None or other.name in seen_funcs:
                continue
            written = scrape_writer(fn)
            var = _first_param(other)
            if not written or var is None:
                continue
            reads = scrape_reader(other, {var})
            out.extend(_pair_findings(module, fname, written, set(reads),
                                      written, reads))
            break
    key = lambda f: (f.rule, f.line, f.col, f.message)  # noqa: E731
    seen: set = set()
    uniq = []
    for f in sorted(out, key=key):
        if key(f) not in seen:
            seen.add(key(f))
            uniq.append(f)
    return uniq


# ---------------------------------------------------------------------------
# constants / struct formats
# ---------------------------------------------------------------------------

_CONST_RE = re.compile(r"(MAGIC|VERSION)")
_PACKISH = re.compile(r"(to_wire|serialize|pack)")
_UNPACKISH = re.compile(r"(from_wire|deserialize|unpack|peek|parse)")


@rule("wire-const-mismatch")
def check_const_mismatch(module: ParsedModule, ctx: RepoContext):
    bound: dict = {}
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name) \
                and isinstance(node.value, ast.Constant):
            name = node.targets[0].id
            if not (name.isupper() and _CONST_RE.search(name)):
                continue
            val = node.value.value
            if name in bound and bound[name][0] != val:
                yield Finding(
                    rule="wire-const-mismatch", path=module.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"wire constant '{name}' is bound to "
                            f"conflicting values ({bound[name][0]!r} vs "
                            f"{val!r}) — pack and peek will disagree")
            else:
                bound.setdefault(name, (val, node.lineno))

    pack_fmts: set = set()
    unpack_fmts: set = set()
    sites: dict = {}
    for fn in walk_functions(module.tree):
        side = None
        # unpack first: "unpack_frame" also contains the substring "pack"
        if _UNPACKISH.search(fn.name):
            side = unpack_fmts
        elif _PACKISH.search(fn.name):
            side = pack_fmts
        if side is None:
            continue
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                callee = dotted(node.func) or ""
                if callee.split(".")[-1] in ("pack", "pack_into", "unpack",
                                             "unpack_from", "Struct",
                                             "calcsize") \
                        and callee.split(".")[0] == "struct" \
                        and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str):
                    fmt = node.args[0].value
                    side.add(fmt)
                    sites.setdefault(fmt, (node.lineno, node.col_offset))
    if pack_fmts and unpack_fmts and pack_fmts != unpack_fmts:
        for fmt in sorted(pack_fmts ^ unpack_fmts):
            line, col = sites[fmt]
            yield Finding(
                rule="wire-const-mismatch", path=module.path, line=line,
                col=col,
                message=f"struct format {fmt!r} is used on only one side of "
                        f"a pack/unpack pair (pack side {sorted(pack_fmts)}, "
                        f"unpack side {sorted(unpack_fmts)})")
