"""Rule ``trace-safety``: host-side impurities inside traced code.

``random.*`` / ``np.random.*`` draw from untracked host state, ``time.*``
reads the host clock, and ``print`` fires once at trace time — all silent
no-ops or wrong under jit.  Flag them only in functions the per-module
trace graph proves reachable from a jit/scan/shard_map/pallas root; host
driver code (training loop, CLI, benchmarks) may use them freely.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import TraceGraph, call_name

_BAD_PREFIXES = ("random.", "np.random.", "numpy.random.", "time.")

_SUGGESTION = {
    "print": "use jax.debug.print inside traced code",
    "time": "host clocks are trace-time constants under jit; time outside "
    "the jitted function",
    "random": "thread a jax.random key through the function instead",
}


def _suggest(name: str) -> str:
    if name == "print":
        return _SUGGESTION["print"]
    if name.startswith("time."):
        return _SUGGESTION["time"]
    return _SUGGESTION["random"]


@rule("trace-safety")
def check(module: ParsedModule, ctx: RepoContext):
    graph = TraceGraph(module.tree)
    if not graph.traced:
        return
    seen: set[int] = set()
    for fn in graph.traced:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) or id(node) in seen:
                continue
            name = call_name(node)
            if name is None:
                continue
            if name == "print" or any(
                name.startswith(p) for p in _BAD_PREFIXES
            ):
                seen.add(id(node))
                yield Finding(
                    rule="trace-safety",
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"'{name}' called inside traced function "
                        f"'{fn.name}': {_suggest(name)}"
                    ),
                )
