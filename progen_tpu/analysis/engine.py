"""graftcheck rule engine: findings, suppressions, baseline, runner.

The analyzer is pure-stdlib AST walking — it never imports jax and never
executes repo code, so it runs in milliseconds and is safe to call from a
tier-1 test or a pre-push hook.  Rules live in sibling ``rules_*`` modules
and register themselves into :data:`RULES` at import time.

Suppression grammar (checked on the finding's line, then the line above if
that line is comment-only):

    x = risky()  # graftcheck: disable=host-sync
    # graftcheck: disable=host-sync,trace-safety
    # graftcheck: disable-file=mesh-axis        (anywhere: whole file)

Baseline: ``tools/graftcheck_baseline.json`` holds accepted legacy findings
keyed on ``(rule, path, message)`` — deliberately not the line number, so
unrelated edits above a baselined finding don't invalidate it.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import json
import re
import tokenize
from pathlib import Path
from typing import Callable, Iterable, Sequence

DEFAULT_MESH_AXES = frozenset({"data", "fsdp", "tensor", "seq"})

_SUPPRESS_RE = re.compile(r"#\s*graftcheck:\s*disable=([\w,\-]+)")
_SUPPRESS_FILE_RE = re.compile(r"#\s*graftcheck:\s*disable-file=([\w,\-]+)")
_COMMENT_ONLY_RE = re.compile(r"^\s*#")


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str
    path: str  # posix, relative to the analysis root
    line: int
    col: int
    message: str

    def key(self) -> tuple[str, str, str]:
        return (self.rule, self.path, self.message)

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "message": self.message,
        }


@dataclasses.dataclass
class ParsedModule:
    path: str  # posix relpath used in findings
    source: str
    tree: ast.Module
    lines: list[str]


@dataclasses.dataclass
class RepoContext:
    """Cross-file facts shared by all rules (currently: mesh-axis vocab)."""

    root: Path
    mesh_axes: frozenset[str] = DEFAULT_MESH_AXES


# rule name -> callable(module, ctx) -> iterable of Finding
RULES: dict[str, Callable[[ParsedModule, RepoContext], Iterable[Finding]]] = {}


def rule(name: str):
    """Decorator: register a rule function under ``name``."""

    def register(fn):
        fn.rule_name = name
        RULES[name] = fn
        return fn

    return register


# ---------------------------------------------------------------------------
# context discovery
# ---------------------------------------------------------------------------


def _string_tuple_assigns(tree: ast.Module, names: set[str]) -> set[str]:
    found: set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        if not any(t in names for t in targets):
            continue
        if isinstance(node.value, (ast.Tuple, ast.List)):
            elts = node.value.elts
            if elts and all(
                isinstance(e, ast.Constant) and isinstance(e.value, str)
                for e in elts
            ):
                found.update(e.value for e in elts)
    return found


def discover_mesh_axes(root: Path) -> frozenset[str]:
    """Read the mesh-axis vocabulary out of core/mesh.py (AST only).

    Falls back to :data:`DEFAULT_MESH_AXES` when the declaration can't be
    found — a missing vocab must never turn every PartitionSpec into noise.
    """
    axes: set[str] = set()
    for rel in ("progen_tpu/core/mesh.py", "progen_tpu/parallel/sharding.py"):
        path = root / rel
        if not path.is_file():
            continue
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError:
            continue
        axes |= _string_tuple_assigns(tree, {"MESH_AXES", "AXES", "MESH_AXIS_NAMES"})
    return frozenset(axes) if axes else DEFAULT_MESH_AXES


def build_context(root: Path) -> RepoContext:
    return RepoContext(root=root, mesh_axes=discover_mesh_axes(root))


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------


def _comment_lines(lines: Sequence[str]):
    """``(lineno, line_text)`` for lines carrying a real COMMENT token.
    Tokenizing (rather than regexing every line) keeps suppression
    examples inside docstrings — like the ones at the top of this file —
    from acting as live suppressions or rotting into stale ones."""
    src = "\n".join(lines) + "\n"
    comment_rows: set[int] | None = set()
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                comment_rows.add(tok.start[0])
    except (tokenize.TokenError, SyntaxError, IndentationError, ValueError):
        comment_rows = None  # unterminated string etc: fall back to all lines
    for i, text in enumerate(lines, start=1):
        if comment_rows is None or i in comment_rows:
            yield i, text


class Suppressions:
    """Parses the suppression comments of one file and, while findings
    are checked against it, records which entries actually fired — a
    suppression that never matches anything is itself reportable (the
    ``stale-suppression`` rule) so sanctioned-leak comments can't
    outlive the code they sanction."""

    def __init__(self, lines: Sequence[str]):
        self.by_line: dict[int, set[str]] = {}
        self.file_wide: set[str] = set()
        self._comment_only: set[int] = set()
        # entries that matched at least one finding: (line, rule) for
        # per-line entries, (0, rule) for file-wide ones
        self.matched: set[tuple[int, str]] = set()
        for i, text in _comment_lines(lines):
            m = _SUPPRESS_FILE_RE.search(text)
            if m:
                self.file_wide.update(m.group(1).split(","))
            m = _SUPPRESS_RE.search(text)
            if m:
                self.by_line.setdefault(i, set()).update(m.group(1).split(","))
            if _COMMENT_ONLY_RE.match(text):
                self._comment_only.add(i)

    def is_suppressed(self, finding: Finding) -> bool:
        hit = False
        for name in (finding.rule, "all"):
            if name in self.file_wide:
                self.matched.add((0, name))
                hit = True
        if hit:
            return True
        for line in (finding.line, finding.line - 1):
            rules = self.by_line.get(line)
            if rules is None:
                continue
            if line != finding.line and line not in self._comment_only:
                continue  # trailing comment on the previous code line: no
            for name in (finding.rule, "all"):
                if name in rules:
                    self.matched.add((line, name))
                    hit = True
        return hit

    def stale_findings(self, path: str,
                       rules_run: set | None = None) -> list[Finding]:
        """Suppression entries that no finding ever matched.  With a
        rule filter active, entries naming rules that didn't run are
        skipped — their target simply wasn't looked for."""

        def eligible(name: str) -> bool:
            if rules_run is None:
                return True
            return name == "all" or name in rules_run

        out: list[Finding] = []
        for line in sorted(self.by_line):
            for name in sorted(self.by_line[line]):
                if eligible(name) and (line, name) not in self.matched:
                    out.append(Finding(
                        rule="stale-suppression", path=path, line=line,
                        col=0,
                        message=f"suppression 'graftcheck: disable={name}' "
                                "never matched a finding — the sanctioned "
                                "code is gone, delete the comment"))
        for name in sorted(self.file_wide):
            if eligible(name) and (0, name) not in self.matched:
                out.append(Finding(
                    rule="stale-suppression", path=path, line=1, col=0,
                    message=f"suppression 'graftcheck: disable-file={name}' "
                            "never matched a finding — the sanctioned "
                            "code is gone, delete the comment"))
        return out


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------


def load_baseline(path: Path) -> set[tuple[str, str, str]]:
    data = json.loads(path.read_text())
    return {
        (f["rule"], f["path"], f["message"]) for f in data.get("findings", [])
    }


def save_baseline(path: Path, findings: Sequence[Finding]) -> None:
    data = {
        "version": 1,
        "findings": [
            {"rule": f.rule, "path": f.path, "message": f.message}
            for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule))
        ],
    }
    path.write_text(json.dumps(data, indent=2) + "\n")


def apply_baseline(
    findings: Sequence[Finding], baseline: set[tuple[str, str, str]]
) -> tuple[list[Finding], list[Finding]]:
    """Split findings into (new, baselined)."""
    new, old = [], []
    for f in findings:
        (old if f.key() in baseline else new).append(f)
    return new, old


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------

_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "node_modules", ".venv"}


def iter_python_files(paths: Sequence[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_file() and p.suffix == ".py":
            yield p
        elif p.is_dir():
            for sub in sorted(p.rglob("*.py")):
                if not any(part in _SKIP_DIRS for part in sub.parts):
                    yield sub


def parse_module(path: Path, root: Path) -> ParsedModule | None:
    source = path.read_text()
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None
    try:
        rel = path.resolve().relative_to(root.resolve()).as_posix()
    except ValueError:
        rel = path.as_posix()
    return ParsedModule(
        path=rel, source=source, tree=tree, lines=source.splitlines()
    )


def check_source(
    source: str,
    path: str = "<string>",
    rules: Sequence[str] | None = None,
    ctx: RepoContext | None = None,
    report_stale: bool = False,
) -> list[Finding]:
    """Analyze a source string — the unit-test entry point."""
    tree = ast.parse(source)
    module = ParsedModule(
        path=path, source=source, tree=tree, lines=source.splitlines()
    )
    return check_module(module, ctx or RepoContext(root=Path(".")), rules,
                        report_stale=report_stale)


def check_module(
    module: ParsedModule,
    ctx: RepoContext,
    rules: Sequence[str] | None = None,
    *,
    report_stale: bool = False,
) -> list[Finding]:
    suppress = Suppressions(module.lines)
    out: list[Finding] = []
    for name, fn in RULES.items():
        if rules is not None and name not in rules:
            continue
        for finding in fn(module, ctx):
            if not suppress.is_suppressed(finding):
                out.append(finding)
    if report_stale:
        rules_run = None if rules is None else set(rules)
        for finding in suppress.stale_findings(module.path, rules_run):
            # a stale-suppression finding is suppressible like any other
            # (and doing so un-stales the entry that names it)
            if not suppress.is_suppressed(finding):
                out.append(finding)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def run(
    paths: Sequence[Path],
    root: Path,
    rules: Sequence[str] | None = None,
    *,
    report_stale: bool = False,
) -> list[Finding]:
    # rule modules register themselves on import; keep this lazy so that
    # `from progen_tpu.analysis import engine` alone stays import-cycle free
    from progen_tpu.analysis import load_rules

    load_rules()
    ctx = build_context(root)
    findings: list[Finding] = []
    for file in iter_python_files(paths):
        module = parse_module(file, root)
        if module is None:
            findings.append(
                Finding(
                    rule="parse-error",
                    path=file.as_posix(),
                    line=1,
                    col=0,
                    message="file does not parse as Python",
                )
            )
            continue
        findings.extend(check_module(module, ctx, rules,
                                     report_stale=report_stale))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


# ---------------------------------------------------------------------------
# output
# ---------------------------------------------------------------------------


def format_human(findings: Sequence[Finding], baselined: int = 0) -> str:
    lines = [
        f"{f.path}:{f.line}:{f.col}: [{f.rule}] {f.message}" for f in findings
    ]
    tail = f"{len(findings)} finding(s)"
    if baselined:
        tail += f" ({baselined} baselined finding(s) hidden)"
    lines.append(tail)
    return "\n".join(lines)


def format_json(findings: Sequence[Finding], baselined: int = 0) -> str:
    return json.dumps(
        {
            "version": 1,
            "findings": [f.to_json() for f in findings],
            "count": len(findings),
            "baselined": baselined,
        },
        indent=2,
    )


def format_sarif(findings: Sequence[Finding], baselined: int = 0) -> str:
    """SARIF 2.1.0 — the interchange format CI annotators and editors
    consume; one run, one result per finding, columns 1-based."""
    rule_ids = sorted({f.rule for f in findings})
    results = [
        {
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {
                            "uri": f.path,
                            "uriBaseId": "SRCROOT",
                        },
                        "region": {
                            "startLine": max(1, f.line),
                            "startColumn": f.col + 1,
                        },
                    }
                }
            ],
        }
        for f in findings
    ]
    doc = {
        "$schema": "https://docs.oasis-open.org/sarif/sarif/v2.1.0/os/"
                   "schemas/sarif-schema-2.1.0.json",
        "version": "2.1.0",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "graftcheck",
                        "informationUri":
                            "https://example.invalid/progen-tpu/graftcheck",
                        "rules": [{"id": r} for r in rule_ids],
                    }
                },
                "results": results,
                "properties": {"baselined": baselined},
            }
        ],
    }
    return json.dumps(doc, indent=2)
