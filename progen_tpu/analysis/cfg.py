"""Intraprocedural control-flow graphs + a generic forward dataflow
fixpoint, pure stdlib — the path-sensitive substrate under the lifecycle
rules (``rules_lifecycle``).

One :class:`CFG` per function.  Nodes are *simple statements* (one node
per assign/expr/return/...), plus synthetic nodes:

* ``entry`` / ``exit`` — function entry and *normal* exit (every
  ``return`` and body fall-through reaches ``exit``);
* ``raise_exit`` — exceptional exit: exceptions that escape the
  function, explicit or implicit, land here;
* ``branch`` — the test of an ``if``/``while`` (``stmt`` is the
  ``ast.If``/``ast.While``, out-edges are labelled ``true``/``false``);
* ``for`` — a ``for`` head (``true`` = the iterator yielded, ``false``
  = exhausted);
* ``except`` — an ``except`` clause head (entered via ``exc`` edges).

Edge labels: ``norm`` (sequencing), ``true``/``false`` (branch
outcomes), ``exc`` (exception propagation — from any statement that can
raise to the enclosing handlers and, because a typed handler may not
match, onward to ``raise_exit``).

``try``/``finally`` is modelled by *instantiating* the ``finally`` body
once per distinct continuation (fall-through, return, break, continue,
exception) — the node lists differ but share the same ``ast`` statement
objects, so per-statement analyses behave identically on every copy.
``while True:``-style constant tests drop the dead edge so analyses
don't report along impossible paths.

The fixpoint (:func:`forward_dataflow`) is edge-sensitive: the transfer
function sees ``(node, state, edge_label)`` and can e.g. withhold an
acquisition along the acquiring statement's own ``exc`` edge, or narrow
``x is None`` facts along ``true``/``false``.  States must be hashable
values with equality; ``join`` must be monotone for termination.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Callable, Iterable

__all__ = ["CFG", "Node", "build_cfg", "forward_dataflow"]

FuncDef = (ast.FunctionDef, ast.AsyncFunctionDef)

# statement types that get their own node and cannot raise by themselves
_SIMPLE = (
    ast.Assign, ast.AugAssign, ast.AnnAssign, ast.Expr, ast.Pass,
    ast.Delete, ast.Global, ast.Nonlocal, ast.Import, ast.ImportFrom,
    ast.Assert, ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
)

_CATCH_ALL = {"Exception", "BaseException"}


@dataclasses.dataclass
class Node:
    idx: int
    kind: str  # entry|exit|raise_exit|stmt|branch|for|with|except|return|raise
    stmt: ast.stmt | None = None

    @property
    def line(self) -> int:
        return getattr(self.stmt, "lineno", 0)


class CFG:
    def __init__(self, fn):
        self.fn = fn
        self.nodes: list[Node] = []
        self.succ: dict[int, list[tuple[int, str]]] = {}
        self.entry = self._new("entry")
        self.exit = self._new("exit")
        self.raise_exit = self._new("raise_exit")

    def _new(self, kind: str, stmt: ast.stmt | None = None) -> int:
        n = Node(len(self.nodes), kind, stmt)
        self.nodes.append(n)
        return n.idx

    def _edge(self, src: int, dst: int, label: str = "norm") -> None:
        edges = self.succ.setdefault(src, [])
        if (dst, label) not in edges:
            edges.append((dst, label))

    # -- queries (unit tests assert against these) -------------------------

    def node(self, idx: int) -> Node:
        return self.nodes[idx]

    def successors(self, idx: int) -> list[tuple[int, str]]:
        return list(self.succ.get(idx, ()))

    def nodes_for_line(self, lineno: int) -> list[Node]:
        """Every node whose statement starts on ``lineno`` — a finally
        body statement appears once per instantiated continuation."""
        return [n for n in self.nodes if n.stmt is not None
                and n.stmt.lineno == lineno]

    def reachable_from(self, idx: int, *, labels: set[str] | None = None
                       ) -> set[int]:
        seen, stack = set(), [idx]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            for dst, label in self.succ.get(n, ()):
                if labels is None or label in labels:
                    stack.append(dst)
        return seen


# ---------------------------------------------------------------------------
# construction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class _Frame:
    """One level of enclosing control context, innermost last."""

    kind: str  # "loop" | "try"
    # loop
    head: int = -1
    breaks: list = dataclasses.field(default_factory=list)
    is_for: bool = False
    # try
    handler_heads: tuple = ()
    catch_all: bool = False
    finalbody: tuple = ()
    section: str = "body"  # which part of the try is being built
    fin_cache: dict = dataclasses.field(default_factory=dict)


def _may_raise(stmt: ast.stmt) -> bool:
    """Conservative 'this statement can raise': it contains a call (or is
    an assert).  Attribute/subscript errors are deliberately ignored —
    treating every expression as throwing would drown real error-path
    findings in impossible ones."""
    if isinstance(stmt, (ast.Assert, ast.Raise)):
        return True
    for sub in ast.walk(stmt):
        if isinstance(sub, ast.Call):
            return True
        if isinstance(sub, (ast.Await, ast.Yield, ast.YieldFrom)):
            return True
    return False


def _const_test(test: ast.expr):
    """Constant-valued branch test -> its truthiness, else None."""
    if isinstance(test, ast.Constant):
        return bool(test.value)
    return None


class _Builder:
    def __init__(self, fn):
        self.cfg = CFG(fn)

    def build(self) -> CFG:
        dangling = self._body(self.cfg.fn.body, [(self.cfg.entry, "norm")], [])
        self._connect(dangling, self.cfg.exit)
        return self.cfg

    # -- plumbing ----------------------------------------------------------

    def _connect(self, preds: list[tuple[int, str]], dst: int) -> None:
        for src, label in preds:
            self.cfg._edge(src, dst, label)

    def _route(self, preds, frames, purpose) -> None:
        """Send ``preds`` out of the frame stack: through every enclosing
        ``finally`` to the purpose's destination (exit / loop head / loop
        break / handlers+raise_exit)."""
        for i in range(len(frames) - 1, -1, -1):
            fr = frames[i]
            if fr.kind == "loop" and purpose[0] in ("break", "continue") \
                    and fr is purpose[1]:
                if purpose[0] == "break":
                    fr.breaks.extend(preds)
                else:
                    self._connect(preds, fr.head)
                return
            if fr.kind != "try":
                continue
            if purpose[0] == "exc" and fr.section == "body" \
                    and fr.handler_heads:
                for h in fr.handler_heads:
                    self._connect(preds, h)
                if fr.catch_all:
                    return
                # a typed handler may not match: keep propagating
                preds = [(src, "exc") for src, _ in preds]
            if fr.finalbody:
                head = self._finally_instance(frames, i, purpose)
                self._connect(preds, head)
                return
        if purpose[0] == "exc":
            self._connect(preds, self.cfg.raise_exit)
        else:
            self._connect(preds, self.cfg.exit)

    def _finally_instance(self, frames, i, purpose) -> int:
        """Shared copy of ``frames[i]``'s finally body for ``purpose``;
        its own exit continues routing outward past frame ``i``."""
        fr = frames[i]
        key = (purpose[0], id(purpose[1]) if len(purpose) > 1 else None)
        if key in fr.fin_cache:
            return fr.fin_cache[key]
        head = self.cfg._new("finally", None)
        fr.fin_cache[key] = head
        outer = frames[:i]
        dangling = self._body(list(fr.finalbody), [(head, "norm")], outer)
        self._route(dangling, outer, purpose)
        return head

    # -- statement sequencing ----------------------------------------------

    def _body(self, stmts, preds, frames) -> list[tuple[int, str]]:
        for stmt in stmts:
            preds = self._stmt(stmt, preds, frames)
            if not preds:
                break  # everything below is unreachable
        return preds

    def _stmt(self, stmt, preds, frames) -> list[tuple[int, str]]:
        if isinstance(stmt, ast.If):
            return self._if(stmt, preds, frames)
        if isinstance(stmt, ast.While):
            return self._while(stmt, preds, frames)
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            return self._for(stmt, preds, frames)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, preds, frames)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, preds, frames)
        if isinstance(stmt, ast.Match):
            return self._match(stmt, preds, frames)
        if isinstance(stmt, ast.Return):
            node = self.cfg._new("return", stmt)
            self._connect(preds, node)
            self._exc(node, stmt, frames)
            self._route([(node, "norm")], frames, ("return",))
            return []
        if isinstance(stmt, ast.Raise):
            node = self.cfg._new("raise", stmt)
            self._connect(preds, node)
            self._route([(node, "exc")], frames, ("exc",))
            return []
        if isinstance(stmt, ast.Break):
            node = self.cfg._new("stmt", stmt)
            self._connect(preds, node)
            loop = self._innermost_loop(frames)
            self._route([(node, "norm")], frames, ("break", loop))
            return []
        if isinstance(stmt, ast.Continue):
            node = self.cfg._new("stmt", stmt)
            self._connect(preds, node)
            loop = self._innermost_loop(frames)
            self._route([(node, "norm")], frames, ("continue", loop))
            return []
        # simple statement
        node = self.cfg._new("stmt", stmt)
        self._connect(preds, node)
        return self._stmt_node(stmt, node, frames)

    def _stmt_node(self, stmt, node, frames) -> list[tuple[int, str]]:
        self._exc(node, stmt, frames)
        return [(node, "norm")]

    def _exc(self, node, stmt, frames) -> None:
        if _may_raise(stmt):
            self._route([(node, "exc")], frames, ("exc",))

    @staticmethod
    def _innermost_loop(frames) -> _Frame:
        for fr in reversed(frames):
            if fr.kind == "loop":
                return fr
        raise AssertionError("break/continue outside loop")

    # -- compound statements ----------------------------------------------

    def _if(self, stmt, preds, frames):
        node = self.cfg._new("branch", stmt)
        self._connect(preds, node)
        self._exc(node, ast.Expr(value=stmt.test), frames)
        const = _const_test(stmt.test)
        out = []
        if const is not False:
            out.extend(self._body(stmt.body, [(node, "true")], frames))
        if const is not True:
            if stmt.orelse:
                out.extend(self._body(stmt.orelse, [(node, "false")], frames))
            else:
                out.append((node, "false"))
        return out

    def _while(self, stmt, preds, frames):
        node = self.cfg._new("branch", stmt)
        self._connect(preds, node)
        self._exc(node, ast.Expr(value=stmt.test), frames)
        fr = _Frame(kind="loop", head=node)
        const = _const_test(stmt.test)
        if const is not False:
            back = self._body(stmt.body, [(node, "true")], frames + [fr])
            self._connect(back, node)
        out = list(fr.breaks)
        if const is not True:
            if stmt.orelse:
                out.extend(self._body(stmt.orelse, [(node, "false")], frames))
            else:
                out.append((node, "false"))
        return out

    def _for(self, stmt, preds, frames):
        node = self.cfg._new("for", stmt)
        self._connect(preds, node)
        self._exc(node, ast.Expr(value=stmt.iter), frames)
        fr = _Frame(kind="loop", head=node, is_for=True)
        back = self._body(stmt.body, [(node, "true")], frames + [fr])
        self._connect(back, node)
        out = list(fr.breaks)
        if stmt.orelse:
            out.extend(self._body(stmt.orelse, [(node, "false")], frames))
        else:
            out.append((node, "false"))
        return out

    def _with(self, stmt, preds, frames):
        node = self.cfg._new("with", stmt)
        self._connect(preds, node)
        self._exc(node, stmt, frames)  # entering may raise
        return self._body(stmt.body, [(node, "norm")], frames)

    def _match(self, stmt, preds, frames):
        node = self.cfg._new("branch", stmt)
        self._connect(preds, node)
        out = [(node, "false")]  # no case matched
        for case in stmt.cases:
            out.extend(self._body(case.body, [(node, "true")], frames))
        return out

    def _try(self, stmt, preds, frames):
        heads = []
        catch_all = not stmt.handlers  # bare try/finally: nothing caught
        for h in stmt.handlers:
            heads.append(self.cfg._new("except", None))
            if h.type is None:
                catch_all = True
            else:
                name = None
                if isinstance(h.type, (ast.Name, ast.Attribute)):
                    name = h.type.attr if isinstance(h.type, ast.Attribute) \
                        else h.type.id
                if name in _CATCH_ALL:
                    catch_all = True
        fin = tuple(stmt.finalbody)
        fr = _Frame(kind="try", handler_heads=tuple(heads),
                    catch_all=catch_all and bool(stmt.handlers),
                    finalbody=fin)
        body_out = self._body(stmt.body, preds, frames + [fr])
        if stmt.orelse:
            fr.section = "else"
            body_out = self._body(stmt.orelse, body_out, frames + [fr])
        out = list(body_out)
        fr.section = "handler"
        for head, h in zip(heads, stmt.handlers):
            out.extend(self._body(h.body, [(head, "norm")], frames + [fr]))
        if fin:
            if not out:
                return []  # every try path returned/raised: finally
                # copies already exist on those routes
            # normal completion runs its own finally copy, then falls
            # through to whatever follows the try statement
            head = self.cfg._new("finally", None)
            self._connect(out, head)
            return self._body(list(fin), [(head, "norm")], frames)
        return out


def build_cfg(fn) -> CFG:
    """CFG for one ``ast.FunctionDef`` / ``ast.AsyncFunctionDef``."""
    if not isinstance(fn, FuncDef):
        raise TypeError(f"build_cfg wants a function def, got {type(fn)}")
    return _Builder(fn).build()


# ---------------------------------------------------------------------------
# dataflow
# ---------------------------------------------------------------------------


def forward_dataflow(
    cfg: CFG,
    *,
    init,
    transfer: Callable,
    join: Callable,
    max_iter: int = 100_000,
):
    """Forward fixpoint over ``cfg``.

    ``transfer(node, state, label)`` maps the state at a node's entry to
    the state propagated along one labelled out-edge; ``join(a, b)``
    merges states where paths meet.  Returns ``{node_idx: entry_state}``
    for every reached node.  Monotone ``join`` + finite state lattice =>
    termination; ``max_iter`` is a backstop against non-monotone bugs.
    """
    states = {cfg.entry: init}
    work = [cfg.entry]
    iters = 0
    while work:
        iters += 1
        if iters > max_iter:
            raise RuntimeError("dataflow did not converge (non-monotone "
                               "transfer/join?)")
        n = work.pop()
        state = states[n]
        node = cfg.nodes[n]
        for dst, label in cfg.succ.get(n, ()):
            out = transfer(node, state, label)
            old = states.get(dst)
            new = out if old is None else join(old, out)
            if new != old:
                states[dst] = new
                work.append(dst)
    return states


def functions(tree: ast.Module) -> Iterable:
    """All function defs in a module, nested included (mirror of
    ``jaxgraph.walk_functions`` without the import)."""
    for node in ast.walk(tree):
        if isinstance(node, FuncDef):
            yield node
