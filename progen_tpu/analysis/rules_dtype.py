"""Rules ``dtype-pet`` and ``dtype-f32-literal``: mixed-precision hygiene.

``dtype-pet``: every ``jnp.einsum`` / ``lax.dot_general`` in the numeric
core (``ops/``, ``decode/``) must pin ``preferred_element_type`` — on TPU
a bf16×bf16 contraction otherwise accumulates in bf16, which is exactly
the silent-precision-loss class the MXU's f32 accumulator exists to avoid.

``dtype-f32-literal``: a Python float literal that is not exactly
representable in bfloat16 (e.g. ``1e-6``, ``0.1``) mixed into arithmetic
with an explicitly-bf16 operand rounds at the binding — epsilons vanish,
scales drift.  Exact literals (``0.5``, ``2.0``) pass.
"""

from __future__ import annotations

import ast
import math
import struct

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import call_name

_CONTRACTIONS = frozenset(
    {
        "jnp.einsum",
        "jax.numpy.einsum",
        "np.einsum",  # misuse in ops/ would be wrong anyway; flag it
        "lax.dot_general",
        "jax.lax.dot_general",
        "lax.dot",
        "jax.lax.dot",
    }
)

_SCOPED_DIRS = ("ops/", "decode/")


def _in_scope(path: str) -> bool:
    return any(f"/{d}" in path or path.startswith(d) for d in _SCOPED_DIRS)


def bf16_exact(value: float) -> bool:
    """True if ``value`` round-trips bfloat16 exactly (8-bit mantissa)."""
    if not math.isfinite(value):
        return True
    f32 = struct.unpack(">I", struct.pack(">f", value))[0]
    if struct.unpack(">f", struct.pack(">I", f32))[0] != value:
        return False  # not even f32-exact
    # round-to-nearest-even to the top 16 bits
    lower = f32 & 0xFFFF
    rounded = f32 & 0xFFFF0000
    if lower > 0x8000 or (lower == 0x8000 and (f32 >> 16) & 1):
        rounded += 0x10000
    return struct.unpack(">f", struct.pack(">I", rounded & 0xFFFFFFFF))[0] == value


def _mentions_bf16(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr == "bfloat16":
            return True
        if isinstance(sub, ast.Constant) and sub.value == "bfloat16":
            return True
    return False


@rule("dtype-pet")
def check_pet(module: ParsedModule, ctx: RepoContext):
    if not _in_scope(module.path):
        return
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name not in _CONTRACTIONS:
            continue
        if any(kw.arg == "preferred_element_type" for kw in node.keywords):
            continue
        yield Finding(
            rule="dtype-pet",
            path=module.path,
            line=node.lineno,
            col=node.col_offset,
            message=(
                f"'{name}' without preferred_element_type: bf16 inputs "
                "accumulate in bf16 on the MXU; pass "
                "preferred_element_type=jnp.float32"
            ),
        )


@rule("dtype-f32-literal")
def check_literals(module: ParsedModule, ctx: RepoContext):
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.BinOp):
            continue
        for lit, other in (
            (node.left, node.right),
            (node.right, node.left),
        ):
            if (
                isinstance(lit, ast.Constant)
                and isinstance(lit.value, float)
                and not bf16_exact(lit.value)
                and _mentions_bf16(other)
            ):
                yield Finding(
                    rule="dtype-f32-literal",
                    path=module.path,
                    line=lit.lineno,
                    col=lit.col_offset,
                    message=(
                        f"float literal {lit.value!r} is not bf16-exact but "
                        "mixes into bf16 arithmetic; compute in f32 and cast "
                        "once at the end"
                    ),
                )
                break
