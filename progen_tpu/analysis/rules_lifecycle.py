"""resource-leak: path-sensitive resource-linearity checking.

Every resource class the serving fleet leaks in practice is declared in
:data:`SPECS` as an acquire/release pair (plus how ownership can leave a
function).  For each function we build the CFG (``analysis/cfg.py``) and
run a forward dataflow whose state is the set of *held* resources; any
path on which a held resource reaches

* the normal function exit (fall-through or early ``return``), or
* an explicit ``raise`` that escapes the function, or
* a rebinding of the holding variable

without a release or an ownership transfer is a finding.  Exception
edges count: an acquire inside a ``try`` whose handler forgets to roll
the resource back (the PR 16 fork-rollback class) reaches the normal
exit *through the handler* and is reported.

Ownership transfer is deliberately generous — passing the resource to
any call, storing it anywhere, returning it, or building a bigger value
out of it all stop tracking.  The rule only fires when a function
provably keeps the last reference to itself and drops it, which is what
keeps a path-sensitive rule quiet enough to gate CI at zero findings.

``with acquire() as x:`` is sanctioned by construction.  Declaring a new
resource is one :class:`ResourceSpec` entry; docs/ANALYSIS.md walks
through the fields.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from progen_tpu.analysis.cfg import build_cfg, forward_dataflow
from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import dotted, qualnames, walk_functions

RULE = "resource-leak"


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """One acquire/release protocol.

    ``acquire`` patterns are regexes full-matched against the dotted
    callee (``self._pool.allocate``).  ``mode`` says where the resource
    lives: ``result`` (the call's return value) or ``arg0`` (the first
    argument becomes an *obligation*, e.g. a noted batch id that must be
    acked).  ``release_arg`` callees release any tracked name passed as
    an argument; ``release_self`` are method names ON the resource
    (``sock.close()``).  ``escapes=False`` disables transfer-by-use for
    obligation tokens — passing a batch id around does not discharge the
    credit it owes."""

    name: str
    acquire: tuple[str, ...]
    mode: str = "result"
    release_arg: tuple[str, ...] = ()
    release_self: tuple[str, ...] = ()
    escapes: bool = True
    flag_discard: bool = True


SPECS: tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="pool page(s)",
        acquire=(r"(?:.*\.)?_?pool\.allocate",),
        release_arg=(r"(?:.*\.)?_?pool\.release",
                     r"(?:.*\.)?_?pool\._release_ref"),
    ),
    ResourceSpec(
        name="ack credit",
        mode="arg0",
        acquire=(r"(?:.*\.)?router\.note_handle",),
        release_arg=(r"(?:.*\.)?_return_credit",
                     r"(?:.*\.)?router\.forward",
                     r"(?:.*\.)?router\.ack"),
        escapes=False,
        flag_discard=False,
    ),
    ResourceSpec(
        name="handoff handle",
        acquire=(r"(?:.*\.)?_?handoff(?:_queue)?\.get",),
        release_arg=(r"(?:.*\.)?_?handoff(?:_queue)?\.requeue",),
    ),
    ResourceSpec(
        name="file handle",
        acquire=(r"open", r"tempfile\.NamedTemporaryFile",
                 r"tempfile\.TemporaryDirectory"),
        release_self=("close", "cleanup"),
    ),
    ResourceSpec(
        name="socket",
        acquire=(r"socket\.socket", r"socket\.create_connection"),
        release_self=("close", "detach"),
    ),
    ResourceSpec(
        name="tracer span",
        acquire=(r"(?:.*\.)?_?tracer\.span",),
        release_self=("__exit__",),
    ),
)

_ACQ = [[re.compile(p) for p in s.acquire] for s in SPECS]
_REL_ARG = [[re.compile(p) for p in s.release_arg] for s in SPECS]


def _acquire_spec(call: ast.Call) -> int | None:
    callee = dotted(call.func)
    if callee is None:
        return None
    for i, pats in enumerate(_ACQ):
        if any(p.fullmatch(callee) for p in pats):
            return i
    return None


# Token: (var, spec_index, line, col, raised) — ``raised`` marks that the
# path crossed an explicit raise while holding the resource.


@dataclasses.dataclass
class _Effects:
    """Statement effects, computed once per CFG node."""

    released: frozenset  # (name, spec_i)
    acquired: tuple      # (target, spec_i, line, col)
    bound: frozenset     # names (re)bound by this statement
    escaped: frozenset   # names used in an ownership-transferring position
    assert_names: frozenset


def _receiver_base(call: ast.Call) -> ast.Name | None:
    f = call.func
    while isinstance(f, ast.Attribute):
        f = f.value
    return f if isinstance(f, ast.Name) else None


def _stmt_effects(stmt: ast.stmt) -> _Effects:
    released: set = set()
    acquired: list = []
    bound: set = set()
    skip_ids: set = set()  # Name nodes that are not escaping uses

    for sub in ast.walk(stmt):
        if not isinstance(sub, ast.Call):
            continue
        base = _receiver_base(sub)
        if base is not None:
            skip_ids.add(id(base))
        callee = dotted(sub.func)
        if callee is None:
            continue
        for i, spec in enumerate(SPECS):
            if any(p.fullmatch(callee) for p in _REL_ARG[i]):
                for arg in list(sub.args) + [k.value for k in sub.keywords]:
                    if isinstance(arg, ast.Name):
                        released.add((arg.id, i))
                        skip_ids.add(id(arg))
            if spec.release_self and isinstance(sub.func, ast.Attribute) \
                    and isinstance(sub.func.value, ast.Name) \
                    and sub.func.attr in spec.release_self:
                released.add((sub.func.value.id, i))
            if spec.mode == "arg0":
                if any(p.fullmatch(callee) for p in _ACQ[i]) and sub.args \
                        and isinstance(sub.args[0], ast.Name):
                    acquired.append((sub.args[0].id, i,
                                     sub.lineno, sub.col_offset))
                    skip_ids.add(id(sub.args[0]))

    if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
        targets = stmt.targets if isinstance(stmt, ast.Assign) \
            else [stmt.target]
        for t in targets:
            if isinstance(t, ast.Name):
                bound.add(t.id)
        value = stmt.value
        if isinstance(value, ast.Call) and len(bound) == 1 \
                and len(targets) == 1 and isinstance(targets[0], ast.Name):
            spec_i = _acquire_spec(value)
            if spec_i is not None and SPECS[spec_i].mode == "result":
                acquired.append((targets[0].id, spec_i,
                                 value.lineno, value.col_offset))

    if isinstance(stmt, ast.Assert):
        names = {n.id for n in ast.walk(stmt.test)
                 if isinstance(n, ast.Name)}
        return _Effects(frozenset(released), tuple(acquired),
                        frozenset(bound), frozenset(), frozenset(names))

    escaped = {
        n.id for n in ast.walk(stmt)
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
        and id(n) not in skip_ids
    }
    return _Effects(frozenset(released), tuple(acquired), frozenset(bound),
                    frozenset(escaped), frozenset())


def _narrow_killed(test: ast.expr, label: str) -> frozenset:
    """Names whose resource provably does not exist on this branch edge
    (``allocate`` returning None took the failure path)."""
    if isinstance(test, ast.Compare) and len(test.ops) == 1 \
            and isinstance(test.left, ast.Name) \
            and isinstance(test.comparators[0], ast.Constant) \
            and test.comparators[0].value is None:
        if isinstance(test.ops[0], ast.Is) and label == "true":
            return frozenset({test.left.id})
        if isinstance(test.ops[0], ast.IsNot) and label == "false":
            return frozenset({test.left.id})
    if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
            and isinstance(test.operand, ast.Name) and label == "true":
        return frozenset({test.operand.id})
    if isinstance(test, ast.Name) and label == "false":
        return frozenset({test.id})
    return frozenset()


def _for_element_release(stmt) -> set:
    """``for pid in pages: pool.release(pid)`` — releasing every element
    of a tracked collection releases the collection.  Returns the spec
    indices whose release the body performs on the loop variable."""
    if not isinstance(stmt.target, ast.Name):
        return set()
    loop_var = stmt.target.id
    out = set()
    for body_stmt in stmt.body:
        eff = _stmt_effects(body_stmt)
        out.update(i for (name, i) in eff.released if name == loop_var)
    return out


def _check_fn(fn, qual: str, path: str) -> list[Finding]:
    cfg = build_cfg(fn)
    found: dict = {}  # (line, col, message) -> Finding

    def emit(line, col, message):
        key = (line, col, message)
        if key not in found:
            found[key] = Finding(rule=RULE, path=path, line=line, col=col,
                                 message=message)

    effects_cache: dict = {}

    def effects(node):
        eff = effects_cache.get(node.idx)
        if eff is None:
            eff = _stmt_effects(node.stmt)
            effects_cache[node.idx] = eff
        return eff

    def transfer(node, state, label):
        if node.kind in ("entry", "exit", "raise_exit", "except", "finally"):
            return state
        if node.kind == "branch":
            killed = _narrow_killed(node.stmt.test, label) \
                if isinstance(node.stmt, (ast.If, ast.While)) else frozenset()
            if not killed:
                return state
            return frozenset(t for t in state if t[0] not in killed)
        if node.kind == "for":
            stmt = node.stmt
            out = set(state)
            rel_specs = _for_element_release(stmt)
            if isinstance(stmt.iter, ast.Name):
                it = stmt.iter.id
                # element-wise release, or iteration = use we can't
                # follow: either way the collection token goes away
                out = {t for t in out if t[0] != it}
            targets = {n.id for n in ast.walk(stmt.target)
                       if isinstance(n, ast.Name)}
            out = {t for t in out if t[0] not in targets}
            _ = rel_specs
            return frozenset(out)
        if node.kind == "with":
            out = set(state)
            for item in node.stmt.items:
                if isinstance(item.context_expr, ast.Name):
                    # ``with x:`` — the context manager owns shutdown
                    out = {t for t in out if t[0] != item.context_expr.id}
                if item.optional_vars is not None:
                    names = {n.id for n in ast.walk(item.optional_vars)
                             if isinstance(n, ast.Name)}
                    out = {t for t in out if t[0] not in names}
            return frozenset(out)

        # stmt / return / raise
        eff = effects(node)
        out = set(state)
        if eff.released:
            out = {t for t in out if (t[0], t[1]) not in eff.released}
        if eff.escaped:
            out = {t for t in out
                   if not (SPECS[t[1]].escapes and t[0] in eff.escaped)}
        if isinstance(node.stmt, ast.Assert) and label == "exc":
            # the assert names the resource: on the failure edge the
            # guarded value was falsy/None — nothing was held
            out = {t for t in out if t[0] not in eff.assert_names}
            return frozenset(out)
        if eff.bound:
            for t in list(out):
                if t[0] in eff.bound:
                    emit(t[2], t[3],
                         f"in {qual}(): '{t[0]}' is rebound while still "
                         f"holding {SPECS[t[1]].name} acquired here")
                    out.discard(t)
        if label != "exc":
            for (target, spec_i, line, col) in eff.acquired:
                out = {t for t in out if t[0] != target}
                out.add((target, spec_i, line, col, False))
        if node.kind == "raise" and label == "exc":
            out = {(v, s, ln, c, True) for (v, s, ln, c, _) in out}
        return frozenset(out)

    states = forward_dataflow(cfg, init=frozenset(), transfer=transfer,
                              join=lambda a, b: a | b)

    for var, spec_i, line, col, _raised in states.get(cfg.exit, frozenset()):
        emit(line, col,
             f"in {qual}(): {SPECS[spec_i].name} acquired into '{var}' can "
             "reach function exit without release or ownership transfer")
    for var, spec_i, line, col, raised in states.get(cfg.raise_exit,
                                                     frozenset()):
        if raised:
            emit(line, col,
                 f"in {qual}(): {SPECS[spec_i].name} acquired into '{var}' "
                 "leaks when a raise propagates out of the function")

    # acquire whose result is discarded: nothing can ever release it
    seen_discard: set = set()
    for node in cfg.nodes:
        if node.kind != "stmt" or not isinstance(node.stmt, ast.Expr):
            continue
        value = node.stmt.value
        if not isinstance(value, ast.Call):
            continue
        spec_i = _acquire_spec(value)
        if spec_i is None or SPECS[spec_i].mode != "result" \
                or not SPECS[spec_i].flag_discard:
            continue
        key = (value.lineno, value.col_offset)
        if key in seen_discard:
            continue  # finally-body copies share statements
        seen_discard.add(key)
        emit(value.lineno, value.col_offset,
             f"in {qual}(): result of {dotted(value.func)}() "
             f"({SPECS[spec_i].name}) is discarded — an unbound acquire "
             "can never be released")

    return list(found.values())


@rule(RULE)
def check_resource_leaks(module: ParsedModule, ctx: RepoContext):
    quals = qualnames(module.tree)
    out: list[Finding] = []
    for fn in walk_functions(module.tree):
        out.extend(_check_fn(fn, quals.get(fn, fn.name), module.path))
    return out
