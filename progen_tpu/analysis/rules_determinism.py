"""det-*: determinism lint for token-identity zones.

Replay, spec-decoding verification, preemption evict-replay, and the
QoS trace harness all depend on scheduling decisions being a pure
function of the request stream.  Three things quietly break that
contract: iterating an unordered ``set`` (or ``dict.values()``) to pick
winners, reading a wall clock where virtual/sanctioned time is the
rule, and ambient randomness (``random.*`` module state, ``hash()``
with ``PYTHONHASHSEED`` unset).

The zones — which files/functions must be deterministic and which
clocks they are allowed to touch — are declared in :data:`DET_ZONES`.
The engine's monotonic-clock usage is the design (virtual time is
derived from it at replay), so ``time.perf_counter`` is sanctioned in
the engine scheduling zone but not elsewhere.

Rules: ``det-set-iter``, ``det-wallclock``, ``det-ambient-rng``.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import dotted, qualnames


@dataclasses.dataclass(frozen=True)
class DetZone:
    path_re: str        # matched against the module's repo-relative path
    qual_re: str        # matched against the function qualname
    clocks: tuple = ()  # dotted call names sanctioned inside this zone
    why: str = ""


DET_ZONES: tuple[DetZone, ...] = (
    DetZone(r"progen_tpu/decode/qos\.py$", r".*",
            why="QoS ordering is replayed by the overload trace harness"),
    DetZone(r"progen_tpu/serve/router\.py$", r".*",
            why="placement must replay for exactly-once completion"),
    DetZone(r"progen_tpu/decode/spec\.py$", r".*",
            why="spec accept/reject is part of token identity"),
    DetZone(
        r"progen_tpu/decode/engine\.py$",
        r"(?:.*\.)?(submit_fork|_release_forks|_maybe_preempt|_preempt_slot"
        r"|_admit_pending\w*|_admit_from_handoff|_plan_slot_pages"
        r"|_ensure_chunk_pages|_free_slot_pages|_harvest_done)$",
        clocks=(r"time\.perf_counter(?:_ns)?",),
        why="engine scheduling; the monotonic clock is the sanctioned "
            "timebase that virtual time is derived from"),
)

_ZONES = tuple(
    (re.compile(z.path_re), re.compile(z.qual_re),
     tuple(re.compile(c) for c in z.clocks), z.why)
    for z in DET_ZONES
)


def _zone_for(path: str, qual: str):
    for path_re, qual_re, clocks, why in _ZONES:
        if path_re.search(path) and qual_re.fullmatch(qual):
            return clocks, why
    return None


def _zone_functions(module: ParsedModule):
    for fn, qual in qualnames(module.tree).items():
        zone = _zone_for(module.path, qual)
        if zone is not None:
            yield fn, qual, zone


# ---------------------------------------------------------------------------
# det-set-iter
# ---------------------------------------------------------------------------

_ORDER_SENSITIVE_BUILTINS = {"min", "max", "next", "list", "tuple",
                             "enumerate", "zip"}


def _set_names(fn) -> set:
    """Names bound (anywhere in the function) to a definitely-set value."""
    names: set = set()
    for node in ast.walk(fn):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Name):
            target = node.targets[0].id
            value = node.value
        elif isinstance(node, ast.AnnAssign) and node.value is not None \
                and isinstance(node.target, ast.Name):
            target = node.target.id
            value = node.value
        else:
            continue
        if _is_set_expr(value, names):
            names.add(target)
    return names


def _is_set_expr(node, set_names) -> bool:
    if isinstance(node, (ast.Set, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        callee = dotted(node.func)
        if callee in ("set", "frozenset"):
            return True
        # set-returning methods on a known set
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("union", "intersection", "difference",
                                       "symmetric_difference", "copy") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in set_names:
            return True
    if isinstance(node, ast.BinOp) \
            and isinstance(node.op, (ast.BitOr, ast.BitAnd, ast.Sub,
                                     ast.BitXor)):
        return _is_set_expr(node.left, set_names) \
            or _is_set_expr(node.right, set_names)
    if isinstance(node, ast.Name):
        return node.id in set_names
    return False


def _unordered_iter_desc(node, set_names) -> str | None:
    """If iterating ``node`` has nondeterministic order, describe why."""
    if _is_set_expr(node, set_names):
        return "a set"
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "values" and not node.args:
        # dict.values(): insertion-ordered per-process, but across
        # processes/restarts insertion order is load order — only flag
        # when the receiver is itself built from a set; plain
        # dict.values() iteration is deterministic under replay.
        if _is_set_expr(node.func.value, set_names):
            return "values() of a set-keyed mapping"
        return None
    if isinstance(node, ast.Call):
        callee = dotted(node.func)
        if callee == "sorted":
            return None
        if callee in ("list", "tuple", "reversed") and node.args:
            return _unordered_iter_desc(node.args[0], set_names)
    return None


@rule("det-set-iter")
def check_set_iteration(module: ParsedModule, ctx: RepoContext):
    for fn, qual, (clocks, why) in _zone_functions(module):
        set_names = _set_names(fn)
        for node in ast.walk(fn):
            iters = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters.append(node.iter)
            elif isinstance(node, (ast.ListComp, ast.DictComp,
                                   ast.GeneratorExp)):
                # SetComp is exempt: set -> set is order-insensitive
                iters.extend(gen.iter for gen in node.generators)
            elif isinstance(node, ast.Call):
                callee = dotted(node.func)
                if callee in _ORDER_SENSITIVE_BUILTINS and node.args:
                    iters.append(node.args[0])
            for it in iters:
                desc = _unordered_iter_desc(it, set_names)
                if desc is not None:
                    yield Finding(
                        rule="det-set-iter", path=module.path,
                        line=it.lineno, col=it.col_offset,
                        message=f"iteration over {desc} feeds a decision in "
                                f"determinism zone '{qual}' ({why}) — sort "
                                "on a stable key first")


# ---------------------------------------------------------------------------
# det-wallclock
# ---------------------------------------------------------------------------

_WALLCLOCKS = (
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.datetime.now", "datetime.datetime.utcnow", "datetime.now",
    "datetime.utcnow",
)


@rule("det-wallclock")
def check_wallclock(module: ParsedModule, ctx: RepoContext):
    for fn, qual, (clocks, why) in _zone_functions(module):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee not in _WALLCLOCKS:
                continue
            if any(c.fullmatch(callee) for c in clocks):
                continue
            yield Finding(
                rule="det-wallclock", path=module.path,
                line=node.lineno, col=node.col_offset,
                message=f"wall-clock read {callee}() inside determinism "
                        f"zone '{qual}' ({why}) — thread a sanctioned clock "
                        "in instead")


# ---------------------------------------------------------------------------
# det-ambient-rng
# ---------------------------------------------------------------------------

_RNG_OK = re.compile(r"random\.(Random|SystemRandom)$")
_RNG_MODULES = ("random.", "numpy.random.", "np.random.")


@rule("det-ambient-rng")
def check_ambient_rng(module: ParsedModule, ctx: RepoContext):
    for fn, qual, (clocks, why) in _zone_functions(module):
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted(node.func)
            if callee is None:
                continue
            if callee == "hash":
                yield Finding(
                    rule="det-ambient-rng", path=module.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"builtin hash() inside determinism zone "
                            f"'{qual}' ({why}) depends on PYTHONHASHSEED — "
                            "use a content digest (zlib.crc32/hashlib)")
                continue
            if any(callee.startswith(m) for m in _RNG_MODULES) \
                    and not _RNG_OK.search(callee):
                yield Finding(
                    rule="det-ambient-rng", path=module.path,
                    line=node.lineno, col=node.col_offset,
                    message=f"ambient RNG call {callee}() inside determinism "
                            f"zone '{qual}' ({why}) — use an explicitly "
                            "seeded generator threaded from the request")
