"""graftcheck: JAX/TPU-aware static analysis for this codebase.

Pure-stdlib (never imports jax); entry points:

* ``tools/graftcheck.py`` — CLI with human/JSON output and CI exit codes
* :func:`progen_tpu.analysis.engine.run` — programmatic, used by the tier-1
  gate test
* :func:`progen_tpu.analysis.engine.check_source` — single-snippet checks,
  used by the per-rule unit tests

Rules register themselves into ``engine.RULES`` when their module is
imported; :func:`load_rules` imports them all.
"""

from __future__ import annotations

import importlib

from progen_tpu.analysis.engine import (  # noqa: F401
    Finding,
    RULES,
    apply_baseline,
    build_context,
    check_source,
    format_human,
    format_json,
    format_sarif,
    load_baseline,
    run,
    save_baseline,
)

_RULE_MODULES = (
    "rules_trace",
    "rules_rng",
    "rules_dtype",
    "rules_sharding",
    "rules_hostsync",
    "rules_jit",
    "rules_pallas",
    "rules_lifecycle",
    "rules_wire",
    "rules_determinism",
)


def load_rules() -> dict:
    for mod in _RULE_MODULES:
        importlib.import_module(f"progen_tpu.analysis.{mod}")
    return RULES
