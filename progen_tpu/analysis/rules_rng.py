"""Rules ``rng-reuse`` and ``rng-split-dropped``: PRNG key discipline.

JAX keys are splittable counters, not stateful generators: feeding the same
key to two samplers yields correlated (often identical) draws, and calling
``jax.random.split`` without using the result is always a bug.

``rng-reuse`` does a linear abstract walk of each function body, counting
consumptions per key variable; ``if``/``else`` branches merge by max (either
branch may run), loop bodies are walked twice (a consumption that survives
one iteration without a re-split fires on the second pass).
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import call_name, walk_functions

_KEY_PARAM_NAMES = frozenset({"key", "rng", "rng_key", "prng_key", "keys"})

# producers: assigning their result (re)binds a fresh key
_KEY_PRODUCERS = frozenset(
    {
        "jax.random.key",
        "jax.random.PRNGKey",
        "jax.random.split",
        "jax.random.fold_in",
        "jax.random.wrap_key_data",
        "jax.random.clone",
    }
)

# consumers: passing a key here uses up its entropy
_RNG_PREFIX = "jax.random."


def _is_underscore(name: str) -> bool:
    return name == "_" or name.startswith("_unused")


def _key_args(node: ast.Call) -> list[str]:
    """Names passed to a jax.random.* call (positionally or as key=)."""
    out = []
    for a in node.args:
        if isinstance(a, ast.Name):
            out.append(a.id)
    for kw in node.keywords:
        if kw.arg == "key" and isinstance(kw.value, ast.Name):
            out.append(kw.value.id)
    return out


class _FnScan:
    def __init__(self, fn, module_path: str):
        self.fn = fn
        self.path = module_path
        self.findings: list[Finding] = []
        self._reported: set[tuple[int, str]] = set()
        # var -> consumption count; presence marks "known key variable"
        counts: dict[str, int] = {}
        for a in fn.args.args + fn.args.kwonlyargs + fn.args.posonlyargs:
            if a.arg in _KEY_PARAM_NAMES:
                counts[a.arg] = 0
        self.final = self._walk_body(fn.body, counts)

    # -- state ops ---------------------------------------------------------

    def _consume(self, counts, name: str, node: ast.AST) -> None:
        if name not in counts:
            return
        counts[name] += 1
        if counts[name] >= 2:
            key = (node.lineno, name)
            if key not in self._reported:
                self._reported.add(key)
                self.findings.append(
                    Finding(
                        rule="rng-reuse",
                        path=self.path,
                        line=node.lineno,
                        col=node.col_offset,
                        message=(
                            f"key '{name}' consumed again without an "
                            "intervening jax.random.split"
                        ),
                    )
                )

    def _rebind(self, counts, name: str) -> None:
        counts[name] = 0

    # -- walkers -----------------------------------------------------------

    def _walk_body(self, body, counts) -> dict[str, int]:
        for stmt in body:
            counts = self._walk_stmt(stmt, counts)
        return counts

    def _walk_stmt(self, stmt, counts) -> dict[str, int]:
        if isinstance(stmt, ast.Assign):
            self._scan_expr(stmt.value, counts)
            self._bind_targets(stmt.targets, stmt.value, counts)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._scan_expr(stmt.value, counts)
                self._bind_targets([stmt.target], stmt.value, counts)
        elif isinstance(stmt, ast.AugAssign):
            self._scan_expr(stmt.value, counts)
        elif isinstance(stmt, ast.Expr):
            self._check_dropped_split(stmt)
            self._scan_expr(stmt.value, counts)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._scan_expr(stmt.value, counts)
        elif isinstance(stmt, ast.If):
            self._scan_expr(stmt.test, counts)
            a = self._walk_body(stmt.body, dict(counts))
            b = self._walk_body(stmt.orelse, dict(counts))
            counts = {
                k: max(a.get(k, 0), b.get(k, 0))
                for k in set(a) | set(b)
            }
        elif isinstance(stmt, (ast.For, ast.While)):
            if isinstance(stmt, ast.For):
                self._scan_expr(stmt.iter, counts)
            else:
                self._scan_expr(stmt.test, counts)
            # simulate two iterations: reuse across iterations surfaces on
            # the second pass unless the loop re-splits
            counts = self._walk_body(stmt.body, counts)
            counts = self._walk_body(stmt.body, counts)
            counts = self._walk_body(stmt.orelse, counts)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            counts = self._walk_body(stmt.body, counts)
        elif isinstance(stmt, ast.Try):
            counts = self._walk_body(stmt.body, counts)
            for handler in stmt.handlers:
                counts = self._walk_body(handler.body, dict(counts))
            counts = self._walk_body(stmt.orelse, counts)
            counts = self._walk_body(stmt.finalbody, counts)
        # nested defs get their own _FnScan via walk_functions; skip here
        return counts

    def _bind_targets(self, targets, value, counts) -> None:
        producer = (
            isinstance(value, ast.Call)
            and call_name(value) in _KEY_PRODUCERS
        )
        for t in targets:
            if isinstance(t, ast.Name):
                if producer or t.id in counts:
                    self._rebind(counts, t.id)
                if not producer and t.id in counts and not isinstance(
                    value, ast.Call
                ):
                    # aliasing an unknown value over a key var: stop tracking
                    counts.pop(t.id, None)
                    counts[t.id] = 0
            elif isinstance(t, (ast.Tuple, ast.List)) and producer:
                for elt in t.elts:
                    if isinstance(elt, ast.Name):
                        self._rebind(counts, elt.id)

    def _scan_expr(self, expr, counts) -> None:
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name is None or not name.startswith(_RNG_PREFIX):
                continue
            for var in _key_args(node):
                self._consume(counts, var, node)

    def _check_dropped_split(self, stmt: ast.Expr) -> None:
        value = stmt.value
        if (
            isinstance(value, ast.Call)
            and call_name(value) == "jax.random.split"
        ):
            self.findings.append(
                Finding(
                    rule="rng-split-dropped",
                    path=self.path,
                    line=stmt.lineno,
                    col=stmt.col_offset,
                    message="result of jax.random.split is discarded",
                )
            )


@rule("rng-reuse")
def check_reuse(module: ParsedModule, ctx: RepoContext):
    for fn in walk_functions(module.tree):
        scan = _FnScan(fn, module.path)
        for f in scan.findings:
            if f.rule == "rng-reuse":
                yield f


@rule("rng-split-dropped")
def check_dropped(module: ParsedModule, ctx: RepoContext):
    # dropped splits are also flagged when assigned entirely to underscores
    for fn in walk_functions(module.tree):
        scan = _FnScan(fn, module.path)
        for f in scan.findings:
            if f.rule == "rng-split-dropped":
                yield f
    for node in ast.walk(module.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if call_name(node.value) != "jax.random.split":
                continue
            names: list[str] = []
            for t in node.targets:
                if isinstance(t, ast.Name):
                    names.append(t.id)
                elif isinstance(t, (ast.Tuple, ast.List)):
                    names.extend(
                        e.id for e in t.elts if isinstance(e, ast.Name)
                    )
            if names and all(_is_underscore(n) for n in names):
                yield Finding(
                    rule="rng-split-dropped",
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message="result of jax.random.split is discarded",
                )
