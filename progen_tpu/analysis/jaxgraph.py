"""Shared AST machinery for the graftcheck rules.

Three facilities:

* :func:`dotted` — render ``Name``/``Attribute`` chains as ``"jax.random.split"``.
* :class:`TraceGraph` — which function defs in a module are reachable from a
  jit/scan/shard_map/pallas_call trace root (per-module, name-resolution by
  simple name: precise enough for this codebase, cheap enough for tier-1).
* :class:`StaticEnv` — per-function classification of local names into
  host-static (shapes, ints, config) vs possibly-traced values, used by the
  Pallas index-map rule.

Everything here is best-effort and intentionally conservative in opposite
directions per consumer: TraceGraph under-approximates reachability (only
flags what it is sure is traced), StaticEnv under-approximates staticness
(flags closures it cannot prove static).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Iterable

FuncDef = ast.FunctionDef | ast.AsyncFunctionDef


def dotted(node: ast.AST) -> str | None:
    """``Name``/``Attribute`` chain -> dotted string, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> str | None:
    return dotted(node.func)


def unwrap_partial(node: ast.AST) -> ast.AST:
    """``functools.partial(f, ...)`` / ``partial(f, ...)`` -> ``f``."""
    if isinstance(node, ast.Call):
        name = call_name(node)
        if name in ("partial", "functools.partial") and node.args:
            return unwrap_partial(node.args[0])
    return node


def target_simple_name(node: ast.AST) -> str | None:
    """Simple name a callable expression refers to within this module.

    ``f`` -> ``f``; ``self._step`` / ``cls._step`` -> ``_step`` (methods are
    resolved by simple name); dotted module refs (``jax.random.split``) -> None.
    """
    node = unwrap_partial(node)
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name):
        if node.value.id in ("self", "cls"):
            return node.attr
    return None


_JIT_NAMES = frozenset({"jit", "jax.jit", "pjit", "jax.pjit", "nn.jit"})

# higher-order jax entry points whose callable argument is traced
_TRACING_HOFS = frozenset(
    {
        "lax.scan",
        "jax.lax.scan",
        "lax.while_loop",
        "jax.lax.while_loop",
        "lax.fori_loop",
        "jax.lax.fori_loop",
        "lax.cond",
        "jax.lax.cond",
        "lax.switch",
        "jax.lax.switch",
        "lax.map",
        "jax.lax.map",
        "lax.associative_scan",
        "jax.lax.associative_scan",
        "shard_map",
        "jax.experimental.shard_map.shard_map",
        "_shard_map",
        "pl.pallas_call",
        "pallas_call",
        "pltpu.pallas_call",
        "jax.vmap",
        "vmap",
        "jax.pmap",
        "pmap",
        "jax.grad",
        "jax.value_and_grad",
        "jax.custom_vjp",
        "jax.custom_jvp",
        "jax.checkpoint",
        "jax.remat",
        "jax.linearize",
        "jax.vjp",
        "jax.jvp",
    }
)


def _is_jit_call(node: ast.Call) -> bool:
    return call_name(node) in _JIT_NAMES


def _jit_wrapped_names(node: ast.Call) -> Iterable[str]:
    if node.args:
        name = target_simple_name(node.args[0])
        if name:
            yield name


@dataclasses.dataclass
class JittedCallable:
    """A name bound to a jitted callable: ``step = jax.jit(step_impl, ...)``."""

    bound_name: str
    wrapped_name: str | None
    call: ast.Call  # the jax.jit(...) call (for static/donate kwargs)

    def keyword(self, key: str) -> ast.expr | None:
        for kw in self.call.keywords:
            if kw.arg == key:
                return kw.value
        return None


class TraceGraph:
    """Per-module set of function defs reachable from a trace root."""

    def __init__(self, tree: ast.Module):
        self.defs: dict[str, list[FuncDef]] = {}
        self.parent_def: dict[FuncDef, FuncDef | None] = {}
        self.jitted: list[JittedCallable] = []
        self._roots: set[str] = set()
        self._collect(tree)
        self.traced: set[FuncDef] = self._propagate()

    # -- collection --------------------------------------------------------

    def _collect(self, tree: ast.Module) -> None:
        stack: list[FuncDef] = []

        class V(ast.NodeVisitor):
            def visit_FunctionDef(inner, node: FuncDef):  # noqa: N805
                self.defs.setdefault(node.name, []).append(node)
                self.parent_def[node] = stack[-1] if stack else None
                for dec in node.decorator_list:
                    target = unwrap_partial(dec)
                    if isinstance(target, ast.Call):
                        target = target.func
                    if dotted(target) in _JIT_NAMES:
                        self._roots.add(node.name)
                stack.append(node)
                inner.generic_visit(node)
                stack.pop()

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Call(inner, node: ast.Call):  # noqa: N805
                name = call_name(node)
                if _is_jit_call(node):
                    self._roots.update(_jit_wrapped_names(node))
                elif name in _TRACING_HOFS:
                    for arg in node.args:
                        t = target_simple_name(arg)
                        if t:
                            self._roots.add(t)
                inner.generic_visit(node)

            def visit_Assign(inner, node: ast.Assign):  # noqa: N805
                if isinstance(node.value, ast.Call) and _is_jit_call(node.value):
                    wrapped = (
                        target_simple_name(node.value.args[0])
                        if node.value.args
                        else None
                    )
                    for t in node.targets:
                        bound = target_simple_name(t)
                        if bound:
                            self.jitted.append(
                                JittedCallable(bound, wrapped, node.value)
                            )
                inner.generic_visit(node)

        V().visit(tree)

    # -- propagation -------------------------------------------------------

    def _callees(self, fn: FuncDef) -> set[str]:
        out: set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                t = target_simple_name(node.func)
                if t and t in self.defs:
                    out.add(t)
        return out

    def _propagate(self) -> set[FuncDef]:
        traced: set[FuncDef] = set()
        work: list[FuncDef] = []
        for name in self._roots:
            work.extend(self.defs.get(name, []))
        while work:
            fn = work.pop()
            if fn in traced:
                continue
            traced.add(fn)
            for callee in self._callees(fn):
                work.extend(self.defs.get(callee, []))
        return traced

    def is_traced(self, fn: FuncDef) -> bool:
        return fn in self.traced

    def jitted_by_bound_name(self) -> dict[str, JittedCallable]:
        return {j.bound_name: j for j in self.jitted}


# ---------------------------------------------------------------------------
# staticness
# ---------------------------------------------------------------------------

_STATIC_ANNOTATIONS = frozenset({"int", "float", "bool", "str", "tuple"})


class StaticEnv:
    """Classify an enclosing function's local names as host-static or not.

    Static: int/float/bool/str-annotated params, constants, ``x.shape``
    reads and their unpackings, ``len()``, and arithmetic over static names.
    Everything else assigned locally (in particular unannotated params —
    they are usually arrays) is treated as possibly-traced.
    """

    def __init__(
        self,
        fn: FuncDef,
        returns: dict[str, "list[bool] | bool"] | None = None,
    ):
        self.local: set[str] = set()  # all locally-bound names
        self.static: set[str] = set()
        # one level of interprocedural knowledge: per-element staticness of
        # module-local helpers' return tuples (see module_return_staticness)
        self.returns = returns or {}
        self._classify(fn)

    def _classify(self, fn: FuncDef) -> None:
        args = fn.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            self.local.add(a.arg)
            ann = a.annotation
            if ann is not None and (
                (isinstance(ann, ast.Name) and ann.id in _STATIC_ANNOTATIONS)
                or (
                    isinstance(ann, ast.Constant)
                    and ann.value in _STATIC_ANNOTATIONS
                )
            ):
                self.static.add(a.arg)
        if fn.args.args and fn.args.args[0].arg in ("self", "cls"):
            # self/cls are containers, not traced arrays; attribute reads on
            # them are handled expression-side
            self.static.discard(fn.args.args[0].arg)

        # two passes so forward references between simple assignments settle
        for _ in range(2):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    self._bind(node.targets, node.value)
                elif isinstance(node, ast.AugAssign):
                    self._bind([node.target], node.value, aug=True)
                elif isinstance(node, (ast.For, ast.comprehension)):
                    tgt = node.target
                    it = node.iter
                    names = [
                        n.id
                        for n in ast.walk(tgt)
                        if isinstance(n, ast.Name)
                    ]
                    self.local.update(names)
                    if self.is_static_expr(it):
                        self.static.update(names)

    def _bind(self, targets, value, aug: bool = False) -> None:
        if not aug and isinstance(value, ast.Call):
            info = self.returns.get(target_simple_name(value.func) or "")
            if isinstance(info, list):
                for t in targets:
                    if isinstance(t, (ast.Tuple, ast.List)) and len(
                        t.elts
                    ) == len(info):
                        for elt, elt_static in zip(t.elts, info):
                            if isinstance(elt, ast.Name):
                                self.local.add(elt.id)
                                (
                                    self.static.add
                                    if elt_static
                                    else self.static.discard
                                )(elt.id)
                        return
            elif info is True:
                for t in targets:
                    if isinstance(t, ast.Name):
                        self.local.add(t.id)
                        self.static.add(t.id)
                return
        static_value = self.is_static_expr(value)
        for t in targets:
            if isinstance(t, ast.Name):
                self.local.add(t.id)
                if aug:
                    if not static_value:
                        self.static.discard(t.id)
                elif static_value:
                    self.static.add(t.id)
                else:
                    self.static.discard(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for elt in t.elts:
                    if isinstance(elt, ast.Name):
                        self.local.add(elt.id)
                        if static_value:
                            self.static.add(elt.id)
                        else:
                            self.static.discard(elt.id)

    def is_static_expr(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Name):
            # names never bound locally resolve to module scope (imports,
            # module constants, helper functions): host-static by definition
            return node.id not in self.local or node.id in self.static
        if isinstance(node, ast.Attribute):
            if node.attr in ("shape", "ndim", "dtype", "size"):
                return True
            return self.is_static_expr(node.value)
        if isinstance(node, ast.Subscript):
            return self.is_static_expr(node.value)
        if isinstance(node, ast.BinOp):
            return self.is_static_expr(node.left) and self.is_static_expr(
                node.right
            )
        if isinstance(node, ast.UnaryOp):
            return self.is_static_expr(node.operand)
        if isinstance(node, (ast.Tuple, ast.List)):
            return all(self.is_static_expr(e) for e in node.elts)
        if isinstance(node, ast.Compare):
            return self.is_static_expr(node.left) and all(
                self.is_static_expr(c) for c in node.comparators
            )
        if isinstance(node, ast.IfExp):
            return all(
                self.is_static_expr(n) for n in (node.test, node.body, node.orelse)
            )
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in ("len", "int", "min", "max", "abs", "round", "sum"):
                return all(self.is_static_expr(a) for a in node.args)
            return False
        return False


def module_return_staticness(
    tree: ast.Module,
) -> dict[str, "list[bool] | bool"]:
    """Per-element staticness of single-return module-level helpers.

    ``_prep`` returning ``(g, r, w, b, bsz, nbr, lead)`` yields
    ``[False, False, False, False, True, True, True]`` — enough for a
    caller's tuple-unpack to know that ``nbr`` is a host int.
    """
    out: dict[str, list[bool] | bool] = {}
    for node in tree.body:
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        rets = [
            n
            for n in ast.walk(node)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        if len(rets) != 1:
            continue
        env = StaticEnv(node)
        value = rets[0].value
        if isinstance(value, ast.Tuple):
            out[node.name] = [env.is_static_expr(e) for e in value.elts]
        else:
            out[node.name] = env.is_static_expr(value)
    return out


def walk_functions(tree: ast.Module) -> Iterable[FuncDef]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def qualnames(tree: ast.Module) -> dict[FuncDef, str]:
    """Map every function def to its dotted qualname (``Class.method``)."""
    out: dict[FuncDef, str] = {}

    def visit(node: ast.AST, prefix: str) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{prefix}{child.name}"
                out[child] = q
                visit(child, q + ".")
            elif isinstance(child, ast.ClassDef):
                visit(child, f"{prefix}{child.name}.")
            else:
                visit(child, prefix)

    visit(tree, "")
    return out
