"""Rule ``host-sync``: device→host transfers inside hot loops.

Each ``float(x)`` / ``.item()`` / ``np.asarray(x)`` on a device array
blocks the host until the dispatch queue drains — in the training loop or
the serving engine's step path that serializes the accelerator against
Python.  The rule watches a small set of *hot zones* (qualname patterns in
specific files) and flags any sync primitive applied to a value it cannot
prove is already host-side.

The sanctioned idiom is one explicit, batched ``jax.device_get`` per
decision point, annotated with a suppression so every intentional sync is
grep-able:

    host = jax.device_get(metrics)  # graftcheck: disable=host-sync

Names assigned from that call (and pure-numpy derivations of them) are
treated as host-safe, so downstream ``float(host["loss"])`` does not flag.
"""

from __future__ import annotations

import ast
import dataclasses
import re

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import call_name, qualnames

@dataclasses.dataclass(frozen=True)
class Zone:
    path_re: str
    qual_re: str
    # self attributes known to hold host-side containers (queues, configs,
    # request bookkeeping) — reads/method calls on them are not syncs
    host_attrs: frozenset = frozenset()
    # parameter names that carry host-side payloads by contract (client
    # Request objects, JSON-safe snapshots, numpy masks) — casts and
    # asarray over them validate host data, they never drain the queue
    host_params: frozenset = frozenset()


# the hot zones for this codebase
HOT_ZONES: tuple[Zone, ...] = (
    Zone(
        r"train/trainer\.py$",
        r"Trainer\.(_run_loop|_run_loop_superstep|evaluate|_note_phase"
        r"|_publish_train_health|_statusz_health|_statusz_status)$",
        frozenset({"meter", "tracker", "config", "model_config", "store",
                   "_recorder", "_tracer", "lr_schedule", "cfg",
                   "_watchdog", "_preempt_requested"}),
        # the log dict holds host floats from the loop's one batched
        # jax.device_get — publishing them is not a new sync
        frozenset({"log"}),
    ),
    Zone(
        r"decode/engine\.py$",
        r"ServingEngine\.(step|submit|run_until_idle|_admit_pending"
        r"|_admit_pending_dense|_admit_pending_paged|_plan_slot_pages"
        r"|_free_slot_pages|_evict_slot|_ensure_chunk_pages|_harvest_done"
        r"|drain|snapshot|restore|has_work|_shed_expired|_shed|_guard"
        r"|_dispatch_chunk|_fail_inflight|_activate_xla_fallback"
        r"|_drain_pending|robustness_counters|_prefill_round"
        r"|_admit_from_handoff|_prefill_worker_call|_merge_call"
        r"|admit_handle|run_prefill_round|drain_sheds|_note_stage"
        r"|submit_embed|_embed_round|run_embed_round|embed_pending"
        r"|_build_lmask|status|_maybe_preempt|_preempt_slot|qos_status"
        r"|_publish_qos_gauges|submit_fork|_release_forks|forget_ttft"
        r"|prefix_digest|cache_status|_publish_cache_gauges)$",
        frozenset({"_inflight", "_queue", "completions", "config",
                   "num_slots", "max_len", "chunks_run", "_pool",
                   "_slot_pages", "_page_table", "_paused", "_host_stop",
                   "_admit_order", "_admit_seq", "page_size",
                   "pages_per_row", "paged", "chunk_size", "evictions",
                   "pause_events", "prefix_hits", "robust", "_pending",
                   "_draining", "_aot", "_compiled_keys", "_defer_streak",
                   "fault_retries", "max_queue", "shed_policy",
                   "paged_impl", "_watchdog", "_handoff", "disagg",
                   "spec", "spec_k", "prefill_batch", "_max_advance",
                   "_spec_rounds", "remote_prefill", "stage_seconds",
                   "_tracer", "_stage_hist", "_embed_queue", "lora",
                   "qos_weights", "_qos_gauge_keys", "prefix_lookups",
                   "fork_groups", "_fork_wait", "_ttft"}),
        # requests, admission rows and snapshots are host payloads by API
        # contract: numpy masks, python ints, JSON-safe dicts — never
        # device arrays
        frozenset({"request", "rows", "snap"}),
    ),
    # the page pool is pure host bookkeeping between dispatches: nothing
    # in it may touch a device value, so every sync call is a finding
    Zone(r"decode/paging\.py$", r"PagePool\..*$"),
    # the QoS scheduler runs between every admission decision: pure host
    # bookkeeping over Request metadata (priority/tenant/deadline are
    # python scalars by API contract), a sync here stalls every step.
    # __init__ is deliberately unzoned — weight validation is one-time
    Zone(r"decode/qos\.py$",
         r"(QoSQueue\.(append|appendleft|popleft|_peek|_select"
         r"|_note_served|shed_victim|remove|stats|__len__|__bool__"
         r"|__iter__|__getitem__)|_deadline_key)$",
         frozenset({"_weights", "_front", "_classes", "_deficit",
                    "_rr_at", "_rr_charged", "_seq", "_len",
                    "served_by_class", "served_by_tenant"}),
         frozenset({"r"})),
    # the handoff queue carries device arrays inside handles but is pure
    # host bookkeeping itself — any sync in it would sit on the step path
    # (module-level serialize_handle/deserialize_handle are TRANSPORT and
    # deliberately unzoned: they run on worker/transport threads where the
    # one batched device_get/device_put per frame is the whole point)
    Zone(r"decode/handoff\.py$", r"HandoffQueue\..*$",
         frozenset({"_q", "depth", "puts", "gets", "rejects"})),
    # the serving router is placement policy on the admission path: pure
    # host bookkeeping, any sync would serialize the whole cluster
    Zone(r"serve/router\.py$", r"Router\..*$",
         frozenset({"prefill_alive", "replica_alive", "prefill_load",
                    "prefill_class_load", "outstanding", "requests",
                    "stage", "batches",
                    "_uid_batch", "completed", "submit_times",
                    "max_prefill_queue", "max_outstanding",
                    "prefill_fenced", "replica_fenced",
                    "prefill_gen", "replica_gen", "uid_gen",
                    "replica_digest", "_optimistic", "_page_size_hint",
                    "route_by_cache", "digest_ttl",
                    "cache_imbalance_tokens", "cache_routed",
                    "cache_fallback", "cache_overridden"}),
         # advertised digests are parsed-JSON wire payloads and the
         # routing knobs are host scalars by constructor contract
         frozenset({"digest", "route_by_cache", "digest_ttl",
                    "cache_imbalance_tokens", "now"})),
    # the cluster's ADMISSION/event side must not sync (wire headers are
    # parsed JSON; numpy-building lives in module helpers outside the
    # zone); spawn/accept/log plumbing is transport-side and unzoned
    Zone(r"serve/cluster\.py$",
         r"ServeCluster\.(submit|_dispatch|_shed|poll|pending|drain"
         r"|_pump|_handle_event|_on_hello|_on_handle|_on_peer_dead"
         r"|_on_group_member_dead|_reap_member|_group_members"
         r"|_is_group_role"
         r"|_return_credit|_check_stale|_note_clock|fleet_metrics"
         r"|_note_cache_frame|cache_stats"
         r"|_statusz_health|_statusz_status)$",
         frozenset({"router", "completions", "supervisor", "counters",
                    "tp_group",
                    "_new", "_events", "_peers", "_procs",
                    "_handled_dead", "_respawning", "_parked_uids",
                    "_worker_stats", "_hb", "_shutting_down",
                    "stale_after", "prefill_procs", "replicas",
                    "spec", "_tracer", "_lat", "_clock_offsets",
                    "_stats_age", "_statusz", "_statusz_ports",
                    "_slo", "_slo_last", "_ok_ctr", "_shed_ctr",
                    "generation", "_worker_gen", "_worker_spec",
                    "_retiring", "_pending_routable", "_next_idx",
                    "_spec_paths", "_statusz_providers",
                    "_ttft", "_cache_counts"})),
    # the control plane's tick sits between poll rounds on the drive
    # loop: pure host policy over router/heartbeat bookkeeping, any
    # sync here would stall every request in flight
    Zone(r"serve/control\.py$",
         r"(ControlPlane\.(gather|tick|_pick_victim|_journal|controlz)"
         r"|_worst_burns)$",
         frozenset({"cluster", "policy", "journal", "ticks", "swaps",
                    "_last_inputs", "_tracer", "_slo", "_up_ctr",
                    "_down_ctr", "_swap_ctr", "_g_prefill",
                    "_g_replicas", "_g_gen"}),
         # SLO evaluate results and heartbeat stage_seconds are
         # JSON-safe host floats by contract
         frozenset({"slo_results"})),
    Zone(r"serve/policy\.py$",
         r"(BurnRatePolicy\.(decide|note_action|_cooling|config)"
         r"|_worst_burn|PolicyInputs\..*|ScaleDecision\..*)$",
         frozenset({"min_prefill", "max_prefill", "min_replicas",
                    "max_replicas", "up_burn", "down_burn",
                    "up_queue_per_worker", "down_queue_per_worker",
                    "cooldown_s", "_last_action"}),
         # PolicyInputs fields are host floats/dicts by contract
         frozenset({"inputs", "burn_rates"})),
    # span recording sits on every hot path above: it must never sync
    # (spans carry pre-computed floats, never device values)
    Zone(r"observe/trace\.py$", r"Tracer\.(span|add|event)$"),
    # the introspection plane reads host snapshots only: any sync in a
    # handler would break the zero-perturbation invariant (an enabled
    # run must be token-identical to a disabled one)
    Zone(r"observe/statusz\.py$",
         r"(StatuszServer\.(_render|_call|_json)|render_prometheus"
         r"|_fmt|_sample|_prom_name)$",
         frozenset({"role", "index", "providers", "port"}),
         # exposition inputs are JSON-safe host values by API contract
         frozenset({"v", "value", "snapshot", "base", "labels", "extra"})),
    Zone(r"observe/slo\.py$",
         r"(BurnRateTracker\.(sample|evaluate)|SLOSpec\..*|evaluate"
         r"|frac_within|frac_within_values|burn_rate|_diff_metric"
         r"|_full_counts)$",
         frozenset({"specs", "windows", "registry", "_samples"}),
         # registry snapshots and their diffs are host floats by contract
         frozenset({"snap", "snapshot", "new", "old", "values",
                    "frac_good", "target", "threshold_s", "now", "p"})),
    Zone(r"observe/metrics\.py$",
         r"(Counter\.inc|Gauge\.set|Histogram\.observe)$"),
    Zone(r"train/step\.py$",
         r".*\.(train_step|_train_step_body|train_multi_step|eval_step)$"),
)

_SYNC_CALLS = frozenset(
    {
        "np.asarray",
        "numpy.asarray",
        "np.array",
        "numpy.array",
        "jax.device_get",
        "jax.block_until_ready",
    }
)
_CAST_CALLS = frozenset({"float", "int", "bool"})


def _zone_for(path: str, qualname: str) -> Zone | None:
    for zone in HOT_ZONES:
        if re.search(zone.path_re, path) and re.fullmatch(
            zone.qual_re, qualname
        ):
            return zone
    return None


def _root_name(node: ast.AST) -> str | None:
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call)):
        if isinstance(node, ast.Call):
            node = node.func
        else:
            node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _HostSafe:
    """Names provably host-side within one function (flow-insensitive)."""

    def __init__(
        self,
        fn: ast.FunctionDef | ast.AsyncFunctionDef,
        host_attrs: frozenset = frozenset(),
        host_params: frozenset = frozenset(),
    ):
        self.names: set[str] = set()
        self.host_attrs = host_attrs
        # zone-declared host payload parameters seed the fixpoint
        for arg in (*fn.args.args, *fn.args.posonlyargs,
                    *fn.args.kwonlyargs):
            if arg.arg in host_params:
                self.names.add(arg.arg)
        # fixpoint over simple assignments: device_get results and pure
        # arithmetic/numpy over host-safe names stay host-safe
        for _ in range(3):
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    if self._host_value(node.value):
                        for t in node.targets:
                            self._mark(t)
                elif isinstance(node, ast.AnnAssign):
                    if node.value is not None and self._host_value(node.value):
                        self._mark(node.target)
                elif isinstance(node, (ast.For, ast.comprehension)):
                    if self._host_value(node.iter):
                        self._mark(node.target)

    def _mark(self, target: ast.AST) -> None:
        if isinstance(target, ast.Name):
            self.names.add(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._mark(e)

    def _host_value(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Constant):
            return True
        if isinstance(node, ast.Call):
            name = call_name(node)
            # _host_fetch is the engine's group-aware device_get wrapper
            # (decode/engine.py): same one-batched-fetch contract, plus
            # replicated-shard handling for process-spanning arrays
            if name in ("jax.device_get", "_host_fetch"):
                return True
            if name and (name.startswith("np.") or name.startswith("numpy.")
                         or name.startswith("math.")):
                return all(self._host_value(a) for a in node.args)
            if name in ("len", "range", "enumerate", "zip", "min", "max",
                        "sum", "sorted", "getattr"):
                return all(self._host_value(a) for a in node.args)
            if name in _CAST_CALLS:
                return all(self._host_value(a) for a in node.args)
            # a method call on a host-side object yields a host-side value
            # (queue.popleft(), inflight.pop(i), host_arr.copy(), ...)
            if isinstance(node.func, ast.Attribute) and self._host_value(
                node.func.value
            ):
                return True
            return False
        if isinstance(node, ast.Name):
            return node.id in self.names
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return node.attr in self.host_attrs
            return self._host_value(node.value)
        if isinstance(node, ast.Subscript):
            return self._host_value(node.value)
        if isinstance(node, ast.BinOp):
            return self._host_value(node.left) and self._host_value(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._host_value(node.operand)
        if isinstance(node, ast.Compare):
            return self._host_value(node.left) and all(
                self._host_value(c) for c in node.comparators
            )
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return all(self._host_value(e) for e in node.elts)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                             ast.DictComp)):
            return all(self._host_value(g.iter) for g in node.generators)
        if isinstance(node, ast.IfExp):
            return self._host_value(node.body) and self._host_value(
                node.orelse
            )
        if isinstance(node, ast.JoinedStr):
            return True
        return False


@rule("host-sync")
def check(module: ParsedModule, ctx: RepoContext):
    quals = qualnames(module.tree)
    for fn, qual in quals.items():
        zone = _zone_for(module.path, qual)
        if zone is None:
            continue
        safe = _HostSafe(fn, host_attrs=zone.host_attrs,
                         host_params=zone.host_params)
        own_stmts = _own_nodes(fn, quals)
        for node in own_stmts:
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            finding = None
            if name in _SYNC_CALLS:
                if not (node.args and safe._host_value(node.args[0])):
                    finding = f"'{name}' forces a device sync"
            elif name in _CAST_CALLS:
                if node.args and not safe._host_value(node.args[0]):
                    arg_root = _root_name(node.args[0]) or "value"
                    finding = (
                        f"'{name}({arg_root}…)' forces a device sync on a "
                        "value not fetched via jax.device_get"
                    )
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in ("item", "block_until_ready")
                and not safe._host_value(node.func.value)
            ):
                finding = f"'.{node.func.attr}()' forces a device sync"
            if finding:
                yield Finding(
                    rule="host-sync",
                    path=module.path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"{finding} inside hot path '{qual}'; batch into one "
                        "explicit jax.device_get per decision point"
                    ),
                )


def _own_nodes(fn, quals):
    """Walk ``fn`` without descending into nested function defs."""
    out = []
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out
