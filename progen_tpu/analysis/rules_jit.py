"""Rules ``donation`` and ``recompile``: jit boundary contracts.

``donation``: ``donate_argnums`` hands the argument's buffer to XLA — the
Python reference left behind is a zombie whose next read raises (TPU) or
silently aliases (CPU).  The rule tracks names bound via
``f = jax.jit(impl, donate_argnums=...)`` and flags any later *read* of a
variable that was passed in a donated position, until it is reassigned.

``recompile``: jit caches on the hash of static args and on the structure
of traced ones — passing a config-like object as a traced arg either
errors (unhashable leaves) or retraces per call.  Two checks: (a) a jitted
function whose parameter looks like config/state-free metadata but is not
listed in static_argnums/static_argnames; (b) call sites of known-jitted
callables passing dict/list literals with string leaves or lambdas.
"""

from __future__ import annotations

import ast

from progen_tpu.analysis.engine import Finding, ParsedModule, RepoContext, rule
from progen_tpu.analysis.jaxgraph import (
    TraceGraph,
    call_name,
    dotted,
    walk_functions,
)

_CONFIG_PARAM_NAMES = frozenset(
    {
        "config",
        "cfg",
        "model_config",
        "train_config",
        "mesh_config",
        "sampler_config",
        "options",
        "settings",
        "policy",
        "tokenizer",
    }
)


def _static_names(jit_call: ast.Call) -> set[str]:
    out: set[str] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnames":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, str
                ):
                    out.add(node.value)
    return out


def _static_nums(jit_call: ast.Call) -> set[int]:
    out: set[int] = set()
    for kw in jit_call.keywords:
        if kw.arg == "static_argnums":
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, int
                ):
                    out.add(node.value)
    return out


def _donated_nums(jit_call: ast.Call) -> set[int]:
    out: set[int] = set()
    for kw in jit_call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            for node in ast.walk(kw.value):
                if isinstance(node, ast.Constant) and isinstance(
                    node.value, int
                ):
                    out.add(node.value)
    return out


# ---------------------------------------------------------------------------
# donation
# ---------------------------------------------------------------------------


@rule("donation")
def check_donation(module: ParsedModule, ctx: RepoContext):
    graph = TraceGraph(module.tree)
    donating = {
        j.bound_name: _donated_nums(j.call)
        for j in graph.jitted
        if _donated_nums(j.call)
    }
    if not donating:
        return
    for fn in walk_functions(module.tree):
        yield from _scan_donation(fn, donating, module.path)


def _scan_donation(fn, donating, path):
    # linear walk of the function body: after `out = step(state, batch)`
    # with argnum 0 donated, reads of `state` flag until it is rebound
    donated_live: dict[str, int] = {}  # var -> line of donating call
    for stmt in _linear_stmts(fn):
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(stmt, "value", None)
            if value is not None:
                yield from _flag_reads(value, donated_live, path)
                _note_donation(value, donating, donated_live)
            targets = (
                stmt.targets
                if isinstance(stmt, ast.Assign)
                else [stmt.target]
            )
            for t in targets:
                for n in ast.walk(t):
                    if isinstance(n, ast.Name):
                        donated_live.pop(n.id, None)
        elif isinstance(stmt, ast.Expr):
            yield from _flag_reads(stmt.value, donated_live, path)
            _note_donation(stmt.value, donating, donated_live)
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            yield from _flag_reads(stmt.value, donated_live, path)
        else:
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.expr):
                    yield from _flag_reads(sub, donated_live, path)
                    break


def _linear_stmts(fn):
    """Flatten the body including if/loop bodies, skipping nested defs."""
    stack = list(reversed(fn.body))
    while stack:
        stmt = stack.pop()
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield stmt
        for field in ("body", "orelse", "finalbody"):
            stack.extend(reversed(getattr(stmt, field, []) or []))


def _note_donation(expr, donating, donated_live):
    for node in ast.walk(expr):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        simple = name.split(".")[-1] if name else None
        nums = donating.get(simple)
        if not nums:
            continue
        for i, arg in enumerate(node.args):
            if i in nums and isinstance(arg, ast.Name):
                donated_live[arg.id] = node.lineno


def _flag_reads(expr, donated_live, path):
    if not donated_live:
        return
    for node in ast.walk(expr):
        if (
            isinstance(node, ast.Name)
            and isinstance(node.ctx, ast.Load)
            and node.id in donated_live
        ):
            # the donating call itself contains the name; only flag reads
            # on later lines
            if node.lineno > donated_live[node.id]:
                yield Finding(
                    rule="donation",
                    path=path,
                    line=node.lineno,
                    col=node.col_offset,
                    message=(
                        f"'{node.id}' was donated to a jitted call on line "
                        f"{donated_live[node.id]} and read afterwards; its "
                        "buffer may already be reused"
                    ),
                )
                donated_live.pop(node.id, None)
                return


# ---------------------------------------------------------------------------
# recompile
# ---------------------------------------------------------------------------


@rule("recompile")
def check_recompile(module: ParsedModule, ctx: RepoContext):
    graph = TraceGraph(module.tree)
    jitted_names: set[str] = set()

    # (a) jitted defs taking config-like params without static markings
    for j in graph.jitted:
        jitted_names.add(j.bound_name)
        if not j.wrapped_name:
            continue
        statics = _static_names(j.call)
        nums = _static_nums(j.call)
        for fn in graph.defs.get(j.wrapped_name, []):
            params = [a.arg for a in fn.args.args]
            if params and params[0] in ("self", "cls"):
                params = params[1:]
            for i, p in enumerate(params):
                if (
                    p in _CONFIG_PARAM_NAMES
                    and p not in statics
                    and i not in nums
                ):
                    yield Finding(
                        rule="recompile",
                        path=module.path,
                        line=fn.lineno,
                        col=fn.col_offset,
                        message=(
                            f"jitted function '{fn.name}' takes config-like "
                            f"arg '{p}' without static_argnums/"
                            "static_argnames: retraces on every new object"
                        ),
                    )

    for fn in walk_functions(module.tree):
        for dec in fn.decorator_list:
            target = dec.func if isinstance(dec, ast.Call) else dec
            if dotted(target) in ("jit", "jax.jit", "pjit", "jax.pjit"):
                statics = (
                    _static_names(dec) if isinstance(dec, ast.Call) else set()
                )
                nums = (
                    _static_nums(dec) if isinstance(dec, ast.Call) else set()
                )
                params = [a.arg for a in fn.args.args]
                if params and params[0] in ("self", "cls"):
                    params = params[1:]
                for i, p in enumerate(params):
                    if (
                        p in _CONFIG_PARAM_NAMES
                        and p not in statics
                        and i not in nums
                    ):
                        yield Finding(
                            rule="recompile",
                            path=module.path,
                            line=fn.lineno,
                            col=fn.col_offset,
                            message=(
                                f"jitted function '{fn.name}' takes "
                                f"config-like arg '{p}' without "
                                "static_argnums/static_argnames: retraces "
                                "on every new object"
                            ),
                        )

    # (b) call sites passing literal containers with non-array leaves
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        simple = name.split(".")[-1] if name else None
        if simple not in jitted_names:
            continue
        for arg in node.args:
            if _is_structural_literal(arg):
                yield Finding(
                    rule="recompile",
                    path=module.path,
                    line=arg.lineno,
                    col=arg.col_offset,
                    message=(
                        f"literal with non-array leaves passed to jitted "
                        f"'{simple}': strings/lambdas in a traced pytree "
                        "error or retrace; mark the arg static or hoist it"
                    ),
                )


def _is_structural_literal(node: ast.AST) -> bool:
    if isinstance(node, ast.Lambda):
        return True
    if isinstance(node, (ast.Dict, ast.List, ast.Set, ast.Tuple)):
        for sub in ast.walk(node):
            if isinstance(sub, ast.Lambda):
                return True
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                # a dict VALUE that is a string is config-like; dict keys
                # are legitimate pytree structure
                if not _is_dict_key(node, sub):
                    return True
    return False


def _is_dict_key(container: ast.AST, const: ast.Constant) -> bool:
    for sub in ast.walk(container):
        if isinstance(sub, ast.Dict) and const in sub.keys:
            return True
    return False
