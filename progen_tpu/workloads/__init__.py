"""First-class served workloads beyond left-to-right generation.

Three request classes ride the existing engine/cluster/trainer:

* **constrained span-infilling** (:mod:`.infill`) — :class:`ScaffoldSpec`
  compiles a protein scaffold template (frozen positions, per-position
  allowed alphabets) into the per-request ``(G, V)`` logit mask the
  engine threads through every sampling site;
* **embeddings** (:func:`progen_tpu.decode.prefill.make_embedder`,
  re-exported here) — one prefill-shaped forward, mean-pooled final
  hidden states, no decode slots consumed;
* **multi-tenant batched LoRA** (:mod:`.lora`) — stacked per-tenant
  low-rank adapter banks gathered per slot inside the decode step.

``WORKLOADS`` names the request classes the router/bench understand.
"""

from progen_tpu.decode.prefill import make_embedder
from progen_tpu.workloads.infill import (
    ScaffoldSpec,
    mask_from_wire,
    mask_to_wire,
)
from progen_tpu.workloads.lora import (
    adapter_bank_bytes,
    bank_from_trained,
    bank_num_tenants,
    init_lora_bank,
    lora_sites,
    random_lora_bank,
    validate_lora_bank,
)

WORKLOADS = ("generate", "infill", "embed", "lora")

__all__ = [
    "WORKLOADS",
    "ScaffoldSpec",
    "adapter_bank_bytes",
    "bank_from_trained",
    "bank_num_tenants",
    "init_lora_bank",
    "lora_sites",
    "make_embedder",
    "mask_from_wire",
    "mask_to_wire",
    "random_lora_bank",
    "validate_lora_bank",
]
