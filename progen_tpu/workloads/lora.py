"""Multi-tenant LoRA adapter banks for serving.

An adapter SITE is one dense projection in the model: the fused q/k/v
projection and the attention output projection of every layer, plus the
SGU channel projection of every gMLP layer.  A serving BANK stacks the
low-rank factors of ``T`` tenants per site::

    bank[f"attn{i}"]["qkv"] = {"a": (T, dim, r),   "b": (T, r, 3*inner)}
    bank[f"attn{i}"]["out"] = {"a": (T, inner, r), "b": (T, r, dim)}
    bank[f"ff{i}"]["sgu"]   = {"a": (T, half, r),  "b": (T, r, half)}

Tenant 0 is the BASE model: its factor rows are all-zero by construction
and the model applies the delta through an output-side ``where`` guard
(``models/progen.apply_lora``), so a tenant-0 slot is bit-identical to
running without adapters at all.  At decode time each batch row gathers
its own tenant's factors (``models/progen.lora_delta``) — one program
serves every tenant in the batch.

Any LoRA alpha/scale is folded into ``b`` when the bank is built
(:func:`bank_from_trained`); serving never sees a scale knob.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.models.progen import ProGenConfig


def lora_sites(config: ProGenConfig) -> dict[str, dict[str, tuple[int, int]]]:
    """``{layer: {site: (d_in, d_out)}}`` for every adapter site."""
    inner = config.heads * config.dim_head
    sites: dict[str, dict[str, tuple[int, int]]] = {}
    for i in range(config.depth):
        sites[f"attn{i}"] = {
            "qkv": (config.dim, 3 * inner),
            "out": (inner, config.dim),
        }
    for i in range(config.depth):
        if config.layer_uses_gmlp(i):
            # gMLP layers run non-GLU, so hidden = dim * ff_mult and the
            # SGU channel projection maps half -> half
            half = (config.dim * config.ff_mult) // 2
            sites[f"ff{i}"] = {"sgu": (half, half)}
    return sites


def init_lora_bank(config: ProGenConfig, num_tenants: int, rank: int,
                   seed: int = 0) -> dict:
    """Fresh serving bank: ``a`` rows lecun-normal per tenant, ``b`` rows
    zero (standard LoRA init — every tenant starts as an exact no-op),
    tenant 0 all-zero."""
    if num_tenants < 1:
        raise ValueError("num_tenants must be >= 1 (tenant 0 is the base)")
    key = jax.random.key(seed)
    bank: dict = {}
    for layer, s in sorted(lora_sites(config).items()):
        bank[layer] = {}
        for name, (din, dout) in sorted(s.items()):
            key, sub = jax.random.split(key)
            a = jax.random.normal(sub, (num_tenants, din, rank),
                                  jnp.float32) * (din ** -0.5)
            a = a.at[0].set(0.0)
            bank[layer][name] = {
                "a": a,
                "b": jnp.zeros((num_tenants, rank, dout), jnp.float32),
            }
    return bank


def random_lora_bank(config: ProGenConfig, num_tenants: int, rank: int,
                     seed: int = 0, scale: float = 1e-2) -> dict:
    """A bank whose non-base tenants produce NONZERO deltas (both factors
    random) — test fixtures and bench load need tenants that visibly
    diverge from the base model.  Tenant 0 stays all-zero."""
    bank = init_lora_bank(config, num_tenants, rank, seed=seed)
    key = jax.random.key(seed + 1)
    for layer in sorted(bank):
        for name in sorted(bank[layer]):
            key, sub = jax.random.split(key)
            site = bank[layer][name]
            b = jax.random.normal(sub, site["b"].shape, jnp.float32) * scale
            site["b"] = b.at[0].set(0.0)
    return bank


def bank_num_tenants(bank: dict) -> int:
    for layer in bank.values():
        for site in layer.values():
            return int(site["a"].shape[0])
    raise ValueError("empty adapter bank")


def validate_lora_bank(config: ProGenConfig, bank: dict) -> int:
    """Shape-check a bank against the model's sites; returns ``T``."""
    sites = lora_sites(config)
    if set(bank) != set(sites):
        raise ValueError(
            f"bank layers {sorted(bank)} != model sites {sorted(sites)}")
    t = bank_num_tenants(bank)
    r = None
    for layer, s in sites.items():
        if set(bank[layer]) != set(s):
            raise ValueError(
                f"bank[{layer!r}] sites {sorted(bank[layer])} != "
                f"{sorted(s)}")
        for name, (din, dout) in s.items():
            a = bank[layer][name]["a"]
            b = bank[layer][name]["b"]
            if r is None:
                r = a.shape[-1]
            want_a = (t, din, r)
            want_b = (t, r, dout)
            if tuple(a.shape) != want_a or tuple(b.shape) != want_b:
                raise ValueError(
                    f"bank[{layer!r}][{name!r}] shapes a={tuple(a.shape)} "
                    f"b={tuple(b.shape)}, want a={want_a} b={want_b}")
    return t


def bank_from_trained(config: ProGenConfig, rank: int, trained: list,
                      scale: float = 1.0) -> dict:
    """Build a serving bank from per-tenant TRAINED adapter trees.

    ``trained[t]`` holds tenant ``t + 1``'s factors as
    ``{layer: {site: {"a": (din, r), "b": (r, dout)}}}`` (what
    ``train/lora.py``'s ``extract_adapters`` returns).  Tenant 0 is the
    all-zero base row; ``scale`` (e.g. alpha / rank) is folded into
    ``b`` so serving needs no scale knob.
    """
    sites = lora_sites(config)
    num_tenants = len(trained) + 1
    bank: dict = {}
    for layer, s in sorted(sites.items()):
        bank[layer] = {}
        for name, (din, dout) in sorted(s.items()):
            a_rows = [jnp.zeros((din, rank), jnp.float32)]
            b_rows = [jnp.zeros((rank, dout), jnp.float32)]
            for tree in trained:
                site = tree[layer][name]
                a_rows.append(jnp.asarray(site["a"], jnp.float32))
                b_rows.append(jnp.asarray(site["b"], jnp.float32) * scale)
            bank[layer][name] = {
                "a": jnp.stack(a_rows),
                "b": jnp.stack(b_rows),
            }
    validate_lora_bank(config, bank)
    assert bank_num_tenants(bank) == num_tenants
    return bank


def adapter_bank_bytes(config: ProGenConfig, num_tenants: int, rank: int,
                       bytes_per_el: int = 4) -> int:
    """HBM footprint of a serving bank (f32 by default) — the memory
    plan's adapter line item."""
    total = 0
    for s in lora_sites(config).values():
        for din, dout in s.values():
            total += num_tenants * rank * (din + dout) * bytes_per_el
    return total


def tenant_ids(bank: dict) -> np.ndarray:
    """The usable non-base tenant ids for a bank: ``1..T-1``."""
    return np.arange(1, bank_num_tenants(bank))
