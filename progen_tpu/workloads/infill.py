"""Constrained span-infilling: scaffold templates -> per-position logit masks.

Protein engineering's scaffold-constrained design: a template fixes some
positions to known residues (the scaffold), leaves spans free (the design
region), and optionally restricts free positions to a sub-alphabet
(e.g. hydrophobics only).  :class:`ScaffoldSpec` is the host-side API: it
splits the template into the prime (the longest frozen prefix — served
through the normal prefill path, no masking needed) and a ``(G, V)``
boolean mask over the ``G`` generated positions, where a frozen position
is a one-hot row (the sampler is FORCED to emit it) and a free position
allows its alphabet.

The mask is pure data (numpy, no jax) so specs build anywhere — client
code, the cluster driver, test fixtures — and serialize through the
snapshot/wire helpers below.  Engine-side semantics live in
``decode/sampler.apply_logit_mask``: an all-pass mask is bit-identical to
no mask at all.
"""

from __future__ import annotations

import dataclasses
from typing import Iterable, Sequence

import numpy as np


def _is_int(x) -> bool:
    return isinstance(x, (int, np.integer)) and not isinstance(x, bool)


@dataclasses.dataclass(frozen=True)
class ScaffoldSpec:
    """A scaffold-constrained infilling request.

    ``template``: one entry per sequence position —

    * an ``int`` token id: frozen scaffold position (forced);
    * ``None``: free position over ``alphabet`` (or the full vocab);
    * an iterable of token ids: free position over that allowed set.

    ``alphabet``: default allowed set for ``None`` entries (``None`` =
    full vocabulary).  ``vocab``: vocabulary size ``V``.

    The longest all-``int`` prefix becomes the prime (at least one
    position — the engine needs a non-empty prime; start templates with
    a BOS/context token).  Everything after it is generated under the
    mask, INCLUDING interior frozen positions (a one-hot row forces the
    scaffold token regardless of key/top-k/temperature).
    """

    template: Sequence
    vocab: int = 256
    alphabet: Iterable[int] | None = None

    def __post_init__(self):
        if len(self.template) < 2:
            raise ValueError("template needs at least a prime position and "
                             "one position to generate")
        if not _is_int(self.template[0]):
            raise ValueError(
                "template must start with at least one frozen token (the "
                "prime the engine prefills); got a free position at index 0")
        if len(self.prime()) == len(self.template):
            raise ValueError("template is fully frozen — nothing to infill")
        for g, row in enumerate(self._rows()):
            if not row.any():
                raise ValueError(
                    f"template position {len(self.prime()) + g} allows no "
                    "tokens — every generated position needs >= 1")

    def prime(self) -> list[int]:
        """The longest frozen prefix, served as the request's prime."""
        out: list[int] = []
        for e in self.template:
            if not _is_int(e):
                break
            out.append(int(e))
        return out

    @property
    def max_new_tokens(self) -> int:
        return len(self.template) - len(self.prime())

    def _rows(self):
        v = self.vocab
        default = np.zeros(v, bool)
        if self.alphabet is None:
            default[:] = True
        else:
            idx = np.asarray(sorted(set(int(a) for a in self.alphabet)),
                             np.int64)
            if idx.size and (idx.min() < 0 or idx.max() >= v):
                raise ValueError(f"alphabet outside vocab {v}")
            default[idx] = True
        for e in self.template[len(self.prime()):]:
            row = np.zeros(v, bool)
            if e is None:
                row = default.copy()
            elif _is_int(e):
                if not (0 <= int(e) < v):
                    raise ValueError(f"frozen token {e} outside vocab {v}")
                row[int(e)] = True
            else:
                idx = np.asarray(sorted(set(int(a) for a in e)), np.int64)
                if idx.size == 0:
                    yield row
                    continue
                if idx.min() < 0 or idx.max() >= v:
                    raise ValueError(f"allowed set {e} outside vocab {v}")
                row[idx] = True
            yield row

    def logit_mask(self) -> np.ndarray:
        """``(max_new_tokens, V)`` bool: row ``g`` constrains the token
        generated at template position ``len(prime) + g``."""
        return np.stack(list(self._rows()), axis=0)

    def request_kwargs(self) -> dict:
        """Keyword arguments for ``decode.engine.Request`` (tokens,
        max_new_tokens, logit_mask) — kept as plain data so this module
        never imports the engine."""
        return {
            "tokens": self.prime(),
            "max_new_tokens": self.max_new_tokens,
            "logit_mask": self.logit_mask(),
        }

    def full_mask(self, length: int) -> np.ndarray:
        """``(length, V)`` absolute-position mask for
        ``make_chunked_sampler``'s ``logit_mask``: generated template
        positions carry their rows, everything else is all-pass (prime
        positions are never sampled; positions past the template are
        unconstrained)."""
        p = len(self.prime())
        if length < p + self.max_new_tokens:
            raise ValueError(
                f"length {length} shorter than template {len(self.template)}")
        out = np.ones((length, self.vocab), bool)
        out[p:p + self.max_new_tokens] = self.logit_mask()
        return out


def mask_to_wire(mask) -> list | None:
    """Compact JSON-safe encoding of a ``(G, V)`` bool mask: per position,
    ``None`` for an all-pass row, else the sorted list of allowed ids.
    ``None`` for a ``None``/all-pass mask (the common generate case costs
    zero bytes on the wire)."""
    if mask is None:
        return None
    mask = np.asarray(mask, bool)
    rows = [None if row.all() else np.flatnonzero(row).tolist()
            for row in mask]
    if all(r is None for r in rows):
        return None
    return rows


def mask_from_wire(rows, vocab: int) -> np.ndarray | None:
    """Inverse of :func:`mask_to_wire`."""
    if rows is None:
        return None
    out = np.ones((len(rows), vocab), bool)
    for g, r in enumerate(rows):
        if r is not None:
            out[g] = False
            out[g, np.asarray(r, np.int64)] = True
    return out
