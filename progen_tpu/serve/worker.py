"""Serving worker process: ``python -m progen_tpu.serve.worker``.

One process per stage instance, spawned by :class:`ServeCluster` with
``JAX_PLATFORMS``/``XLA_FLAGS`` pinned so each worker owns its own JAX
runtime (pattern of ``tests/_multihost_worker.py``).  The worker
connects back to the router, says hello, builds its engine from the
JSON spec file, and enters its stage loop:

- ``prefill``: requests in → :meth:`ServingEngine.run_prefill_round` →
  serialized handle frames out, throttled by an ack credit window (the
  replica acks on admission; unacked handles ≤ the engine's
  ``handoff_depth``) so prefilled state never piles up un-merged;
- ``decode``: handle frames in → :func:`deserialize_handle` →
  :meth:`ServingEngine.admit_handle` (``remote_prefill=True``: the
  engine NEVER runs its own prefill — prefill wall leaves this process
  entirely) → completion messages out.

Every process builds bit-identical params from the same spec (same
init seed, same jit recipe — or the same checkpoint), so handles made
by any worker merge into any replica and trajectories depend only on
(params, prime, seed, knobs): placement is invisible in the tokens.

A payload-CRC-corrupt handle frame is reported home as a typed
``bad_frame`` message (the router replays the named requests); a
desynced stream ends the process, and stage supervision restarts it.

A decode replica may also be a multi-process TENSOR-PARALLEL GROUP
(``PROGEN_TPU_TP_GROUP_*`` env vars, docs/SERVING.md §13): member 0 is
the leader (role ``decode``), members 1..G-1 are followers (role
``dshard<k>``, same replica index).  The group forms a private
``jax.distributed`` job whose engine runs under a process-spanning
``tensor=G`` mesh; every collective-bearing step is driven in lockstep
by a leader-broadcast plan so the members' jax programs always agree.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import sys
import time
from collections import deque

from progen_tpu.core.cache import honor_env_platforms

honor_env_platforms()


def make_spec(config, *, mixed_precision: bool = True, init_seed: int = 0,
              checkpoint_path: str | None = None, draft: str = "identity",
              engine: dict | None = None, draft_config=None,
              heartbeat_s: float = 1.0, trace: dict | None = None,
              statusz: bool = False, lora: dict | None = None,
              aot_warmup: bool = False,
              warmup_max_prime: int | None = None) -> dict:
    """Build the JSON-able worker spec.  ``engine`` holds
    :class:`ServingEngine` kwargs (slots/chunk/paged/spec/...,
    including ``quantize`` — every worker built from the spec quantizes
    the same full-precision init/checkpoint tree, so int8 replicas stay
    bit-identical to each other); ``disagg`` is implied.  Params come from ``checkpoint_path`` when
    set, else from ``jit(model.init)(key(init_seed))`` — identical in
    every process either way.  ``trace`` (``{"dir": ..., "capacity"?}``)
    enables span tracing in every worker; each dumps its ring to
    ``trace_<role>_<index>.json`` in that directory at exit
    (docs/OBSERVABILITY.md).  ``statusz=True`` starts a loopback
    introspection server in every process (driver included) on an
    ephemeral port; workers report their port in the hello frame and the
    driver surfaces the map on its own /statusz.

    ``lora`` (``{"tenants": T, "rank": R, "seed"?, "scale"?}``) gives the
    worker a deterministic adapter bank built with
    :func:`~progen_tpu.workloads.lora.random_lora_bank` — bit-identical
    in every process, so multi-tenant handles merge into any replica of
    the same spec.  ``aot_warmup=True`` makes the worker compile its
    whole program grid BEFORE sending its ready frame (warm-before-
    routable: the control plane only routes to workers that answered
    ready, so a scaled-up worker never eats cold compiles on live
    traffic); ``warmup_max_prime`` caps the bucket sweep."""
    spec = {
        "config": config.to_dict(),
        "mixed_precision": bool(mixed_precision),
        "init_seed": int(init_seed),
        "checkpoint_path": checkpoint_path,
        "draft": draft,
        "engine": dict(engine or {}),
        "heartbeat_s": float(heartbeat_s),
    }
    if trace:
        spec["trace"] = dict(trace)
    if statusz:
        spec["statusz"] = True
    if lora:
        spec["lora"] = dict(lora)
    if aot_warmup:
        spec["aot_warmup"] = True
        if warmup_max_prime is not None:
            spec["warmup_max_prime"] = int(warmup_max_prime)
    if draft_config is not None:
        spec["draft_config"] = draft_config.to_dict()
    return spec


def build_engine_from_spec(spec: dict, *, remote_prefill: bool = False,
                           group_size: int = 1):
    """Construct the ServingEngine a worker spec describes — also used
    by tests/benches to build the in-process REFERENCE engine with the
    exact same param recipe, making token-identity a hard assert.

    ``group_size > 1`` builds the TP-GROUP flavor: the engine runs under
    a process-spanning ``tensor=group_size`` mesh (one device per member
    process) with the ``tp`` rule set, and the bit-identical per-process
    param tree is placed as global arrays before construction.  Every
    member calls this with the same spec, so the group's params — like a
    single-process replica's — depend only on (init seed | checkpoint).
    """
    import jax
    import jax.numpy as jnp

    from progen_tpu.core.precision import make_policy
    from progen_tpu.decode import ServingEngine
    from progen_tpu.models import ProGen, ProGenConfig
    from progen_tpu.parallel import unbox

    cfg = ProGenConfig.from_dict(spec["config"])
    policy = make_policy(bool(spec.get("mixed_precision", True)))
    model = ProGen(config=cfg, policy=policy)
    toks = jnp.zeros((1, cfg.seq_len), jnp.int32)
    if spec.get("checkpoint_path"):
        from progen_tpu.checkpoint import CheckpointStore, abstract_params_like

        store = CheckpointStore(spec["checkpoint_path"])
        params = {"params": store.restore_params(
            abstract_params_like(model, toks))}
        store.close()
    else:
        params = unbox(jax.jit(model.init)(
            jax.random.key(int(spec.get("init_seed", 0))), toks))
    kw = dict(spec.get("engine", {}))
    kw["disagg"] = True
    if kw.get("spec") and "draft_config" in spec:
        kw["draft_config"] = ProGenConfig.from_dict(spec["draft_config"])
    if spec.get("lora"):
        # spec-driven bank: random_lora_bank is deterministic per seed,
        # so every process rebuilds the SAME adapters (like init params)
        from progen_tpu.workloads.lora import random_lora_bank

        lcfg = spec["lora"]
        kw["lora_bank"] = random_lora_bank(
            cfg, int(lcfg["tenants"]), int(lcfg["rank"]),
            seed=int(lcfg.get("seed", 0)),
            scale=float(lcfg.get("scale", 1e-2)))
    if group_size > 1:
        import numpy as np

        from progen_tpu.core.mesh import MeshConfig, make_mesh
        from progen_tpu.parallel.sharding import (
            param_shardings,
            validate_tp_divisibility,
        )

        strategies = ("tp",)
        validate_tp_divisibility(cfg, group_size, strategies=strategies)
        mesh = make_mesh(MeshConfig(data=1, fsdp=1, tensor=group_size,
                                    seq=1))
        shardings = param_shardings(model, toks, mesh, strategies)

        def _place(leaf, sharding):
            # every member holds the full leaf; hand each device its
            # slice so placement needs no cross-process resharding
            host = np.asarray(leaf)
            return jax.make_array_from_callback(
                host.shape, sharding, lambda idx: host[idx])

        params = jax.tree_util.tree_map(_place, params, shardings)
        kw["mesh"], kw["strategies"] = mesh, strategies
    return ServingEngine(cfg, params, policy=policy,
                         remote_prefill=remote_prefill, **kw)


def _completion_to_wire(c) -> dict:
    msg = {
        "type": "completion",
        "uid": c.uid,
        "prime": [int(t) for t in c.prime],
        "tokens": [int(t) for t in c.tokens],
        "finish_reason": c.finish_reason,
        "status": c.status,
        "worker_latency": float(c.latency),
    }
    if c.embedding is not None:
        msg["embedding"] = [float(x) for x in c.embedding]
    return msg


def _drain_inbox(inbox, *, timeout: float):
    """Pull every queued event (blocking up to ``timeout`` for the
    first); returns (messages, router_dead)."""
    out = []
    t = timeout
    while True:
        try:
            item = inbox.get(timeout=t)
        except _queue.Empty:
            return out, False
        t = 0.0
        if item[0] == "dead":
            return out, True
        out.append((item[2], item[3]))  # (header, frame)


def _stats_frame(eng, counters, **extra) -> dict:
    """One stats/metrics frame, sent both as the final flush and in reply
    to a mid-run ``stats_req`` (the drain-time freshness fix): the worker
    echoes its clock so the driver can stamp the snapshot's capture age."""
    from progen_tpu.observe.metrics import get_registry

    msg = {"type": "stats",
           "clock": time.perf_counter(),
           "stage_seconds": eng.stage_seconds,
           "transport": counters.as_dict(),
           "chunks_run": eng.chunks_run,
           # every role reports its robustness + QoS tallies: prefill
           # workers own the cluster's scheduling queues, so their
           # per-class/per-tenant counters are the fleet QoS view
           "robust": eng.robustness_counters(),
           "metrics": get_registry().snapshot()}
    dig = eng.prefix_digest()
    if dig is not None:
        # cache advertisement rides the stats frame too, so a drain-time
        # flush leaves the router's digest table current
        msg["digest"] = dig
    msg.update(extra)
    return msg


def _prefill_loop(eng, peer, inbox, counters, *, heartbeat_s: float,
                  window: int, incarnation: int = 0,
                  generation: int = 0) -> None:
    from progen_tpu.decode.handoff import (
        request_from_wire,
        serialize_handle,
    )
    from progen_tpu.observe.metrics import get_registry
    from progen_tpu.observe.trace import get_tracer

    tracer = get_tracer()
    unacked: set = set()
    batch_seq = 0
    running = True
    stall_t0 = None  # opened when prefill is blocked on ack credits
    last_hb = time.perf_counter()
    while running or eng.pending:
        idle = not (eng.pending and len(unacked) < window)
        msgs, dead = _drain_inbox(inbox, timeout=0.1 if idle else 0.0)
        if dead:
            return
        for header, _ in msgs:
            t = header.get("type")
            if t == "req":
                eng.submit(request_from_wire(
                    header["req"], vocab=eng.config.num_tokens))
            elif t == "embed_req":
                eng.submit_embed(request_from_wire(
                    header["req"], vocab=eng.config.num_tokens))
            elif t == "ack":
                unacked.discard(header.get("batch_id"))
            elif t == "shutdown":
                running = False
            elif t == "stats_req":
                peer.send_json(_stats_frame(eng, counters))
        # embed traffic shares this worker's prefill-shaped programs but
        # needs no ack credits — completions ship straight home
        while eng.embed_pending:
            eng.run_embed_round()
            for c in eng.drain_sheds():
                peer.send_json(_completion_to_wire(c))
        if eng.pending and len(unacked) >= window:
            if stall_t0 is None:
                stall_t0 = time.perf_counter()
        elif stall_t0 is not None:
            now = time.perf_counter()
            tracer.add("worker.credit_stall", stall_t0, now - stall_t0,
                       queue=eng.pending)
            stall_t0 = None
        for c in eng.drain_sheds():
            peer.send_json(_completion_to_wire(c))
        while eng.pending and len(unacked) < window:
            before = eng.pending
            h = eng.run_prefill_round()
            for c in eng.drain_sheds():
                peer.send_json(_completion_to_wire(c))
            if h is not None:
                # the incarnation nonce keeps a respawned worker's ids
                # (batch_seq restarts at 0) distinct from any the dead
                # incarnation left in the router's bookkeeping
                batch_id = f"{peer.index}.{incarnation}:{batch_seq}"
                batch_seq += 1
                frame = serialize_handle(
                    h, counters=counters,
                    extra_header={"batch_id": batch_id,
                                  "src": peer.index,
                                  "generation": generation,
                                  "trace_ctx": {
                                      "clock": time.perf_counter(),
                                      "src_proc": f"prefill:{peer.index}"}})
                unacked.add(batch_id)
                peer.send_bytes(frame)
                # handed-off requests are harvested by a decode replica,
                # never here — drop their first-token stamps
                eng.forget_ttft(r.uid for r in h.requests)
            elif eng.pending >= before:
                break  # no progress (should not happen; avoid spinning)
        now = time.perf_counter()
        if now - last_hb >= heartbeat_s:
            last_hb = now
            peer.send_json({
                "type": "hb", "queue": eng.pending,
                "unacked": len(unacked),
                "clock": now,
                "stage_seconds": eng.stage_seconds,
                "metrics": get_registry().snapshot()})
    peer.send_json(_stats_frame(eng, counters))


def _decode_loop(eng, peer, inbox, counters, *, heartbeat_s: float) -> None:
    from progen_tpu.decode.handoff import FrameCorrupt, deserialize_handle
    from progen_tpu.observe.metrics import get_registry
    from progen_tpu.observe.trace import get_tracer

    tracer = get_tracer()
    backlog: deque = deque()  # [header, frame, handle|None, recv_clock]
    running = True
    max_backlog = 0
    last_hb = time.perf_counter()
    while running or eng.has_work or backlog:
        idle = not (eng.has_work or backlog)
        msgs, dead = _drain_inbox(inbox, timeout=0.1 if idle else 0.0)
        if dead:
            return
        for header, frame in msgs:
            t = header.get("type")
            if t == "handle":
                backlog.append([header, frame, None, time.perf_counter()])
                max_backlog = max(max_backlog, len(backlog))
            elif t == "shutdown":
                running = False
            elif t == "stats_req":
                peer.send_json(_stats_frame(
                    eng, counters, max_handoff_backlog=max_backlog))
        while backlog:
            entry = backlog[0]
            if entry[2] is None:
                try:
                    entry[2] = deserialize_handle(entry[1],
                                                  counters=counters)
                except FrameCorrupt:
                    counters.crc_failures += 1
                    backlog.popleft()
                    peer.send_json({
                        "type": "bad_frame",
                        "batch_id": entry[0].get("batch_id"),
                        "uids": [d["uid"]
                                 for d in entry[0].get("reqs", [])]})
                    continue
            if not eng.admit_handle(entry[2]):
                break  # handoff at depth: step() below frees it
            backlog.popleft()
            # queue-wait: frame receipt -> successful admission, tagged
            # with the uids the handle header names
            now = time.perf_counter()
            tracer.add("worker.queue_wait", entry[3], now - entry[3],
                       uids=[d["uid"] for d in entry[0].get("reqs", [])],
                       batch_id=entry[0].get("batch_id"))
            peer.send_json({"type": "ack",
                            "batch_id": entry[0].get("batch_id")})
        if eng.has_work:
            for c in eng.step():
                peer.send_json(_completion_to_wire(c))
        now = time.perf_counter()
        if now - last_hb >= heartbeat_s:
            last_hb = now
            hb_msg = {
                "type": "hb", "inflight": eng.num_active,
                "handoff_backlog": len(backlog),
                "clock": now,
                "stage_seconds": eng.stage_seconds,
                "metrics": get_registry().snapshot()}
            dig = eng.prefix_digest()
            if dig is not None:
                hb_msg["digest"] = dig
            peer.send_json(hb_msg)
    peer.send_json(_stats_frame(eng, counters,
                                max_handoff_backlog=max_backlog))


# --- tp-group lockstep ------------------------------------------------
#
# A tp-group engine's jitted programs are collectives: every member must
# issue the SAME sequence of admit/step calls or the group deadlocks.
# The engine itself is deterministic — identical inputs in identical
# order produce identical host state on every member — so only the
# leader's nondeterministic inputs (which handle frames arrived, and
# whether shutdown was requested) need broadcasting.  Each loop
# iteration the leader publishes a tiny JSON plan; everything after it
# is deterministic replay.

_PLAN_BYTES = 16384  # fixed-size plan buffer (collectives need one shape)


def _group_plan_exchange(plan: dict | None) -> dict:
    """Leader→members broadcast of one lockstep plan dict.  Followers
    pass ``None``; every member returns the leader's plan."""
    import numpy as np
    from jax.experimental import multihost_utils

    buf = np.zeros(_PLAN_BYTES, np.uint8)
    if plan is not None:
        raw = json.dumps(plan).encode()
        if len(raw) >= _PLAN_BYTES:
            raise ValueError(
                f"tp-group plan overflows {_PLAN_BYTES}B: {len(raw)}B")
        buf[:len(raw)] = np.frombuffer(raw, np.uint8)
    # the broadcast's internal psum promotes uint8; narrow back before
    # reinterpreting the element buffer as the JSON byte string
    out = np.asarray(multihost_utils.broadcast_one_to_all(buf),
                     dtype=np.uint8)
    return json.loads(bytes(out).rstrip(b"\x00").decode())


def _group_all_ok(flag: bool) -> bool:
    """Group consensus: True iff EVERY member voted True."""
    import numpy as np
    from jax.experimental import multihost_utils

    votes = multihost_utils.process_allgather(
        np.asarray([1 if flag else 0], np.int32))
    return bool(np.asarray(votes).min() > 0)


def _claim_slab(slabs: dict, batch_id: str, inbox, eng, peer, counters,
                *, deadline_s: float = 120.0):
    """Take ``batch_id``'s slab frame, waiting for late delivery.

    The leader only announces batch ids it has already received, but a
    follower's slab rides a separate TCP stream and may trail the plan
    broadcast.  Returns ``[header, frame, recv_clock]`` or None when the
    router died; a slab that never arrives is a wiring bug, not a
    transient — raise rather than desync the group."""
    deadline = time.perf_counter() + deadline_s
    while batch_id not in slabs:
        msgs, dead = _drain_inbox(inbox, timeout=0.2)
        if dead:
            return None
        for header, frame in msgs:
            t = header.get("type")
            if t == "handle":
                slabs[header.get("batch_id")] = [
                    header, frame, time.perf_counter()]
            elif t == "stats_req":
                peer.send_json(_stats_frame(eng, counters))
            # shutdown is leader-planned; a follower's copy is ignored
        if time.perf_counter() > deadline:
            raise RuntimeError(
                f"tp-group slab for batch {batch_id!r} never arrived")
    return slabs.pop(batch_id)


def _group_decode_loop(eng, peer, inbox, counters, *, heartbeat_s: float,
                       group_rank: int, group_size: int) -> None:
    """Decode loop for one member of a tp-group replica.

    Mirrors :func:`_decode_loop` exactly — same admit-then-step order,
    same at-depth backpressure — but frame arrival and shutdown flow
    through the leader's plan, deserialization verdicts take a group
    vote (a frame only enters the engine when EVERY member could parse
    its slab), and only the leader speaks results (ack / bad_frame /
    completion) to the router.  Heartbeats and stats stay per-member:
    the driver supervises each process independently."""
    from progen_tpu.decode.handoff import (
        FrameCorrupt,
        deserialize_handle_sharded,
    )
    from progen_tpu.observe.metrics import get_registry
    from progen_tpu.observe.trace import get_tracer

    leader = group_rank == 0
    tracer = get_tracer()
    backlog: deque = deque()  # [header, frame, handle|None, recv_clock]
    slabs: dict = {}          # batch_id -> [header, frame, recv_clock]
    announce: list = []       # leader: arrived, not yet planned
    running = True
    max_backlog = 0
    last_hb = time.perf_counter()
    while running or eng.has_work or backlog:
        idle = not (eng.has_work or backlog)
        msgs, dead = _drain_inbox(inbox, timeout=0.1 if idle else 0.0)
        if dead:
            return
        for header, frame in msgs:
            t = header.get("type")
            if t == "handle":
                bid = header.get("batch_id")
                slabs[bid] = [header, frame, time.perf_counter()]
                if leader:
                    announce.append(bid)
            elif t == "shutdown":
                if leader:
                    running = False
            elif t == "stats_req":
                peer.send_json(_stats_frame(
                    eng, counters, max_handoff_backlog=max_backlog,
                    group_rank=group_rank, group_size=group_size))
        plan = _group_plan_exchange(
            {"admit": announce, "running": running} if leader else None)
        running = bool(plan["running"])
        announce = []
        for bid in plan["admit"]:
            entry = _claim_slab(slabs, bid, inbox, eng, peer, counters)
            if entry is None:
                return
            backlog.append([entry[0], entry[1], None, entry[2]])
            max_backlog = max(max_backlog, len(backlog))
        while backlog:
            entry = backlog[0]
            if entry[2] is None:
                try:
                    handle = deserialize_handle_sharded(
                        entry[1], eng.mesh, counters=counters)
                    ok = True
                except FrameCorrupt:
                    handle, ok = None, False
                if not _group_all_ok(ok):
                    # some member's slab was corrupt: the whole group
                    # drops the batch so engine states stay identical
                    if not ok:
                        counters.crc_failures += 1
                    backlog.popleft()
                    if leader:
                        peer.send_json({
                            "type": "bad_frame",
                            "batch_id": entry[0].get("batch_id"),
                            "uids": [d["uid"]
                                     for d in entry[0].get("reqs", [])]})
                    continue
                entry[2] = handle
            if not eng.admit_handle(entry[2]):
                break  # handoff at depth: step() below frees it
            backlog.popleft()
            now = time.perf_counter()
            tracer.add("worker.queue_wait", entry[3], now - entry[3],
                       uids=[d["uid"] for d in entry[0].get("reqs", [])],
                       batch_id=entry[0].get("batch_id"))
            if leader:
                peer.send_json({"type": "ack",
                                "batch_id": entry[0].get("batch_id")})
        if eng.has_work:
            for c in eng.step():
                if leader:
                    peer.send_json(_completion_to_wire(c))
        now = time.perf_counter()
        if now - last_hb >= heartbeat_s:
            last_hb = now
            hb_msg = {
                "type": "hb", "inflight": eng.num_active,
                "handoff_backlog": len(backlog),
                "clock": now,
                "stage_seconds": eng.stage_seconds,
                "metrics": get_registry().snapshot()}
            if leader:
                dig = eng.prefix_digest()
                if dig is not None:
                    hb_msg["digest"] = dig
            peer.send_json(hb_msg)
    peer.send_json(_stats_frame(eng, counters,
                                max_handoff_backlog=max_backlog,
                                group_rank=group_rank,
                                group_size=group_size))


def main(argv) -> int:
    role, index, port, spec_path = (
        argv[0], int(argv[1]), int(argv[2]), argv[3])
    incarnation = int(argv[4]) if len(argv) > 4 else 0
    generation = int(argv[5]) if len(argv) > 5 else 0
    from progen_tpu.core.cache import enable_compilation_cache

    enable_compilation_cache()
    # tp-group membership (docs/SERVING.md §13): the G member processes
    # of one decode replica form a private jax.distributed job.  Must
    # initialize BEFORE anything touches the backend.
    group_size = int(os.environ.get("PROGEN_TPU_TP_GROUP_SIZE", "1"))
    group_rank = int(os.environ.get("PROGEN_TPU_TP_GROUP_RANK", "0"))
    if group_size > 1:
        import jax

        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address="localhost:{}".format(
                int(os.environ["PROGEN_TPU_TP_GROUP_PORT"])),
            num_processes=group_size,
            process_id=group_rank)
    with open(spec_path) as fh:
        spec = json.load(fh)

    from progen_tpu.observe.trace import (
        configure_tracing,
        get_tracer,
        trace_dump_path,
    )
    from progen_tpu.observe.transport import TransportCounters
    from progen_tpu.serve.transport import Peer, connect

    tcfg = spec.get("trace")
    if tcfg:
        configure_tracing(enabled=True,
                          capacity=tcfg.get("capacity"),
                          process=f"{role}:{index}")

    counters = TransportCounters()

    # the introspection server comes up BEFORE the engine build so
    # /healthz answers (phase "building") during a minutes-long cold jit;
    # its port rides the hello frame for the driver's endpoint map
    statusz_srv = None
    holder: dict = {"phase": "connecting"}
    if spec.get("statusz"):
        from progen_tpu.observe.statusz import StatuszServer

        def _health():
            out = {"phase": holder["phase"],
                   "transport": counters.as_dict()}
            eng_ = holder.get("eng")
            if eng_ is not None:
                out["pending"] = eng_.pending
                out["active"] = eng_.num_active
            return out

        def _status():
            eng_ = holder.get("eng")
            return (eng_.status() if eng_ is not None
                    else {"phase": holder["phase"]})

        statusz_srv = StatuszServer(
            role=role, index=index,
            providers={"health": _health, "status": _status})
        statusz_srv.start()

    sock = connect(port)
    peer = Peer(sock, counters)
    peer.role, peer.index = role, index
    # the clock echo lets the driver estimate this process's perf_counter
    # offset, so merged trace timelines are causally ordered
    hello = {"type": "hello", "role": role, "index": index,
             "generation": generation,
             "clock": time.perf_counter()}
    if statusz_srv is not None:
        hello["statusz_port"] = statusz_srv.port
    peer.send_json(hello)

    print(f"worker {role}:{index} building engine", flush=True)
    holder["phase"] = "building"
    t0 = time.perf_counter()
    eng = build_engine_from_spec(
        spec,
        remote_prefill=(role == "decode" or role.startswith("dshard")),
        group_size=group_size)
    eng.generation = generation
    warm = {}
    if spec.get("aot_warmup"):
        # warm-before-routable: the ready frame is what makes a
        # scaled-up worker placeable, so every compile lands before it
        holder["phase"] = "warming"
        warm = eng.aot_warmup(max_prime=spec.get("warmup_max_prime"))
    if group_size > 1:
        # group barrier before ANY member reports ready: the leader's
        # ready frame means the whole replica can run collectives
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("progen_tpu_tp_group_ready")
    print(f"worker {role}:{index} engine ready in "
          f"{time.perf_counter() - t0:.1f}s", flush=True)
    holder["eng"] = eng
    holder["phase"] = "serving"
    ready = {"type": "ready", "build_s": time.perf_counter() - t0,
             "generation": generation}
    if warm:
        ready["warmup"] = warm
    peer.send_json(ready)

    inbox: _queue.Queue = _queue.Queue()
    peer.start_reader(inbox)
    hb = float(spec.get("heartbeat_s", 1.0))
    if role == "prefill":
        window = max(1, int(spec.get("engine", {}).get("handoff_depth", 2)))
        _prefill_loop(eng, peer, inbox, counters,
                      heartbeat_s=hb, window=window,
                      incarnation=incarnation, generation=generation)
    elif group_size > 1:
        _group_decode_loop(eng, peer, inbox, counters, heartbeat_s=hb,
                           group_rank=group_rank, group_size=group_size)
    else:
        _decode_loop(eng, peer, inbox, counters, heartbeat_s=hb)
    if tcfg and tcfg.get("dir"):
        try:
            get_tracer().dump(
                trace_dump_path(tcfg["dir"], f"{role}:{index}"))
        except OSError as e:
            print(f"worker {role}:{index} trace dump failed: {e}",
                  file=sys.stderr, flush=True)
    print(f"worker {role}:{index} exiting", flush=True)
    if statusz_srv is not None:
        statusz_srv.stop()
    peer.close()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
