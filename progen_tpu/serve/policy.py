"""Scaling policy for the elastic serving control plane — pure,
deterministic decision logic with NO side effects.

The control plane (``serve/control.py``) samples live signals from the
cluster — SLO error-budget burn rates (``observe/slo.py``), per-stage
queue depths and outstanding decode tokens from the router, worker
``stage_seconds`` — packs them into a :class:`PolicyInputs`, and asks
the policy what to do.  The policy returns :class:`ScaleDecision`
objects; the control plane executes them through the cluster's elastic
verbs and journals both.

Everything here is host-side stdlib and **deterministic**: the same
sequence of ``PolicyInputs`` always yields the same decisions, because
time enters only through ``inputs.now`` (never a wall clock read) and
the policy keeps no hidden state beyond the last-action timestamps it
needs for cooldown.  That makes policy behaviour unit-testable with
synthetic clocks and replayable from the control journal.

:class:`BurnRatePolicy` is the default: scale **up** when any watched
SLO burns faster than ``up_burn`` (budget spent faster than the
objective allows) or a stage's queue backlog exceeds
``up_queue_per_worker``; scale **down** when every burn rate is below
``down_burn`` AND the stage is near idle.  Hysteresis comes from the
gap between the up and down thresholds plus a per-stage ``cooldown_s``
after ANY action on that stage (including swaps), so the fleet cannot
flap.  Bounds are hard: the policy never leaves
``[min_prefill, max_prefill] x [min_replicas, max_replicas]``.
"""

from __future__ import annotations

import dataclasses
import math

__all__ = ["PolicyInputs", "ScaleDecision", "BurnRatePolicy"]


@dataclasses.dataclass(frozen=True)
class PolicyInputs:
    """One sampled view of the cluster, as the policy sees it.

    ``burn_rates`` maps SLO spec name -> fastest-window burn rate
    (float, ``math.inf`` allowed; specs with no data are omitted).
    ``prefill_queue`` / ``replica_outstanding`` map worker index ->
    queued request count / un-acked decode sequences.  ``queued_uids``
    counts requests parked on the driver waiting for any prefill slot.
    ``stage_seconds`` maps stage name -> cumulative seconds (fleet
    totals from worker heartbeats), for policies that weigh relative
    stage cost.  ``queued_by_class`` maps priority class -> fleet-wide
    queued-at-prefill count (docs/SERVING.md §10) — journaled with
    every decision, and available to QoS-aware policies that scale on
    high-class backlog rather than total depth.  ``replica_cache`` maps
    replica index -> ``{"value", "sole_hot", "stale"}`` from the
    router's cache digest table (docs/SERVING.md §11): the control
    plane's scale-down victim selection consumes it (evict the
    coldest/most-duplicated cache, never the sole holder of a hot
    prefix), and it is journaled so every scale-down is attributable
    to the cache picture it saw.  ``tp_group`` is the member-process
    count of ONE decode replica (docs/SERVING.md §13) — the policy
    still counts replicas, but a decode decision moves ``tp_group``
    whole processes, so cost-aware policies can weigh it."""

    now: float
    prefill_workers: int
    decode_replicas: int
    burn_rates: dict
    prefill_queue: dict
    replica_outstanding: dict
    queued_uids: int = 0
    stage_seconds: dict = dataclasses.field(default_factory=dict)
    queued_by_class: dict = dataclasses.field(default_factory=dict)
    replica_cache: dict = dataclasses.field(default_factory=dict)
    tp_group: int = 1


@dataclasses.dataclass(frozen=True)
class ScaleDecision:
    """One action the policy wants taken.

    ``action`` is ``"scale_up"`` or ``"scale_down"``; ``role`` is
    ``"prefill"`` or ``"decode"``.  ``cause`` names the signal that
    tripped the threshold and ``observed``/``threshold`` record the
    comparison, so the control journal can show WHY every action
    happened without re-deriving it."""

    action: str
    role: str
    cause: str
    observed: float
    threshold: float


def _worst_burn(burn_rates: dict) -> float:
    """Fastest burn across specs; 0.0 when nothing has data yet."""
    worst = 0.0
    for v in burn_rates.values():
        if v is None:
            continue
        v = float(v)
        if v > worst:
            worst = v
    return worst


class BurnRatePolicy:
    """Threshold policy over burn rate and queue depth, with hysteresis.

    Per tick it emits at most one decision per role — elastic actions
    are deliberately incremental (one worker at a time) so each spawn's
    warmup cost and each retire's drain are observable before the next
    move.  ``cooldown_s`` starts at the *decision* (the control plane
    also calls :meth:`note_action` when IT acts, e.g. a rolling swap,
    so policy and plane share one cooldown clock)."""

    def __init__(self, *, min_prefill: int = 1, max_prefill: int = 4,
                 min_replicas: int = 1, max_replicas: int = 4,
                 up_burn: float = 2.0, down_burn: float = 0.5,
                 up_queue_per_worker: float = 4.0,
                 down_queue_per_worker: float = 0.5,
                 cooldown_s: float = 5.0):
        if min_prefill < 1 or min_replicas < 1:
            raise ValueError("min fleet sizes must be >= 1")
        if max_prefill < min_prefill or max_replicas < min_replicas:
            raise ValueError("max fleet size below min")
        if down_burn >= up_burn:
            raise ValueError(
                f"need down_burn < up_burn for hysteresis, got "
                f"{down_burn} >= {up_burn}")
        self.min_prefill = min_prefill
        self.max_prefill = max_prefill
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_burn = up_burn
        self.down_burn = down_burn
        self.up_queue_per_worker = up_queue_per_worker
        self.down_queue_per_worker = down_queue_per_worker
        self.cooldown_s = cooldown_s
        self._last_action: dict[str, float] = {}

    # ------------------------------------------------------------- decisions

    def note_action(self, role: str, now: float) -> None:
        """Start ``role``'s cooldown at ``now`` (the control plane calls
        this for actions it initiates itself, e.g. swap rolls)."""
        self._last_action[role] = now

    def _cooling(self, role: str, now: float) -> bool:
        return now - self._last_action.get(role, -math.inf) < self.cooldown_s

    def decide(self, inputs: PolicyInputs) -> list[ScaleDecision]:
        """At most one decision per role; deterministic in ``inputs``."""
        out = []
        worst = _worst_burn(inputs.burn_rates)

        # --- prefill: backlog = driver-parked uids + worker queues
        if not self._cooling("prefill", inputs.now):
            n = max(1, inputs.prefill_workers)
            backlog = (inputs.queued_uids
                       + sum(inputs.prefill_queue.values())) / n
            d = None
            if inputs.prefill_workers < self.max_prefill:
                if worst >= self.up_burn:
                    d = ScaleDecision("scale_up", "prefill", "burn_rate",
                                      worst, self.up_burn)
                elif backlog >= self.up_queue_per_worker:
                    d = ScaleDecision("scale_up", "prefill", "queue_depth",
                                      backlog, self.up_queue_per_worker)
            if (d is None and inputs.prefill_workers > self.min_prefill
                    and worst <= self.down_burn
                    and backlog <= self.down_queue_per_worker):
                d = ScaleDecision("scale_down", "prefill", "burn_rate",
                                  worst, self.down_burn)
            if d is not None:
                out.append(d)
                self.note_action("prefill", inputs.now)

        # --- decode: pressure = outstanding sequences per replica
        if not self._cooling("decode", inputs.now):
            n = max(1, inputs.decode_replicas)
            pressure = sum(inputs.replica_outstanding.values()) / n
            d = None
            if inputs.decode_replicas < self.max_replicas:
                if worst >= self.up_burn and pressure >= 1.0:
                    d = ScaleDecision("scale_up", "decode", "burn_rate",
                                      worst, self.up_burn)
                elif pressure >= self.up_queue_per_worker:
                    d = ScaleDecision("scale_up", "decode", "outstanding",
                                      pressure, self.up_queue_per_worker)
            if (d is None and inputs.decode_replicas > self.min_replicas
                    and worst <= self.down_burn
                    and pressure <= self.down_queue_per_worker):
                d = ScaleDecision("scale_down", "decode", "burn_rate",
                                  worst, self.down_burn)
            if d is not None:
                out.append(d)
                self.note_action("decode", inputs.now)

        return out

    def config(self) -> dict:
        """JSON-safe view for the /controlz journal."""
        return {
            "policy": type(self).__name__,
            "min_prefill": self.min_prefill,
            "max_prefill": self.max_prefill,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_burn": self.up_burn,
            "down_burn": self.down_burn,
            "up_queue_per_worker": self.up_queue_per_worker,
            "down_queue_per_worker": self.down_queue_per_worker,
            "cooldown_s": self.cooldown_s,
        }
