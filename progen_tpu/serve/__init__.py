"""Multi-process serving runtime (docs/SERVING.md §7).

PR 8 disaggregated prefill from decode inside one process; this package
moves the stages into separate OS processes, each with its own JAX
runtime:

- **prefill workers** run the bucketed prefill programs and serialize
  the resulting :class:`~progen_tpu.decode.handoff.Handle`\\ s onto a
  host-side socket transport (``decode/handoff.py`` wire format);
- **decode replicas** deserialize handles into the existing donating
  merge via :meth:`ServingEngine.admit_handle` and stream completions
  home;
- the **router** (in the driver process) spreads requests across the
  prefill fleet and handles across R decode replicas
  (least-outstanding-tokens), sheds on deadlines, relays ack credits,
  and — with the resilience layer's :class:`StageSupervisor` — restarts
  a dead stage and replays its in-flight requests (per-request seed
  determinism makes the replay token-identical).

Placement is invisible in the tokens: a multi-process cluster produces
bit-identical completions to the single-process engine on the same
request set, greedy and sampled (``tests/test_serve_multiproc.py``).

The **elastic control plane** (``control.py`` + ``policy.py``) makes
the fleet itself dynamic: SLO-burn-driven autoscaling between min/max
bounds, zero-downtime rolling weight swaps (generation-tagged), and
graceful scale-down with zero sheds — all journaled on ``/controlz``
(``tests/test_elastic.py``).
"""

from progen_tpu.serve.cluster import ServeCluster
from progen_tpu.serve.control import ControlPlane
from progen_tpu.serve.policy import BurnRatePolicy, PolicyInputs, ScaleDecision
from progen_tpu.serve.router import Router
from progen_tpu.serve.worker import build_engine_from_spec, make_spec

__all__ = [
    "BurnRatePolicy",
    "ControlPlane",
    "PolicyInputs",
    "Router",
    "ScaleDecision",
    "ServeCluster",
    "build_engine_from_spec",
    "make_spec",
]
