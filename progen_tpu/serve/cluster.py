"""ServeCluster: spawn, route, supervise the multi-process topology.

The cluster lives in the DRIVER process (bench, ``sample.py --serve
--serve_procs``, tests) and owns:

- the listener socket plus one :class:`Peer` per worker (reader threads
  push events onto one queue — the transport side, allowed to sync);
- the :class:`Router` policy state (admission side, must NOT sync —
  host-sync zone in ``analysis/rules_hostsync.py``);
- the :class:`StageSupervisor` restart budget.

Failure semantics (chaos-tested): a dead stage maps to exactly the
requests whose work it held; those are re-dispatched through the normal
path — a replay is token-identical by per-request seed determinism —
or shed as typed ``FAILED_FAULT`` completions when the stage cannot
come back.  Survivor requests never notice.  Corrupt handle frames
(payload CRC) are reported by the replica and replayed the same way.
"""

from __future__ import annotations

import json
import os
import queue as _queue
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path

import numpy as np

from progen_tpu.decode.engine import (
    DRAIN_TIMEOUT,
    FAILED_FAULT,
    SHED_DEADLINE,
    Completion,
    Request,
)
from progen_tpu.decode.handoff import (
    FrameCorrupt,
    request_to_wire,
    split_handle_frame,
    unpack_frame,
)
from progen_tpu.observe import metrics as _metrics
from progen_tpu.observe import trace as _trace
from progen_tpu.observe.transport import TransportCounters
from progen_tpu.resilience.supervise import StageSupervisor
from progen_tpu.serve.router import Router
from progen_tpu.serve.transport import Peer

_REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def _completion_from_wire(header: dict, submit_time: float,
                          finish_time: float) -> Completion:
    """Wire message → Completion (module-level: builds numpy arrays, so
    it stays OUTSIDE the cluster's host-sync zone)."""
    emb = header.get("embedding")
    return Completion(
        uid=header["uid"],
        prime=np.asarray(header.get("prime", []), np.int32),
        tokens=np.asarray(header.get("tokens", []), np.int32),
        finish_reason=header["finish_reason"],
        submit_time=submit_time, finish_time=finish_time,
        status=header.get("status", "ok"),
        embedding=None if emb is None else np.asarray(emb, np.float32),
        worker_latency=float(header.get("worker_latency", 0.0)))


def _shed_completion(request, status: str, now: float) -> Completion:
    return Completion(
        uid=request.uid,
        prime=np.asarray(list(request.tokens), np.int32),
        tokens=np.asarray([], np.int32),
        finish_reason=status, submit_time=request.submit_time,
        finish_time=now, status=status)


def _deadline_of(request) -> float | None:
    if request.deadline is not None:
        return request.deadline
    if request.ttl is not None:
        return request.submit_time + request.ttl
    return None


def _free_port() -> int:
    """A free loopback port for a tp-group's private coordinator (the
    usual bind-then-close probe; each group incarnation gets a fresh
    one so a respawn never collides with a lingering dead job)."""
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _split_group_frame(frame, group_size: int) -> list:
    """Full handle frame → per-member slab frames (module-level: parses
    and re-packs numpy payloads, so it stays OUTSIDE the cluster's
    host-sync zone).  Validates the frame CRCs — raises
    :class:`FrameCorrupt` on a frame that must not be forwarded."""
    header, payload = unpack_frame(frame)
    return split_handle_frame(header, payload, group_size)


class ServeCluster:
    """N prefill workers + R decode replicas behind one router.

    With ``tp_group=G > 1`` each decode replica is a GROUP of G member
    processes forming one tensor-parallel engine (docs/SERVING.md §13):
    the leader keeps the ``("decode", r)`` key, followers are
    ``("dshard<k>", r)``.  The router still sees ONE replica per group —
    handle frames are split into per-member slabs at relay time, and a
    group lives and dies atomically (any member death fails the whole
    group; respawn brings back all G members on a fresh coordinator)."""

    # class-level default so bare stand-ins built around __new__ (test
    # fixtures, controlz fakes) read as ungrouped fleets
    tp_group = 1

    def __init__(self, spec: dict, *, prefill_procs: int = 1,
                 replicas: int = 1, supervisor: StageSupervisor | None = None,
                 spawn_timeout: float = 300.0, stale_after: float = 300.0,
                 log_dir: str | None = None, route_by_cache: bool = True,
                 tp_group: int = 1):
        self.spec = spec
        self.prefill_procs = prefill_procs
        self.replicas = replicas
        self.tp_group = max(1, int(tp_group))
        self.supervisor = supervisor or StageSupervisor(max_restarts=1)
        self.stale_after = stale_after
        self.counters = TransportCounters()  # router-side, all peers
        self.router = Router(prefill_procs, replicas,
                             route_by_cache=route_by_cache)
        self._ttft: dict = {}                # uid -> driver-clock TTFT (s)
        self._cache_counts: dict = {}        # replica -> (hits, lookups)
        self.completions: dict = {}          # uid -> Completion
        self._new: list[Completion] = []
        self._events: _queue.Queue = _queue.Queue()
        self._peers: dict = {}               # (role, idx) -> Peer
        self._procs: dict = {}               # (role, idx) -> Popen
        self._incarnations: dict = {}        # (role, idx) -> spawn count
        self._handled_dead: set = set()
        self._respawning: set = set()
        self._parked_uids: list = []
        # elastic control-plane state: the fleet and its weights are
        # MUTABLE — see add_worker/fence_worker/retire_worker and
        # begin_generation (serve/control.py drives these)
        self.generation = 0                  # current weight generation
        self._worker_gen: dict = {}          # (role, idx) -> generation
        self._worker_spec: dict = {}         # (role, idx) -> spec Path
        self._retiring: set = set()          # planned exits (no restart)
        self._pending_routable: set = set()  # spawned, awaiting ready
        self._next_idx = {"prefill": prefill_procs, "decode": replicas}
        self._worker_stats: dict = {}
        self._stats_age: dict = {}           # (role, idx) -> capture clock
        self._hb: dict = {}
        self._clock_offsets: dict = {}       # (role, idx) -> min offset (s)
        self._statusz_ports: dict = {}       # (role, idx) -> loopback port
        self._tracer = _trace.get_tracer()
        registry = _metrics.get_registry()
        self._lat = registry.histogram("cluster.latency_s")
        # goodput accounting: served vs typed-shed completions — the two
        # counters the ratio-kind SLO specs divide
        self._ok_ctr = registry.counter("cluster.completions_ok")
        self._shed_ctr = registry.counter("cluster.completions_shed")
        self._shutting_down = False
        # live introspection plane (spec["statusz"]): the driver serves
        # the FLEET view — per-worker registry snapshots (riding the
        # heartbeat/stats frames already) merged bucket-for-bucket with
        # its own registry, plus multi-window SLO burn rates
        self._statusz = None
        self._statusz_providers: dict = {}
        self._slo = None
        self._slo_last = 0.0
        if spec.get("statusz"):
            from progen_tpu.observe.slo import BurnRateTracker, SLOSpec
            from progen_tpu.observe.statusz import StatuszServer

            self._slo = BurnRateTracker((
                SLOSpec(name="latency_p95_2s", target=0.95,
                        metric="cluster.latency_s", threshold_s=2.0),
                SLOSpec(name="goodput", target=0.99, kind="ratio"),
            ))
            self._statusz_providers.update({
                "health": self._statusz_health,
                "status": self._statusz_status,
                "metrics": self.fleet_metrics})
            self._statusz = StatuszServer(
                role="driver", providers=self._statusz_providers)
            self._statusz.start()

        self._tmp = tempfile.TemporaryDirectory(prefix="progen_serve_")
        self.log_dir = Path(log_dir) if log_dir else Path(self._tmp.name)
        self._spec_path = Path(self._tmp.name) / "spec.json"
        self._spec_path.write_text(json.dumps(spec))
        self._spec_paths = {0: self._spec_path}  # generation -> spec file
        for i in range(prefill_procs):
            self._worker_gen[("prefill", i)] = 0
        for i in range(replicas):
            for key in self._group_members(i):
                self._worker_gen[key] = 0

        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(prefill_procs + replicas * self.tp_group + 4)
        self.port = self._listener.getsockname()[1]
        self._accepting = True
        self._acceptor = threading.Thread(target=self._accept_loop,
                                          daemon=True, name="serve-accept")
        self._acceptor.start()

        try:
            for i in range(prefill_procs):
                self._spawn("prefill", i)
            for i in range(replicas):
                if self.tp_group > 1:
                    self._spawn_group(i)
                else:
                    self._spawn("decode", i)
            self._wait_workers(spawn_timeout)
        except Exception:
            self.shutdown(collect_stats=False)
            raise

    # ------------------------------------------------------------- processes

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # each worker is its own single-device JAX runtime; strip the
        # parent's virtual-device / pod topology hints (pattern of
        # __graft_entry__'s respawn)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("TPU_WORKER_HOSTNAMES", None)
        env.setdefault("JAX_PLATFORMS", "cpu")
        flags = [f for f in env.get("XLA_FLAGS", "").split()
                 if not f.startswith("--xla_force_host_platform_device_count")]
        flags.append("--xla_force_host_platform_device_count=1")
        env["XLA_FLAGS"] = " ".join(flags)
        env["PYTHONPATH"] = os.pathsep.join(
            [str(_REPO_ROOT)] + ([env["PYTHONPATH"]]
                                 if env.get("PYTHONPATH") else []))
        return env

    def _spawn(self, role: str, idx: int,
               group: tuple | None = None) -> None:
        # the incarnation nonce rides in every batch id the worker
        # mints: a respawn restarts batch_seq at 0, and without the
        # nonce its ids would collide with the dead incarnation's
        # entries still in the router's bookkeeping
        inc = self._incarnations.get((role, idx), 0)
        self._incarnations[(role, idx)] = inc + 1
        # a worker is pinned to the spec AND generation it was created
        # under — a respawn during a rolling swap must come back on the
        # same weights, or its replays would cross generations
        gen = self._worker_gen.setdefault((role, idx), self.generation)
        spec_path = self._worker_spec.get(
            (role, idx), self._spec_paths.get(gen, self._spec_path))
        log_path = self.log_dir / f"{role}_{idx}.log"
        log = open(log_path, "a")
        env = self._worker_env()
        if group is not None:
            size, rank, gport = group
            env["PROGEN_TPU_TP_GROUP_SIZE"] = str(size)
            env["PROGEN_TPU_TP_GROUP_RANK"] = str(rank)
            env["PROGEN_TPU_TP_GROUP_PORT"] = str(gport)
        proc = subprocess.Popen(
            [sys.executable, "-m", "progen_tpu.serve.worker",
             role, str(idx), str(self.port), str(spec_path),
             str(inc), str(gen)],
            env=env, stdout=log, stderr=subprocess.STDOUT,
            cwd=str(_REPO_ROOT))
        log.close()
        self._procs[(role, idx)] = proc

    def _group_members(self, idx: int) -> list:
        """Member keys of decode replica ``idx``, leader first (a
        one-element list when tp-grouping is off)."""
        return [("decode", idx)] + [(f"dshard{k}", idx)
                                    for k in range(1, self.tp_group)]

    def _is_group_role(self, role) -> bool:
        return self.tp_group > 1 and isinstance(role, str) and (
            role == "decode" or role.startswith("dshard"))

    def _spawn_group(self, idx: int) -> None:
        """Spawn ALL member processes of tp-group replica ``idx``; the
        group coordinator port is allocated fresh per incarnation."""
        gport = _free_port()
        for rank, (role, _) in enumerate(self._group_members(idx)):
            self._spawn(role, idx, group=(self.tp_group, rank, gport))

    def _accept_loop(self) -> None:
        while self._accepting:
            try:
                sock, _ = self._listener.accept()
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer = Peer(sock, self.counters)
            peer.start_reader(self._events)

    def _log_tail(self, role: str, idx: int, n: int = 30) -> str:
        path = self.log_dir / f"{role}_{idx}.log"
        try:
            lines = path.read_text().splitlines()
        except OSError:
            return "<no log>"
        return "\n".join(lines[-n:])

    def _wait_workers(self, timeout: float) -> None:
        """Pump until every spawned worker said hello."""
        deadline = time.perf_counter() + timeout
        want = self.prefill_procs + self.replicas * self.tp_group
        while len(self._peers) < want:
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"cluster handshake timed out: {len(self._peers)}/"
                    f"{want} workers connected")
            for (role, idx), proc in self._procs.items():
                if proc.poll() is not None and (role, idx) not in self._peers:
                    raise RuntimeError(
                        f"worker {role}:{idx} exited rc={proc.returncode} "
                        f"before hello\n--- log tail ---\n"
                        f"{self._log_tail(role, idx)}")
            self._pump(0.2)

    def kill_worker(self, role: str, idx: int) -> None:
        """SIGKILL a stage instance (chaos testing)."""
        proc = self._procs.get((role, idx))
        if proc is not None and proc.poll() is None:
            os.kill(proc.pid, signal.SIGKILL)

    # ------------------------------------------------------- elastic verbs
    # The control plane (serve/control.py) mutates fleet membership and
    # weights through these.  Indices are allocated monotonically and
    # NEVER reused: batch ids stay unique, supervision budgets stay per
    # physical instance, and a retired index can't alias a future one.

    def begin_generation(self, spec: dict) -> int:
        """Register a new weight generation (new checkpoint / LoRA bank
        in ``spec``); workers spawned afterwards serve it.  Existing
        workers keep their own generation — the swap is a rolling
        replace, not an in-place reload."""
        gen = self.generation + 1
        path = Path(self._tmp.name) / f"spec_gen{gen}.json"
        path.write_text(json.dumps(spec))
        self._spec_paths[gen] = path
        self.generation = gen
        self._tracer.event("cluster.generation", generation=gen)
        return gen

    def add_worker(self, role: str, *, generation: int | None = None,
                   warm: bool = True) -> int:
        """Spawn one more stage instance at a fresh index.  The worker
        is NOT routable until its ready frame arrives (with ``warm``,
        the spec forces :meth:`ServingEngine.aot_warmup` before ready —
        warm-before-routable, so scale-up capacity never serves cold).
        Returns the new index; :meth:`wait_routable` blocks on it."""
        gen = self.generation if generation is None else int(generation)
        idx = self._next_idx[role]
        self._next_idx[role] = idx + 1
        key = (role, idx)
        grouped = role == "decode" and self.tp_group > 1
        member_keys = self._group_members(idx) if grouped else [key]
        for k in member_keys:
            self._worker_gen[k] = gen
        if warm:
            base_path = self._spec_paths.get(gen, self._spec_path)
            warm_path = Path(self._tmp.name) / f"spec_gen{gen}_warm.json"
            if not warm_path.exists():
                wspec = json.loads(base_path.read_text())
                wspec["aot_warmup"] = True
                warm_path.write_text(json.dumps(wspec))
            for k in member_keys:
                self._worker_spec[k] = warm_path
        # only the LEADER key gates routability: its ready frame sits
        # behind the group barrier, so leader-ready means group-ready
        self._pending_routable.add(key)
        if role == "prefill":
            self.prefill_procs += 1
        else:
            self.replicas += 1
        self._tracer.event("cluster.scale_up", role=role, idx=idx,
                           generation=gen)
        if grouped:
            self._spawn_group(idx)
        else:
            self._spawn(role, idx)
        return idx

    def wait_routable(self, role: str, idx: int,
                      timeout: float = 300.0) -> None:
        """Pump until the scaled-up worker's ready frame made it
        routable (raises on timeout or if it died before ready without
        a restart grant)."""
        key = (role, idx)
        deadline = time.perf_counter() + timeout
        while key in self._pending_routable:
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"worker {role}:{idx} not routable after {timeout}s"
                    f"\n--- log tail ---\n{self._log_tail(role, idx)}")
            proc = self._procs.get(key)
            if (proc is not None and proc.poll() is not None
                    and key in self._handled_dead
                    and key not in self._respawning):
                raise RuntimeError(
                    f"worker {role}:{idx} died before ready\n"
                    f"--- log tail ---\n{self._log_tail(role, idx)}")
            self._pump(0.1)

    def fence_worker(self, role: str, idx: int) -> None:
        """Stop routing NEW work to a stage instance; its in-flight
        work continues (the drain half of retire/swap)."""
        self.router.fence_worker(role, idx)
        self._tracer.event("cluster.fence", role=role, idx=idx)

    def retire_worker(self, role: str, idx: int, *,
                      timeout: float = 120.0) -> None:
        """Gracefully remove a stage instance with ZERO sheds: fence it,
        send shutdown (the worker loop finishes every queued request and
        ships the results before exiting), then wait for its EOF — the
        dead-peer path sees the planned exit, requeues any leftovers
        through the replay machinery, and removes it everywhere.  On
        timeout the worker is killed; its uids still replay."""
        key = (role, idx)
        self.router.fence_worker(role, idx)
        self._tracer.event("cluster.retire", role=role, idx=idx,
                           generation=self._worker_gen.get(key, 0))
        if key in self._handled_dead and key not in self._respawning:
            # already dead with no respawn in flight: nothing to drain
            self._finalize_retire(role, idx)
            return
        self._retiring.add(key)
        told: set = set()  # peer objects already sent shutdown
        deadline = time.perf_counter() + timeout
        killed = False
        while key in self._retiring:
            peer = self._peers.get(key)
            if peer is not None and peer.alive and id(peer) not in told:
                # covers the initial send AND a respawn that raced the
                # retire (its fresh peer needs the shutdown too)
                told.add(id(peer))
                peer.send_json({"type": "shutdown"})
            if not killed and time.perf_counter() > deadline:
                killed = True
                self.kill_worker(role, idx)
            elif killed and time.perf_counter() > deadline + 10.0:
                # no EOF arrived (e.g. the worker never connected):
                # finalize the bookkeeping directly
                self._finalize_retire(role, idx)
                break
            self._pump(0.05)

    def _finalize_retire(self, role: str, idx: int) -> None:
        """Remove a retired instance from every bookkeeping structure;
        any uids it still held replay through the normal path (typed
        sheds only if the whole stage is gone)."""
        key = (role, idx)
        self._retiring.discard(key)
        self._pending_routable.discard(key)
        if role == "decode":
            for bid in self.router.unacked_batches(idx):
                self._return_credit(bid)
        affected = self.router.fail_worker(role, idx)
        self.router.retire_worker(role, idx)
        self.supervisor.forget(role, idx)
        self._worker_spec.pop(key, None)
        self._worker_gen.pop(key, None)
        if role == "decode" and self.tp_group > 1:
            # followers share the leader's fate: drop their pins too
            for k in self._group_members(idx)[1:]:
                self._worker_spec.pop(k, None)
                self._worker_gen.pop(k, None)
        if role == "prefill":
            self.prefill_procs -= 1
        else:
            self.replicas -= 1
        self._tracer.event("cluster.retired", role=role, idx=idx,
                           replayed=len(affected))
        now = time.perf_counter()
        for uid in affected:
            self._dispatch(uid, now)

    # -------------------------------------------------------------- frontend

    def submit(self, request: Request) -> None:
        """Route one request to a prefill worker; deadline- and
        availability-sheds produce typed completions, never raises for
        operational conditions (mirrors ``ServingEngine.submit``)."""
        if request.uid in self.router.requests:
            raise ValueError(f"duplicate uid {request.uid!r}")
        self._pump(0.0)
        now = time.perf_counter()
        self.router.requests[request.uid] = request
        self.router.submit_times[request.uid] = now
        self._dispatch(request.uid, now)
        self._tracer.add("cluster.submit", now,
                         time.perf_counter() - now, trace=request.uid)

    def submit_embed(self, request: Request) -> None:
        """Route one EMBEDDING request.  Embed traffic is its own request
        class: it rides a prefill worker's engine (prefill-shaped
        forward, no decode slots, no handle), so the router's prefill
        stage bookkeeping covers its whole lifecycle — completion,
        requeue-on-death, shedding all reuse the generate paths."""
        request.workload = "embed"
        self.submit(request)

    def _dispatch(self, uid, now: float) -> None:
        request = self.router.requests[uid]
        deadline = _deadline_of(request)
        if deadline is not None and now > deadline:
            self._shed(uid, SHED_DEADLINE, now)
            return
        w = self.router.pick_prefill(
            priority=getattr(request, "priority", 0))
        if w is None:
            if any(k[0] == "prefill" for k in self._respawning):
                self._parked_uids.append(uid)
                return
            self._shed(uid, FAILED_FAULT, now)
            return
        self.router.assign_prefill(uid, request, w, now)
        self._tracer.event("cluster.place", trace=uid, worker=w)
        peer = self._peers.get(("prefill", w))
        if peer is None or not peer.alive:
            # raced a death the event queue has not surfaced yet; the
            # dead-peer path will pick the uid up via fail_worker
            return
        kind = "embed_req" if getattr(request, "workload",
                                      "generate") == "embed" else "req"
        peer.send_json({"type": kind,
                        "req": request_to_wire(request, now=now)})

    def _shed(self, uid, status: str, now: float) -> None:
        request = self.router.requests[uid]
        if not self.router.complete(uid):
            return
        comp = _shed_completion(request, status, now)
        comp.generation = self.router.generation_of(uid)
        self.completions[uid] = comp
        self._new.append(comp)
        self._shed_ctr.inc()

    def poll(self, timeout: float = 0.0) -> list[Completion]:
        """Process transport events for up to ``timeout`` seconds;
        returns completions that arrived since the last poll."""
        self._pump(timeout)
        out, self._new = self._new, []
        return out

    @property
    def pending(self) -> int:
        return len(self.router.requests) - len(self.router.completed)

    def drain(self, timeout: float = 600.0) -> list[Completion]:
        """Block until every submitted request has completed (served or
        typed-shed); returns ALL completions sorted by uid.

        ``timeout`` is a hard bound: past it every still-open request is
        answered with a typed ``DRAIN_TIMEOUT`` completion instead of
        raising — a wedged worker can no longer stall drain (and thus
        retire/scale-down, which requires bounded drain) forever.  The
        exactly-once contract holds: a late real completion for a
        timed-out uid is dropped by the router's dedup."""
        deadline = time.perf_counter() + timeout
        while self.pending > 0:
            if time.perf_counter() > deadline:
                now = time.perf_counter()
                stuck = [uid for uid in self.router.requests
                         if uid not in self.router.completed]
                for uid in stuck:
                    self._shed(uid, DRAIN_TIMEOUT, now)
                self._tracer.event("cluster.drain_timeout",
                                   timeout_s=timeout, shed=len(stuck))
                break
            self._pump(0.1)
        # freshness flush: ask every live worker for a stats/metrics
        # frame NOW, so post-drain stats() reflects the drained state
        # rather than the last pre-drain heartbeat snapshot
        t_req = time.perf_counter()
        live = [k for k, p in self._peers.items() if p.alive]
        for k in live:
            self._peers[k].send_json({"type": "stats_req"})
        flush_deadline = min(deadline, t_req + 5.0)
        while any(self._stats_age.get(k, -1.0) < t_req for k in live
                  if self._peers.get(k) is not None
                  and self._peers[k].alive):
            if time.perf_counter() > flush_deadline:
                break
            self._pump(0.05)
        return [self.completions[uid] for uid in self.router.requests
                if uid in self.completions]

    # ------------------------------------------------------------ event loop

    def _pump(self, timeout: float) -> None:
        block = timeout > 0.0
        deadline = time.perf_counter() + timeout
        while True:
            try:
                if block:
                    wait = max(0.0, deadline - time.perf_counter())
                    ev = self._events.get(timeout=wait) if wait else \
                        self._events.get_nowait()
                else:
                    ev = self._events.get_nowait()
            except _queue.Empty:
                break
            block = False  # block at most once per pump
            self._handle_event(ev)
        self._check_stale()

    def _handle_event(self, ev) -> None:
        kind, peer = ev[0], ev[1]
        if kind == "dead":
            self._on_peer_dead(peer, ev[2])
            return
        header, frame = ev[2], ev[3]
        t = header.get("type")
        if t == "hello":
            self._on_hello(peer, header)
        elif t == "hb":
            self._note_clock(peer.role, peer.index, header.get("clock"))
            header["age_clock"] = time.perf_counter()
            self._hb[(peer.role, peer.index)] = header
            if peer.role == "decode" and "digest" in header:
                self._note_cache_frame(peer.index, header,
                                       header["age_clock"])
        elif t == "ready":
            # staleness starts here: until ready, the worker is inside
            # its engine build (cold jit can run minutes heartbeat-free)
            peer.ready = True
            key = (peer.role, peer.index)
            if key in self._pending_routable:
                # warm-before-routable: a scaled-up worker joins the
                # routable set only now — its compiles are behind it
                self._pending_routable.discard(key)
                self.router.add_worker(
                    peer.role, peer.index,
                    self._worker_gen.get(key, 0))
                self._tracer.event(
                    "cluster.routable", role=peer.role, idx=peer.index,
                    generation=self._worker_gen.get(key, 0))
                parked, self._parked_uids = self._parked_uids, []
                now = time.perf_counter()
                for uid in parked:
                    self._dispatch(uid, now)
        elif t == "handle":
            self._on_handle(peer, header, frame)
        elif t == "ack":
            self._return_credit(header.get("batch_id"))
        elif t == "bad_frame":
            # payload CRC failed at the replica: typed recovery — the
            # batch's credit goes home and the named requests replay
            # through the normal path
            self._return_credit(header.get("batch_id"))
            now = time.perf_counter()
            for uid in self.router.requeue(header.get("uids", [])):
                self._dispatch(uid, now)
        elif t == "completion":
            uid = header.get("uid")
            if self.router.complete(uid):
                now = time.perf_counter()
                submit = self.router.submit_times.get(uid, 0.0)
                comp = _completion_from_wire(header, submit, now)
                # a uid's generation is the one that PRIMED it (router
                # bookkeeping), not whatever the cluster serves now —
                # in-flight requests finish on their own generation
                comp.generation = self.router.generation_of(uid)
                ttft = self._ttft.pop(uid, None)
                if ttft is not None and submit:
                    comp.first_token_time = submit + ttft
                self.completions[uid] = comp
                self._new.append(comp)
                # the one end-to-end latency code path: the same
                # histogram bench_serving.py reads its p50/p95 from
                self._lat.observe(now - submit if submit else 0.0)
                if header.get("status", "ok") == "ok":
                    self._ok_ctr.inc()
                else:
                    self._shed_ctr.inc()
                self._tracer.event("cluster.done", trace=uid,
                                   latency_s=now - submit)
        elif t == "stats":
            self._note_clock(peer.role, peer.index, header.get("clock"))
            self._worker_stats[(peer.role, peer.index)] = header
            self._stats_age[(peer.role, peer.index)] = time.perf_counter()
            if peer.role == "decode" and "digest" in header:
                self._note_cache_frame(
                    peer.index, header,
                    self._stats_age[(peer.role, peer.index)])

    def _on_hello(self, peer: Peer, header: dict) -> None:
        # index arrives as a JSON int from the worker's hello; no cast —
        # the wire header is parsed host data, and this method sits in a
        # host-sync zone where casts on unproven values flag
        role, idx = header.get("role"), header.get("index", -1)
        peer.role, peer.index = role, idx
        self._peers[(role, idx)] = peer
        if header.get("statusz_port"):
            self._statusz_ports[(role, idx)] = header["statusz_port"]
        # a dead-but-not-yet-restarted stage is visible here before the
        # supervisor acts: up{role,idx} flips 0 in _on_peer_dead and back
        # to 1 on the respawn's hello — mirrored as a tracer event so
        # fleet-membership transitions land on the merged timeline
        _metrics.get_registry().gauge(
            _metrics.labeled("cluster.up", role=role, idx=idx)).set(1.0)
        self._tracer.event("cluster.up", role=role, idx=idx, up=1,
                           generation=header.get("generation", 0))
        self._note_clock(role, idx, header.get("clock"))
        if (role, idx) in self._respawning:
            self._respawning.discard((role, idx))
            self._handled_dead.discard((role, idx))
            if self._is_group_role(role):
                # a tp-group revives as a unit, keyed by its leader:
                # only when the LAST member's hello lands (the group
                # engine needs every member for its collectives)
                if (("decode", idx) not in self._pending_routable
                        and not any(k in self._respawning
                                    for k in self._group_members(idx))):
                    self.router.revive_worker("decode", idx)
                    parked, self._parked_uids = self._parked_uids, []
                    now = time.perf_counter()
                    for uid in parked:
                        self._dispatch(uid, now)
            elif (role, idx) not in self._pending_routable:
                # a pre-ready scale-up respawn stays out of the routable
                # set until its own ready frame (warm-before-routable)
                self.router.revive_worker(role, idx)
                parked, self._parked_uids = self._parked_uids, []
                now = time.perf_counter()
                for uid in parked:
                    self._dispatch(uid, now)

    def _note_cache_frame(self, idx: int, header: dict, at: float) -> None:
        """Feed a decode worker's cache advertisement into the router's
        digest table, mirror its cache gauges as per-worker LABELED
        driver metrics, and refresh the derived fleet hit-rate gauge —
        so the driver's /statusz shows the fleet cache picture without
        a bench run."""
        self.router.note_digest(idx, header["digest"], at)
        m = header.get("metrics") or {}
        registry = _metrics.get_registry()
        vals = {}
        for name in ("engine.prefix_hits", "engine.prefix_lookups",
                     "engine.prefix_pages_shared",
                     "engine.pool_free_pages",
                     "engine.pool_pages_in_use"):
            snap = m.get(name)
            if isinstance(snap, dict) and "value" in snap:
                vals[name] = snap["value"]
                registry.gauge(_metrics.labeled(
                    name, role="decode", idx=idx)).set(snap["value"])
        if "engine.prefix_lookups" in vals:
            self._cache_counts[idx] = (
                vals.get("engine.prefix_hits", 0.0),
                vals["engine.prefix_lookups"])
        hits = sum(h for h, _ in self._cache_counts.values())
        lookups = sum(n for _, n in self._cache_counts.values())
        registry.gauge("cluster.fleet_prefix_hit_rate").set(
            (hits / lookups) if lookups else 0.0)

    def cache_stats(self) -> dict:
        """Fleet cache view for records and /statusz: summed per-replica
        hit counters, the router's routing tallies, and the per-replica
        cache VALUE the scale-down policy consumes."""
        hits = sum(h for h, _ in self._cache_counts.values())
        lookups = sum(n for _, n in self._cache_counts.values())
        return {
            "fleet_prefix_hits": hits,
            "fleet_prefix_lookups": lookups,
            "fleet_prefix_hit_rate": (hits / lookups) if lookups else 0.0,
            "route_by_cache": self.router.route_by_cache,
            "cache_routed": self.router.cache_routed,
            "cache_fallback": self.router.cache_fallback,
            "cache_overridden": self.router.cache_overridden,
            "replica_cache_value": self.router.cache_summary(
                time.perf_counter()),
        }

    def _note_clock(self, role, idx, clock) -> None:
        """Refine the (role, idx) worker's perf_counter offset from a
        clock echo: offset = driver_receive - worker_send overestimates
        the true offset by one network delay, so the MINIMUM over all
        echoes is the tightest causally-safe estimate (driver->worker
        ordering is preserved; docs/OBSERVABILITY.md)."""
        if clock is None:
            return
        off = time.perf_counter() - clock
        prev = self._clock_offsets.get((role, idx))
        if prev is None or off < prev:
            self._clock_offsets[(role, idx)] = off

    def _return_credit(self, batch_id) -> None:
        """Relay one ack credit to the prefill worker that produced
        ``batch_id``.  Called on replica admission AND on every path
        that drops or requeues a noted batch instead (bad frame, dead
        replica, no replica to forward to) — otherwise the producer's
        unacked window leaks a slot per event and the worker stops
        producing handles after ``handoff_depth`` of them.  The router
        yields each batch's credit exactly once, so the drop paths and
        a late replica ack cannot double-grant."""
        src = self.router.ack(batch_id)
        if src is None:
            return
        p = self._peers.get(("prefill", src))
        if p is not None and p.alive:
            p.send_json({"type": "ack", "batch_id": batch_id})

    def _on_handle(self, peer: Peer, header: dict, frame: bytes) -> None:
        t0 = time.perf_counter()
        batch_id = header.get("batch_id")
        uids = [d["uid"] for d in header.get("reqs", [])]
        self.router.note_handle(batch_id, uids, peer.index)
        # routing tags the producer stamped on the handle: another clock
        # echo to tighten the producer's offset estimate, plus two
        # desync tripwires (identity and weight generation) that surface
        # in the trace rather than changing routing — the connection and
        # the router's own bookkeeping stay authoritative
        tc = header.get("trace_ctx") or {}
        self._note_clock("prefill", peer.index, tc.get("clock"))
        src = header.get("src")
        if src is not None and src != peer.index:
            self._tracer.event("handle.src_mismatch", batch_id=batch_id,
                               claimed=src, connection=peer.index)
        gen = header.get("generation")
        noted_gen = self.router.batch_generation(batch_id)
        if gen is not None and noted_gen is not None and gen != noted_gen:
            self._tracer.event("handle.generation_skew", batch_id=batch_id,
                               header_generation=gen, noted=noted_gen)
        # the handle carries each request's first sampled token, so its
        # arrival is the driver-observed TTFT (submit and arrival are
        # both driver clock — no cross-process correction needed); a
        # replayed handle keeps the first stamp, when the token first
        # existed
        for uid in uids:
            st = self.router.submit_times.get(uid)
            if st is not None:
                self._ttft.setdefault(uid, t0 - st)
        # per-generation placement: state primed on gen-G weights may
        # only decode on a gen-G replica (swap correctness/determinism)
        tokens_batch = [self.router.requests[uid].tokens
                        for uid in uids if uid in self.router.requests]
        r = self.router.pick_replica(
            self.router.batch_generation(batch_id),
            tokens_batch=tokens_batch, now=t0)
        if r is None:
            # this batch will never reach replica admission: return its
            # credit before parking/shedding the member requests
            self._return_credit(batch_id)
            now = time.perf_counter()
            if any(k[0] == "decode" for k in self._respawning):
                # replica stage is coming back: send the requests back
                # through prefill once it does
                self._parked_uids.extend(self.router.requeue(uids))
            else:
                for uid in self.router.requeue(uids):
                    self._shed(uid, FAILED_FAULT, now)
            return
        if self.tp_group > 1:
            # tp-group relay re-frames rather than relaying verbatim, so
            # the driver validates the CRCs a lone replica would have —
            # a corrupt frame takes the bad_frame path without being
            # forwarded (the group must never see mismatched slabs)
            try:
                slabs = _split_group_frame(frame, self.tp_group)
            except FrameCorrupt:
                self._return_credit(batch_id)
                now = time.perf_counter()
                for uid in self.router.requeue(uids):
                    self._dispatch(uid, now)
                return
            self.router.forward(batch_id, r, t0)
            for k, member in enumerate(self._group_members(r)):
                mp = self._peers.get(member)
                if mp is not None and mp.alive:
                    mp.send_bytes(slabs[k])
        else:
            self.router.forward(batch_id, r, t0)
            rp = self._peers.get(("decode", r))
            if rp is not None and rp.alive:
                rp.send_bytes(frame)  # verbatim relay: payload zero-copy
        self._tracer.add("cluster.relay", t0, time.perf_counter() - t0,
                         uids=uids, batch_id=batch_id, replica=r)

    def _on_peer_dead(self, peer: Peer, reason: str) -> None:
        if peer.role is None or self._shutting_down:
            return
        key = (peer.role, peer.index)
        if key in self._handled_dead:
            return
        self._handled_dead.add(key)
        _metrics.get_registry().gauge(
            _metrics.labeled("cluster.up", role=peer.role,
                             idx=peer.index)).set(0.0)
        self._tracer.event("cluster.up", role=peer.role, idx=peer.index,
                           up=0, reason=reason)
        proc = self._procs.get(key)
        if proc is not None and proc.poll() is None:
            proc.kill()
        peer.close()
        if self._peers.get(key) is peer:
            del self._peers[key]

        if self._is_group_role(peer.role):
            self._on_group_member_dead(peer, reason)
            return

        if key in self._retiring:
            # planned exit (retire/scale-down/swap): not a failure — no
            # restart budget burned, no respawn; leftovers replay
            self._finalize_retire(peer.role, peer.index)
            return

        if peer.role == "decode":
            # batches forwarded to the dead replica but never admitted:
            # their acks will never arrive, so return each credit now
            for bid in self.router.unacked_batches(peer.index):
                self._return_credit(bid)
        affected = self.router.fail_worker(peer.role, peer.index)
        if self.supervisor.request_restart(peer.role, peer.index, reason):
            self._respawning.add(key)
            self._parked_uids.extend(
                u for u in affected if u not in self._parked_uids)
            self._spawn(peer.role, peer.index)
            # a live sibling can absorb parked work right away
            now = time.perf_counter()
            if (peer.role == "prefill" and self.router.prefill_alive) or \
                    (peer.role == "decode" and self.router.prefill_alive):
                parked, self._parked_uids = self._parked_uids, []
                for uid in parked:
                    self._dispatch(uid, now)
        else:
            now = time.perf_counter()
            for uid in affected:
                self._dispatch(uid, now)  # sheds if the stage is gone

    def _reap_member(self, key) -> None:
        """Kill/close one tp-group member as part of its group's fate
        (the member's own EOF event later early-returns on
        ``_handled_dead``)."""
        self._handled_dead.add(key)
        proc = self._procs.get(key)
        if proc is not None and proc.poll() is None:
            proc.kill()
        p = self._peers.pop(key, None)
        if p is not None:
            p.close()
        _metrics.get_registry().gauge(
            _metrics.labeled("cluster.up", role=key[0],
                             idx=key[1])).set(0.0)

    def _on_group_member_dead(self, peer: Peer, reason: str) -> None:
        """A tp-group lives and dies ATOMICALLY: one member gone means
        the group's collectives can never complete again, so every
        sibling is killed, the router fails the ONE replica the group
        was, and supervision decides ONE restart for all G members (on
        a fresh private coordinator port)."""
        r = peer.index
        if ("decode", r) in self._retiring:
            # planned drain: members exit together, but their EOFs race.
            # Followers' EOFs are noted (handled_dead) and ignored; the
            # LEADER's EOF — last to matter, it ships the final stats —
            # finalizes the whole group.
            if peer.role != "decode":
                return
            for k in self._group_members(r)[1:]:
                self._reap_member(k)
            self._finalize_retire("decode", r)
            return
        for k in self._group_members(r):
            if k != (peer.role, peer.index):
                self._reap_member(k)
                self._tracer.event("cluster.up", role=k[0], idx=k[1],
                                   up=0, reason=f"group fate: {reason}")
        # batches forwarded to the dead group but never admitted: their
        # acks will never arrive, so return each credit now
        for bid in self.router.unacked_batches(r):
            self._return_credit(bid)
        affected = self.router.fail_worker("decode", r)
        if self.supervisor.request_restart("decode", r, reason):
            for k in self._group_members(r):
                self._respawning.add(k)
            self._parked_uids.extend(
                u for u in affected if u not in self._parked_uids)
            self._spawn_group(r)
            now = time.perf_counter()
            if self.router.prefill_alive:
                parked, self._parked_uids = self._parked_uids, []
                for uid in parked:
                    self._dispatch(uid, now)
        else:
            now = time.perf_counter()
            for uid in affected:
                self._dispatch(uid, now)  # sheds if the stage is gone

    def _check_stale(self) -> None:
        if self._shutting_down:
            return
        now = time.perf_counter()
        registry = _metrics.get_registry()
        for (role, idx), hb in list(self._hb.items()):
            seen = hb.get("age_clock")
            if seen is not None:
                # per-worker heartbeat staleness as a typed gauge: a
                # wedged-but-connected stage shows a growing age here
                # before the stale_after trip
                registry.gauge(_metrics.labeled(
                    "cluster.worker_age_s", role=role, idx=idx)
                ).set(round(now - seen, 3))
        if self._slo is not None and now - self._slo_last >= 1.0:
            self._slo_last = now
            self._slo.sample(now, self.fleet_metrics())
        for key, peer in list(self._peers.items()):
            # a peer is exempt until its "ready" frame: engine build
            # sends no heartbeats, and a cold jit compile exceeding
            # stale_after must not burn restart budget on a healthy
            # worker (a build that dies still EOFs its socket)
            if not peer.ready:
                continue
            if peer.alive and now - peer.last_seen > self.stale_after:
                self._events.put(("dead", peer,
                                  f"heartbeat stale > {self.stale_after}s"))
                peer.alive = False

    # --------------------------------------------------------------- teardown

    def shutdown(self, *, collect_stats: bool = True,
                 timeout: float = 30.0) -> dict:
        """Stop the fleet: shutdown messages, final stats collection,
        join (then kill) every child.  Returns :meth:`stats`."""
        self._shutting_down = True
        t_stop = time.perf_counter()
        for peer in list(self._peers.values()):
            if peer.alive:
                peer.send_json({"type": "shutdown"})
        if collect_stats:
            deadline = t_stop + timeout
            want = set(self._peers)
            # wait for stats CAPTURED AFTER the shutdown message — a
            # drain-time stats_req snapshot must not satisfy this, or the
            # final flush (complete transport totals) would be skipped
            while any(self._stats_age.get(k, -1.0) < t_stop for k in want):
                if time.perf_counter() > deadline:
                    break
                self._pump(0.1)
        self._accepting = False
        try:
            self._listener.close()
        except OSError:
            pass
        for key, proc in self._procs.items():
            if proc.poll() is None:
                try:
                    proc.wait(timeout=timeout)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
        for peer in list(self._peers.values()):
            peer.close()
        if self._statusz is not None:
            self._statusz.stop()
        self.dump_trace()
        out = self.stats()
        self._tmp.cleanup()
        return out

    def dump_trace(self) -> str | None:
        """Write the driver's span ring (with the per-worker clock
        offsets as merge metadata) into the spec's trace dir; returns
        the dump path, or None when tracing is off."""
        tcfg = self.spec.get("trace")
        tracer = self._tracer
        if not (tcfg and tcfg.get("dir") and tracer.enabled):
            return None
        tracer.set_meta(offsets={
            f"{role}:{idx}": off
            for (role, idx), off in self._clock_offsets.items()})
        try:
            return tracer.dump(
                _trace.trace_dump_path(tcfg["dir"], tracer.process))
        except OSError as e:
            print(f"cluster: trace dump failed: {e}", file=sys.stderr)
            return None

    # ------------------------------------------------------------- statusz

    def register_statusz_provider(self, name: str, fn) -> None:
        """Expose an extra provider on the driver's statusz server (the
        control plane registers ``control`` here for ``/controlz``).
        No-op when the introspection plane is off."""
        self._statusz_providers[name] = fn

    def fleet_metrics(self) -> dict:
        """Fleet-merged registry snapshot: the driver's own registry plus
        the freshest per-worker snapshot (final stats frame or heartbeat,
        whichever arrived later) — counters/gauges summed, histograms
        merged bucket-for-bucket.  This is what the driver's /metricsz
        serves and what the SLO burn-rate tracker samples."""
        snaps = [_metrics.get_registry().snapshot()]
        for key in set(self._worker_stats) | set(self._hb):
            st = self._worker_stats.get(key)
            hb = self._hb.get(key)
            st_t = self._stats_age.get(key, -1.0)
            hb_t = hb.get("age_clock", -1.0) if hb else -1.0
            pick = st if st_t >= hb_t else hb
            if pick and isinstance(pick.get("metrics"), dict):
                snaps.append(pick["metrics"])
        return _metrics.merge_snapshots(snaps)

    def _statusz_health(self) -> dict:
        now = time.perf_counter()
        peers = {}
        for (role, idx), peer in sorted(self._peers.items()):
            hb = self._hb.get((role, idx), {})
            seen = hb.get("age_clock")
            peers[f"{role}:{idx}"] = {
                "alive": peer.alive,
                "ready": peer.ready,
                "hb_age_s": (round(now - seen, 3)
                             if seen is not None else None),
            }
        return {"pending": self.pending, "peers": peers,
                "supervision": self.supervisor.stats()}

    def _statusz_status(self) -> dict:
        out = self.stats()
        # the fleet-wide view: per-worker registries merged into the
        # driver's (stats() alone reports the driver registry only)
        out["metrics"] = self.fleet_metrics()
        if self._slo is not None:
            # a scrape is a sample point: push the fresh fleet view so
            # the lifetime/burn numbers reflect this instant, not the
            # last 1s-cadence _check_stale tick (a concurrent sample
            # from the serving thread at worst 503s the scrape, which
            # the client retries)
            now = time.perf_counter()
            self._slo.sample(now, out["metrics"])
            out["slo"] = self._slo.evaluate(now)
        return out

    # ------------------------------------------------------------------ stats

    def stats(self) -> dict:
        """Aggregated cluster record fields: router policy state, the
        per-worker stats messages (stage seconds, transport counters,
        queue depths), the router's own transport counters, and the
        supervision history."""
        now = time.perf_counter()
        total = TransportCounters()
        total.merge(self.counters)
        per_worker = {}
        for (role, idx), st in sorted(self._worker_stats.items()):
            entry = {k: v for k, v in st.items() if k != "type"}
            # monotonic age of this snapshot: 0.0s means "captured just
            # now" (the drain/shutdown flush), large means stale
            captured = self._stats_age.get((role, idx))
            if captured is not None:
                entry["age_s"] = round(now - captured, 3)
            per_worker[f"{role}:{idx}"] = entry
            if "transport" in st:
                total.merge(st["transport"])
        heartbeats = {}
        for (role, idx), hb in sorted(self._hb.items()):
            entry = {k: v for k, v in hb.items() if k != "type"}
            seen = entry.pop("age_clock", None)
            if seen is not None:
                entry["age_s"] = round(now - seen, 3)
            heartbeats[f"{role}:{idx}"] = entry
        statusz_ports = {}
        if self._statusz is not None:
            statusz_ports["driver"] = self._statusz.port
        for (role, idx), p in sorted(self._statusz_ports.items()):
            statusz_ports[f"{role}:{idx}"] = p
        return {
            "topology": {"prefill_procs": self.prefill_procs,
                         "replicas": self.replicas,
                         "tp_group": self.tp_group,
                         "generation": self.generation,
                         "retiring": sorted(
                             f"{r}:{i}" for r, i in self._retiring),
                         "pending_routable": sorted(
                             f"{r}:{i}"
                             for r, i in self._pending_routable)},
            **({"statusz_ports": statusz_ports} if statusz_ports else {}),
            "router": self.router.stats(),
            "cache": self.cache_stats(),
            "router_transport": self.counters.as_dict(),
            "transport_total": total.as_dict(),
            "workers": per_worker,
            "heartbeats": heartbeats,
            "metrics": _metrics.get_registry().snapshot(),
            "clock_offsets": {
                f"{role}:{idx}": round(off, 6)
                for (role, idx), off in sorted(self._clock_offsets.items())},
            "supervision": self.supervisor.stats(),
        }
