"""Socket transport: length-prefixed frames between serving processes.

One TCP connection per worker, one frame (``decode/handoff.py`` wire
format) per message.  Control messages are frames with an empty
payload; handle frames carry the serialized state arrays.  The router
relays handle frames VERBATIM — it parses only the prefix + JSON header
(:func:`peek_header`, header-CRC checked) and never touches the
payload, so the payload bytes cross the router zero-copy and a payload
CRC failure is detected exactly once, at the consuming replica.

Threading: each :class:`Peer` owns one daemon reader thread that
pushes ``("frame", peer, header, frame)`` / ``("dead", peer, reason)``
events onto a shared queue.  Reader threads are TRANSPORT threads —
they may sync (serialize/deserialize on worker mains) — while the
router/cluster admission path that consumes the events must not
(``analysis/rules_hostsync.py``).
"""

from __future__ import annotations

import json
import queue
import socket
import threading
import time
import zlib

from progen_tpu.decode.handoff import (
    FRAME_PREFIX_LEN,
    FrameDesync,
    pack_frame,
    parse_prefix,
)

# a frame larger than this is a desynced stream, not a real handle
MAX_FRAME_BYTES = 1 << 32


def _read_exact(sock: socket.socket, n: int, *, first: bool = False) -> bytes:
    """Read exactly ``n`` bytes.  Empty ``b""`` on clean EOF at a frame
    boundary (``first=True``); :class:`FrameDesync` on EOF mid-frame."""
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            if first and not buf:
                return b""
            raise FrameDesync(
                f"connection closed mid-frame ({len(buf)}/{n} bytes)")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock: socket.socket, counters=None) -> bytes | None:
    """Read one complete frame; None on clean EOF at a boundary.

    The prefix and declared lengths are validated here (bad magic or a
    mid-frame EOF raises :class:`FrameDesync` — the stream is
    poisoned); payload CRC is deliberately NOT checked, so relays stay
    zero-copy and the check happens once at the consumer.
    """
    prefix = _read_exact(sock, FRAME_PREFIX_LEN, first=True)
    if not prefix:
        return None
    hlen, plen, _, _ = parse_prefix(prefix)
    if hlen + plen > MAX_FRAME_BYTES:
        raise FrameDesync(f"implausible frame size {hlen + plen}")
    body = _read_exact(sock, hlen + plen)
    frame = prefix + body
    if counters is not None:
        counters.received(len(frame))
    return frame


def send_frame(sock: socket.socket, frame: bytes, counters=None,
               lock: threading.Lock | None = None) -> None:
    if lock is not None:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)
    if counters is not None:
        counters.sent(len(frame))


def peek_header(frame: bytes) -> dict:
    """Parse a frame's JSON header without touching the payload (the
    router's relay path).  Header CRC is verified; payload CRC is not."""
    hlen, _, hcrc, _ = parse_prefix(frame[:FRAME_PREFIX_LEN])
    hdr = frame[FRAME_PREFIX_LEN:FRAME_PREFIX_LEN + hlen]
    if len(hdr) < hlen:
        raise FrameDesync("frame shorter than declared header")
    if zlib.crc32(hdr) != hcrc:
        raise FrameDesync("frame header CRC mismatch")
    try:
        return json.loads(hdr)
    except ValueError as e:
        raise FrameDesync(f"frame header is not JSON: {e}") from e


def connect(port: int, *, host: str = "127.0.0.1", timeout: float = 60.0,
            retry_every: float = 0.2) -> socket.socket:
    """Worker-side connect with retry — the router's listener may come
    up after the worker process does."""
    deadline = time.perf_counter() + timeout
    last: Exception | None = None
    while time.perf_counter() < deadline:
        try:
            sock = socket.create_connection((host, port), timeout=timeout)
        except OSError as e:
            last = e
            time.sleep(retry_every)
            continue
        try:
            # the timeout bounds the CONNECT attempt only: the reader
            # thread blocks in recv() across idle lulls (a prefill
            # worker between requests, a replica mid-decode), and an
            # inherited timeout would surface there as a spurious peer
            # death after the first quiet minute
            sock.settimeout(None)
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            return sock
        except OSError as e:
            sock.close()
            last = e
            time.sleep(retry_every)
    raise ConnectionError(f"could not reach router on port {port}: {last}")


class Peer:
    """One connected serving process, as seen by the router (or the
    router, as seen by a worker).  Identity (``role``/``index``) is
    unknown until the peer's hello frame arrives."""

    def __init__(self, sock: socket.socket, counters=None):
        self.sock = sock
        self.counters = counters
        self.role: str | None = None
        self.index: int | None = None
        self.alive = True
        # set by the cluster when the worker's "ready" frame arrives;
        # staleness is not judged before then (engine build sends no
        # heartbeats and a cold jit compile can take minutes)
        self.ready = False
        self.last_seen = time.perf_counter()
        self._send_lock = threading.Lock()
        self._reader: threading.Thread | None = None

    @property
    def name(self) -> str:
        return f"{self.role or '?'}:{self.index if self.index is not None else '?'}"

    def send_json(self, obj: dict) -> None:
        self.send_bytes(pack_frame(obj))

    def send_bytes(self, frame: bytes) -> None:
        try:
            send_frame(self.sock, frame, self.counters,
                       lock=self._send_lock)
        except OSError:
            # the reader thread reports the death; a failed send is not
            # a separate event (the message is replayed or shed there)
            self.alive = False

    def start_reader(self, events: "queue.Queue") -> None:
        """Spawn the daemon reader: every inbound frame becomes a
        ``("frame", peer, header, frame)`` event; any stream error a
        single ``("dead", peer, reason)`` event."""

        def _run():
            while True:
                try:
                    frame = recv_frame(self.sock, self.counters)
                except (FrameDesync, OSError) as e:
                    if self.counters is not None and \
                            isinstance(e, FrameDesync):
                        self.counters.desyncs += 1
                    self.alive = False
                    events.put(("dead", self, str(e)))
                    return
                if frame is None:
                    self.alive = False
                    events.put(("dead", self, "eof"))
                    return
                self.last_seen = time.perf_counter()
                try:
                    header = peek_header(frame)
                except FrameDesync as e:
                    if self.counters is not None:
                        self.counters.desyncs += 1
                    self.alive = False
                    events.put(("dead", self, str(e)))
                    return
                events.put(("frame", self, header, frame))

        self._reader = threading.Thread(
            target=_run, daemon=True,
            name=f"peer-reader-{self.name}")
        self._reader.start()

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass
