"""Elastic serving control plane: SLO-burn-driven fleet autoscaling,
zero-downtime weight hot-swap, and rolling worker upgrades.

:class:`ControlPlane` runs on the driver alongside :class:`ServeCluster`
(it is NOT another process — elasticity decisions need the router's
host-side view, which lives here already).  The drive loop calls
:meth:`tick` between poll rounds; each tick

1. **samples** the live signals: multi-window SLO burn rates from
   :class:`~progen_tpu.observe.slo.BurnRateTracker` over the
   fleet-merged registry, per-prefill assigned load and per-replica
   outstanding decode tokens from the router, driver-parked request
   count, and fleet ``stage_seconds`` from worker heartbeats;
2. **asks the policy** (``serve/policy.py`` — pure, deterministic,
   cooldown/hysteresis inside) for at most one action per stage;
3. **executes** through the cluster's elastic verbs — scale-up spawns a
   fresh index through the supervised path with AOT warmup forced
   before its ready frame (warm-before-routable), scale-down fences the
   least-loaded instance and retires it with zero sheds (the worker
   drains its own queue; leftovers replay);
4. **journals** the decision as a typed event with the cause signal and
   observed values, mirrored to the tracer (``control.*`` spans in the
   merged Perfetto timeline) and the metrics registry
   (``control.scale_up``/``control.scale_down`` counters,
   ``control.prefill_workers``/``control.decode_replicas``/
   ``control.generation`` gauges), and surfaced as ``/controlz`` on the
   driver's statusz server.

:meth:`swap_weights` is the rolling upgrade: register the new weights
as a **generation** (``cluster.begin_generation``), bring up new-gen
decode replicas first (warm, routable), then roll prefill one instance
at a time — spawn the replacement on the new weights, wait routable,
fence + drain + retire the old one — so placement capacity never dips
and no request is dropped.  Requests prefilled on the old generation
keep decoding on old-generation replicas (the router routes handles by
the generation that primed them); once ``generation_in_flight(old)``
hits zero the old replicas retire.  Every completion carries the
generation tag of the weights that produced it.
"""

from __future__ import annotations

import math
import time

from progen_tpu.observe import metrics as _metrics
from progen_tpu.observe import trace as _trace
from progen_tpu.serve.policy import BurnRatePolicy, PolicyInputs

__all__ = ["ControlPlane"]

_JOURNAL_CAP = 512


def _worst_burns(slo_results) -> dict:
    """Per-spec fastest burn across trailing windows (falling back to
    the lifetime rate when no window has data); ``inf`` strings from
    the JSON-safe form come back as ``math.inf``."""

    def _num(r):
        if r is None:
            return None
        return math.inf if r == "inf" else float(r)

    out = {}
    for res in slo_results:
        worst = None
        for w in res.get("windows", {}).values():
            r = _num(w.get("burn_rate"))
            if r is not None and (worst is None or r > worst):
                worst = r
        if worst is None:
            worst = _num(res.get("burn_rate"))
        if worst is not None:
            out[res["name"]] = worst
    return out


class ControlPlane:
    """Drives a :class:`ServeCluster`'s fleet size and weights.

    ``policy`` defaults to a :class:`BurnRatePolicy` seeded with the
    cluster's current topology as both min and starting point.  The SLO
    tracker is shared with the cluster's statusz plane when that is on
    (one tracker, one set of ``slo.*`` gauges); otherwise the control
    plane keeps its own private tracker over the same fleet-merged
    snapshot."""

    def __init__(self, cluster, policy=None, *, slo_specs=None):
        self.cluster = cluster
        self.policy = policy or BurnRatePolicy(
            min_prefill=cluster.prefill_procs,
            max_prefill=cluster.prefill_procs + 2,
            min_replicas=cluster.replicas,
            max_replicas=cluster.replicas + 2)
        self.journal: list[dict] = []
        self.ticks = 0
        self.swaps = 0
        self._last_inputs: dict = {}
        self._tracer = _trace.get_tracer()
        registry = _metrics.get_registry()
        self._up_ctr = registry.counter("control.scale_up")
        self._down_ctr = registry.counter("control.scale_down")
        self._swap_ctr = registry.counter("control.swaps")
        self._g_prefill = registry.gauge("control.prefill_workers")
        self._g_replicas = registry.gauge("control.decode_replicas")
        self._g_gen = registry.gauge("control.generation")
        if slo_specs is not None or cluster._slo is None:
            from progen_tpu.observe.slo import BurnRateTracker, SLOSpec

            self._slo = BurnRateTracker(slo_specs if slo_specs is not None
                                        else (
                SLOSpec(name="latency_p95_2s", target=0.95,
                        metric="cluster.latency_s", threshold_s=2.0),
                SLOSpec(name="goodput", target=0.99, kind="ratio"),
            ), windows=(10.0, 60.0, 300.0))
        else:
            self._slo = cluster._slo
        cluster.register_statusz_provider("control", self.controlz)

    # --------------------------------------------------------------- signals

    def gather(self, now: float | None = None) -> PolicyInputs:
        """Sample the cluster into one :class:`PolicyInputs` (also what
        :meth:`tick` journals as the decision's observed context)."""
        if now is None:
            now = time.perf_counter()
        c = self.cluster
        self._slo.sample(now, c.fleet_metrics())
        burns = _worst_burns(self._slo.evaluate(now))
        stage_seconds: dict = {}
        for hb in c._hb.values():
            ss = hb.get("stage_seconds")
            for k, v in (ss.items() if ss else ()):
                stage_seconds[k] = stage_seconds.get(k, 0.0) + float(v)
        return PolicyInputs(
            now=now,
            prefill_workers=c.prefill_procs,
            decode_replicas=c.replicas,
            burn_rates=burns,
            prefill_queue=dict(c.router.prefill_load),
            replica_outstanding=dict(c.router.outstanding),
            queued_uids=len(c._parked_uids),
            stage_seconds=stage_seconds,
            queued_by_class=c.router.queued_by_class(),
            replica_cache=c.router.cache_summary(now),
            # tolerate pre-tp-group cluster stand-ins (test fakes)
            tp_group=getattr(c, "tp_group", 1),
        )

    # ----------------------------------------------------------------- ticks

    def tick(self, now: float | None = None) -> list[dict]:
        """One control round: gather → decide → execute → journal.
        Scale-up is non-blocking (the new worker warms and becomes
        routable through the normal event pump); scale-down drains the
        victim before returning.  Returns the journal entries added."""
        inputs = self.gather(now)
        self.ticks += 1
        self._last_inputs = {
            "now": round(inputs.now, 3),
            "prefill_workers": inputs.prefill_workers,
            "decode_replicas": inputs.decode_replicas,
            "burn_rates": {k: ("inf" if v == math.inf else round(v, 4))
                           for k, v in inputs.burn_rates.items()},
            "prefill_queue": dict(inputs.prefill_queue),
            "replica_outstanding": dict(inputs.replica_outstanding),
            "queued_uids": inputs.queued_uids,
            "queued_by_class": dict(inputs.queued_by_class),
            "replica_cache": {i: dict(v) for i, v in
                              inputs.replica_cache.items()},
            "tp_group": inputs.tp_group,
        }
        added = []
        for d in self.policy.decide(inputs):
            if d.action == "scale_up":
                idx = self.cluster.add_worker(d.role)
                self._up_ctr.inc()
            else:
                idx = self._pick_victim(d.role)
                if idx is None:
                    continue
                self.cluster.retire_worker(d.role, idx)
                self._down_ctr.inc()
            added.append(self._journal(
                d.action, inputs.now, role=d.role, idx=idx, cause=d.cause,
                observed=(("inf" if d.observed == math.inf
                           else round(d.observed, 4))),
                threshold=d.threshold))
        self._g_prefill.set(self.cluster.prefill_procs)
        self._g_replicas.set(self.cluster.replicas)
        self._g_gen.set(self.cluster.generation)
        return added

    def _pick_victim(self, role: str) -> int | None:
        """Scale-down victim (never one still warming up, never one
        already fenced).  Prefill: least queued.  Decode: CACHE-VALUED —
        among replicas with a fresh digest, evict the one whose cached
        pages are coldest/most-duplicated (lowest cache value, load as
        tie-break) and NEVER the sole live holder of a hot (actively
        shared) prefix; when every fresh replica is a sole holder, a
        stale-digest replica is sacrificed on load alone (its contents
        are unknown, not known-precious); when no digest is fresh the
        selection degrades to the pre-cache load-only rule.  Returns
        None when nothing is safely evictable."""
        r = self.cluster.router
        if role == "prefill":
            live = {i for i in r._placeable_prefill()
                    if (role, i) not in self.cluster._pending_routable}
            if len(live) <= 1:
                return None
            return min(sorted(live), key=lambda i: r.prefill_load.get(i, 0))
        live = {i for i in r._placeable_replicas()
                if (role, i) not in self.cluster._pending_routable}
        if len(live) <= 1:
            return None
        summary = r.cache_summary(time.perf_counter())

        def ent(i):
            return summary.get(i, {"stale": True, "value": 0.0,
                                   "sole_hot": False})

        fresh = [i for i in sorted(live) if not ent(i)["stale"]]
        if not fresh:
            # no cache knowledge at all: the pre-cache load-only rule
            return min(sorted(live), key=lambda i: r.outstanding.get(i, 0))
        cand = [i for i in fresh if not ent(i)["sole_hot"]]
        if cand:
            return min(cand, key=lambda i: (ent(i)["value"],
                                            r.outstanding.get(i, 0)))
        stale = [i for i in sorted(live) if ent(i)["stale"]]
        if stale:
            return min(stale, key=lambda i: r.outstanding.get(i, 0))
        return None  # every replica is the sole holder of a hot prefix

    # ------------------------------------------------------------------ swap

    def swap_weights(self, spec: dict | None = None, *,
                     checkpoint_path: str | None = None,
                     lora: dict | None = None,
                     timeout: float = 300.0) -> int:
        """Rolling zero-downtime weight swap; returns the new
        generation.  ``spec`` replaces the worker spec outright;
        otherwise the cluster's current spec is cloned with
        ``checkpoint_path`` and/or ``lora`` overridden.

        Sequence (capacity never dips, nothing is dropped):

        1. new-generation decode replicas spawn (warm) and become
           routable — one per live old-generation replica;
        2. prefill rolls ONE instance at a time: spawn replacement on
           the new generation, wait routable, fence + drain + retire
           the old one (its queued requests finish and ship);
        3. wait until no in-flight request primed on the old generation
           remains (they decode on the old replicas they were primed
           for), then retire the old replicas.
        """
        c = self.cluster
        old_gen = c.generation
        if spec is None:
            spec = dict(c.spec)
            if checkpoint_path is not None:
                spec["checkpoint_path"] = checkpoint_path
            if lora is not None:
                spec["lora"] = dict(lora)
        gen = c.begin_generation(spec)
        t0 = time.perf_counter()
        self._journal("swap_begin", t0, old_generation=old_gen,
                      generation=gen,
                      lora=bool(spec.get("lora")),
                      checkpoint=bool(spec.get("checkpoint_path")))

        old_replicas = sorted(
            i for i, g in c.router.replica_gen.items()
            if g == old_gen and i in c.router.replica_alive)
        old_prefill = sorted(
            i for i, g in c.router.prefill_gen.items()
            if g == old_gen and i in c.router.prefill_alive)

        # 1. new-gen decode capacity first: a new-gen prefill's handles
        # need somewhere to decode the moment it becomes routable
        for _ in old_replicas:
            idx = c.add_worker("decode", generation=gen)
            c.wait_routable("decode", idx, timeout)
            self._journal("swap_roll", time.perf_counter(), role="decode",
                          up=idx, generation=gen)

        # 2. roll prefill one at a time — replacement routable BEFORE
        # the old one fences, so placement capacity never dips
        for old_idx in old_prefill:
            idx = c.add_worker("prefill", generation=gen)
            c.wait_routable("prefill", idx, timeout)
            c.retire_worker("prefill", old_idx)
            self._journal("swap_roll", time.perf_counter(), role="prefill",
                          up=idx, down=old_idx, generation=gen)

        # 3. in-flight old-gen requests finish where they were primed
        deadline = time.perf_counter() + timeout
        while c.router.generation_in_flight(old_gen) > 0:
            if time.perf_counter() > deadline:
                raise RuntimeError(
                    f"swap: {c.router.generation_in_flight(old_gen)} "
                    f"gen-{old_gen} requests still in flight after "
                    f"{timeout}s")
            c._pump(0.05)
        for idx in old_replicas:
            c.retire_worker("decode", idx)

        self.swaps += 1
        self._swap_ctr.inc()
        self.policy.note_action("prefill", time.perf_counter())
        self.policy.note_action("decode", time.perf_counter())
        self._g_gen.set(c.generation)
        self._journal("swap_done", time.perf_counter(),
                      generation=gen, old_generation=old_gen,
                      duration_s=round(time.perf_counter() - t0, 3))
        return gen

    # --------------------------------------------------------------- journal

    def _journal(self, event: str, at: float, **fields) -> dict:
        entry = {"event": event, "at": round(at, 3), **fields,
                 "signals": dict(self._last_inputs.get("burn_rates", {}))}
        self.journal.append(entry)
        if len(self.journal) > _JOURNAL_CAP:
            del self.journal[:len(self.journal) - _JOURNAL_CAP]
        self._tracer.event(f"control.{event}", **{
            k: v for k, v in entry.items() if k not in ("event", "at")})
        return entry

    def controlz(self) -> dict:
        """The ``/controlz`` payload: policy config, decision journal,
        live fleet state, last sampled signals."""
        c = self.cluster
        return {
            "policy": self.policy.config(),
            "ticks": self.ticks,
            "swaps": self.swaps,
            "generation": c.generation,
            "fleet": {
                "prefill_procs": c.prefill_procs,
                "replicas": c.replicas,
                "tp_group": getattr(c, "tp_group", 1),
                "pending_routable": sorted(
                    f"{r}:{i}" for r, i in c._pending_routable),
                "retiring": sorted(f"{r}:{i}" for r, i in c._retiring),
                "worker_generations": {
                    f"{r}:{i}": g
                    for (r, i), g in sorted(c._worker_gen.items())},
            },
            "last_inputs": self._last_inputs,
            "journal": self.journal[-128:],
        }
