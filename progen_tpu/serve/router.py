"""Router policy: request → prefill worker, handle → decode replica.

Pure host-side bookkeeping, deliberately free of sockets and JAX so the
placement/failure logic is unit-testable (``tests/test_serve_multiproc.py``)
and syncs are structurally impossible — the module sits inside a
graftcheck host-sync zone (``analysis/rules_hostsync.py``).

Policy:

- requests go to the LEAST-LOADED live prefill worker (queued-request
  count — prefill cost is per request, not per token);
- handles go to the live replica holding the LONGEST CACHED PREFIX of
  the batch's requests (scored against the per-replica digest table the
  workers advertise on heartbeats, plus an optimistic overlay for
  handles forwarded since the last digest), ties and cache misses
  broken by LEAST OUTSTANDING TOKENS (the decode budget a replica is
  still on the hook for: sum of ``max_new_tokens`` forwarded minus
  completed) — a digest older than ``digest_ttl`` is STALE and scores
  zero, so a silent worker degrades to the load-only policy rather
  than attracting traffic on dead information;
- every request's stage is tracked (``prefill → handle → replica``), so
  a dead stage maps to exactly the uids whose work it held:
  :meth:`fail_worker` returns them for replay (seed determinism makes
  replays token-identical) or typed shedding — never an exception.

The control plane (``serve/control.py``) mutates the routable set at
runtime: :meth:`add_worker` grows a stage, :meth:`fence_worker` stops
new placements without touching in-flight bookkeeping (drain), and
:meth:`retire_worker` removes a fully drained instance.  Every worker
carries a weight **generation**; a uid is stamped with the generation
of the prefill worker that primed it and its handle may only decode on
a replica of the same generation, so per-generation determinism holds
across a rolling weight swap.
"""

from __future__ import annotations

from progen_tpu.decode.paging import token_span_digest


class Router:
    """Placement + lifecycle bookkeeping for one serving cluster."""

    def __init__(self, prefill_workers: int, replicas: int, *,
                 route_by_cache: bool = True, digest_ttl: float = 5.0,
                 cache_imbalance_tokens: int = 32):
        if prefill_workers < 1 or replicas < 1:
            raise ValueError("need at least one prefill worker and one "
                             "replica")
        self.route_by_cache = bool(route_by_cache)
        self.digest_ttl = float(digest_ttl)
        # affinity load guard: a cache-holding replica may run at most
        # this many outstanding tokens AHEAD of the least-loaded one
        # before placement reverts to load-only — affinity must never
        # serialize the fleet onto one hot replica
        self.cache_imbalance_tokens = int(cache_imbalance_tokens)
        self.prefill_alive = set(range(prefill_workers))
        self.replica_alive = set(range(replicas))
        self.prefill_fenced: set = set()  # alive but not placeable (draining)
        self.replica_fenced: set = set()
        self.prefill_gen = {w: 0 for w in range(prefill_workers)}
        self.replica_gen = {r: 0 for r in range(replicas)}
        self.prefill_load = {w: 0 for w in range(prefill_workers)}
        # worker -> {priority class -> queued count}: the QoS view of
        # prefill_load, kept in lockstep by the same transitions
        self.prefill_class_load: dict = {
            w: {} for w in range(prefill_workers)}
        self.outstanding = {r: 0 for r in range(replicas)}
        self.requests: dict = {}          # uid -> Request
        self.stage: dict = {}             # uid -> ("prefill"|"handle"|"replica", key)
        self.batches: dict = {}           # batch_id -> {uids, src, replica, acked, open, gen}
        self._uid_batch: dict = {}        # uid -> batch_id it last rode in
        self.uid_gen: dict = {}           # uid -> generation that primed it
        self.completed: set = set()
        self.submit_times: dict = {}      # uid -> router perf_counter instant
        self.max_prefill_queue = 0
        self.max_outstanding = 0
        # replica -> {"keys": {(upto, digest): refcount}, "at": clock,
        # "page_size", "free", "cached", "capacity"} — last advertised
        # cache digest; "at" is on the ROUTER clock (the cluster stamps
        # arrival), so staleness needs no cross-process clock agreement
        self.replica_digest: dict = {}
        # replica -> {(upto, digest): forwarded-at}: prefixes we just
        # routed there and EXPECT cached before the next digest lands —
        # keeps back-to-back same-prefix placements sticky instead of
        # oscillating on heartbeat cadence
        self._optimistic: dict = {}
        self._page_size_hint = 0
        self.cache_routed = 0
        self.cache_fallback = 0
        self.cache_overridden = 0

    # ------------------------------------------------------------- placement

    def _placeable_prefill(self) -> set:
        return self.prefill_alive - self.prefill_fenced

    def _placeable_replicas(self) -> set:
        return self.replica_alive - self.replica_fenced

    def pick_prefill(self, priority: int = 0) -> int | None:
        """Least queued-requests live, unfenced prefill worker; None
        when the whole stage is down or fenced (caller sheds/parks).
        A request lands where the least work of its OWN class or above
        is queued (ties broken by total load) — lower-class backlog
        doesn't repel a high-priority request, since each worker's
        engine schedules it past that backlog anyway.  With uniform
        priorities both keys equal total load: pre-QoS placement."""
        live = self._placeable_prefill()
        if not live:
            return None

        def contending(w: int) -> int:
            return sum(n for p, n in self.prefill_class_load[w].items()
                       if p >= priority)

        return min(sorted(live),
                   key=lambda w: (contending(w), self.prefill_load[w]))

    def pick_replica(self, generation: int | None = None, *,
                     tokens_batch=None,
                     now: float | None = None) -> int | None:
        """Longest-cached-prefix live, unfenced replica (least
        outstanding tokens as tie-break and as the fallback when no
        fresh digest matches anything).  With ``generation`` set, only
        replicas serving that weight generation qualify — a handle
        primed on gen-G weights must decode on gen-G weights or
        determinism (and the swap contract) breaks.  ``tokens_batch``
        is the token sequences riding the handle; cache scoring needs
        it and ``now`` (router clock) — without them, or with
        ``route_by_cache=False``, placement is load-only.  Placement is
        a PERFORMANCE hint: a mispredicted hit costs pool pages, never
        tokens."""
        live = self._placeable_replicas()
        if generation is not None:
            live = {r for r in live
                    if self.replica_gen.get(r, 0) == generation}
        if not live:
            return None
        order = sorted(live)
        if self.route_by_cache and tokens_batch and now is not None:
            scores = {r: self._cache_score(r, tokens_batch, now)
                      for r in order}
            best = max(scores.values())
            if best > 0:
                cand = [r for r in order if scores[r] == best]
                pick = min(cand, key=lambda r: self.outstanding[r])
                least = min(order, key=lambda r: self.outstanding[r])
                if (self.outstanding[pick] - self.outstanding[least]
                        <= self.cache_imbalance_tokens):
                    self.cache_routed += 1
                    return pick
                # the cache holder is too far ahead of the least-loaded
                # replica: spill there instead — a cold prefill beats a
                # hot queue
                self.cache_overridden += 1
                return least
            self.cache_fallback += 1
        return min(order, key=lambda r: self.outstanding[r])

    def _cache_score(self, replica: int, tokens_batch, now: float) -> int:
        """Pages of the batch's primes already cached on ``replica``:
        for each request, the longest CONTIGUOUS run of full prime pages
        present in the replica's advertised (or optimistic) key set —
        the same run the engine's planner can actually share.  A stale
        digest scores 0 (fallback contract)."""
        ent = self.replica_digest.get(replica)
        keys = {}
        if ent is not None and now - ent["at"] <= self.digest_ttl:
            keys = ent["keys"]
        opt = self._optimistic.get(replica, {})
        ps = (ent or {}).get("page_size") or self._page_size_hint
        if not ps or (not keys and not opt):
            return 0
        score = 0
        for tokens in tokens_batch:
            for j in range(1, len(tokens) // ps + 1):
                k = (j * ps, token_span_digest(tokens, j * ps))
                if k in keys:
                    score += 1
                elif k in opt and now - opt[k] <= self.digest_ttl:
                    score += 1
                else:
                    break
        return score

    def note_digest(self, index: int, digest: dict, now: float) -> None:
        """Install a replica's freshly advertised cache digest.  Keys
        collapse to ``(upto, token-digest)`` — the prefill bucket
        (``p_pad``) in the pool's key is dropped, because at routing
        time the handle's bucket is already fixed and a bucket-mismatch
        "hit" merely degrades to a fresh allocation on the replica.
        Fresh truth supersedes the optimistic overlay."""
        keys: dict = {}
        for row in digest.get("keys", ()):
            _p_pad, upto, dg, ref = row
            k = (int(upto), dg)
            keys[k] = max(keys.get(k, 0), int(ref))
        ps = int(digest.get("page_size", 0))
        if ps:
            self._page_size_hint = ps
        self.replica_digest[index] = {
            "keys": keys, "at": float(now), "page_size": ps,
            "free": int(digest.get("free", 0)),
            "cached": int(digest.get("cached", 0)),
            "capacity": int(digest.get("capacity", 0)),
        }
        opt = self._optimistic.get(index)
        if opt:
            for k in list(opt):
                if k in keys or now - opt[k] > self.digest_ttl:
                    del opt[k]

    # ------------------------------------------------------------- lifecycle

    def assign_prefill(self, uid, request, worker: int, now: float) -> None:
        self.requests[uid] = request
        self.submit_times.setdefault(uid, now)
        self.stage[uid] = ("prefill", worker)
        self.uid_gen[uid] = self.prefill_gen.get(worker, 0)
        self.prefill_load[worker] += 1
        cl = self.prefill_class_load.setdefault(worker, {})
        p = getattr(request, "priority", 0)
        cl[p] = cl.get(p, 0) + 1
        self.max_prefill_queue = max(self.max_prefill_queue,
                                     self.prefill_load[worker])

    def _dec_prefill(self, worker, uid) -> None:
        """Undo one ``assign_prefill`` unit of load (stage left prefill:
        handed off, completed, or requeued)."""
        if worker in self.prefill_load:
            self.prefill_load[worker] = max(
                0, self.prefill_load[worker] - 1)
        cl = self.prefill_class_load.get(worker)
        r = self.requests.get(uid)
        if cl is not None and r is not None:
            p = getattr(r, "priority", 0)
            left = cl.get(p, 0) - 1
            if left > 0:
                cl[p] = left
            else:
                cl.pop(p, None)

    def note_handle(self, batch_id: str, uids, src: int) -> None:
        """A prefill worker shipped a handle covering ``uids``.  The
        batch entry lives until its credit is returned (:meth:`ack`)
        AND every member uid has resolved (completed or requeued) —
        then it is pruned, so long-running clusters don't grow."""
        self.batches[batch_id] = {"uids": list(uids), "src": src,
                                  "replica": None, "acked": False,
                                  "open": set(uids),
                                  "gen": self.prefill_gen.get(src, 0)}
        for uid in uids:
            self._uid_batch[uid] = batch_id
            if self.stage.get(uid, (None,))[0] == "prefill":
                self._dec_prefill(src, uid)
            self.stage[uid] = ("handle", batch_id)

    def forward(self, batch_id: str, replica: int,
                now: float | None = None) -> None:
        """The router relayed the handle frame to ``replica``.  With
        ``now`` set and cache routing on, the batch's full prime pages
        enter the replica's optimistic overlay — the replica will cache
        them on admission, and waiting a heartbeat to learn that would
        scatter a same-prefix burst across the fleet."""
        b = self.batches[batch_id]
        b["replica"] = replica
        for uid in b["uids"]:
            if uid in self.completed:
                continue
            self.stage[uid] = ("replica", replica)
            r = self.requests[uid]
            self.outstanding[replica] += int(r.max_new_tokens)
            ps = self._page_size_hint
            if now is not None and self.route_by_cache and ps:
                opt = self._optimistic.setdefault(replica, {})
                for j in range(1, len(r.tokens) // ps + 1):
                    opt.setdefault(
                        (j * ps, token_span_digest(r.tokens, j * ps)), now)
        self.max_outstanding = max(self.max_outstanding,
                                   self.outstanding[replica])

    def ack(self, batch_id: str) -> int | None:
        """Return the batch's credit: marks it acked and returns the
        producing worker so the cluster can relay the grant — None for
        an unknown OR already-acked batch.  Each batch yields exactly
        one credit ever, whether it came from replica admission or from
        a drop path (bad frame, dead replica, no replica to forward
        to), so a duplicate or late ack can never leak a grant."""
        b = self.batches.get(batch_id)
        if b is None or b["acked"]:
            return None
        b["acked"] = True
        src = b["src"]
        self._drop_batch_if_done(batch_id)
        return src

    def unacked_batches(self, replica: int) -> list:
        """Batch ids forwarded to ``replica`` whose admission ack never
        came back — when the replica dies, each still pins one credit
        of its producer's window until the cluster returns it."""
        return [bid for bid, b in self.batches.items()
                if b["replica"] == replica and not b["acked"]]

    def _drop_batch_if_done(self, batch_id) -> None:
        b = self.batches.get(batch_id)
        if b is not None and b["acked"] and not b["open"]:
            del self.batches[batch_id]

    def _leave_batch(self, uid) -> None:
        """``uid`` resolved (completed or requeued): release its seat in
        the batch it last rode in, pruning the entry once empty+acked."""
        batch_id = self._uid_batch.pop(uid, None)
        if batch_id is None:
            return
        b = self.batches.get(batch_id)
        if b is not None:
            b["open"].discard(uid)
            self._drop_batch_if_done(batch_id)

    def complete(self, uid) -> bool:
        """Record a completion; False if ``uid`` already completed (a
        replayed duplicate — identical by determinism, dropped)."""
        if uid in self.completed or uid not in self.requests:
            return False
        self.completed.add(uid)
        kind, key = self.stage.pop(uid, (None, None))
        if kind == "prefill" and key in self.prefill_load:
            self._dec_prefill(key, uid)
        elif kind == "replica" and key in self.outstanding:
            r = self.requests[uid]
            self.outstanding[key] = max(
                0, self.outstanding[key] - int(r.max_new_tokens))
        self._leave_batch(uid)
        return True

    def requeue(self, uids) -> list:
        """Clear stage bookkeeping for ``uids`` (bad frame / dead stage)
        so the cluster can re-dispatch them; returns the live subset."""
        out = []
        for uid in uids:
            if uid in self.completed or uid not in self.requests:
                continue
            kind, key = self.stage.pop(uid, (None, None))
            if kind == "prefill" and key in self.prefill_load:
                self._dec_prefill(key, uid)
            elif kind == "replica" and key in self.outstanding:
                r = self.requests[uid]
                self.outstanding[key] = max(
                    0, self.outstanding[key] - int(r.max_new_tokens))
            self._leave_batch(uid)
            out.append(uid)
        return out

    # ------------------------------------------------------------ membership

    def add_worker(self, role: str, index: int, generation: int = 0) -> None:
        """Grow a stage: ``index`` becomes alive + placeable serving
        weight ``generation``.  Idempotent for an already-known index
        (resets its load and unfences it)."""
        if role == "prefill":
            self.prefill_alive.add(index)
            self.prefill_fenced.discard(index)
            self.prefill_gen[index] = generation
            self.prefill_load[index] = 0
            self.prefill_class_load[index] = {}
        else:
            self.replica_alive.add(index)
            self.replica_fenced.discard(index)
            self.replica_gen[index] = generation
            self.outstanding[index] = 0

    def fence_worker(self, role: str, index: int) -> None:
        """Stop new placements on ``index`` without disturbing its
        in-flight bookkeeping — the drain half of retire/swap."""
        if role == "prefill":
            self.prefill_fenced.add(index)
        else:
            self.replica_fenced.add(index)

    def retire_worker(self, role: str, index: int) -> None:
        """Remove a drained instance entirely: not alive, not fenced,
        no load entry.  A retired index is never reused (the cluster
        allocates monotonically), so stale batch ids can't collide."""
        if role == "prefill":
            self.prefill_alive.discard(index)
            self.prefill_fenced.discard(index)
            self.prefill_gen.pop(index, None)
            self.prefill_load.pop(index, None)
            self.prefill_class_load.pop(index, None)
        else:
            self.replica_alive.discard(index)
            self.replica_fenced.discard(index)
            self.replica_gen.pop(index, None)
            self.outstanding.pop(index, None)
            self.replica_digest.pop(index, None)
            self._optimistic.pop(index, None)

    def generation_of(self, uid) -> int:
        """Weight generation of the prefill pass that primed ``uid``
        (0 until it has been assigned)."""
        return self.uid_gen.get(uid, 0)

    def batch_generation(self, batch_id: str) -> int:
        b = self.batches.get(batch_id)
        return 0 if b is None else b.get("gen", 0)

    def uids_on(self, role: str, index: int) -> list:
        """Uncompleted uids whose current stage is ``(role, index)``
        (for prefill: queued on the worker; for decode: decoding on the
        replica).  Handle-stage uids belong to neither until forwarded."""
        kind = "prefill" if role == "prefill" else "replica"
        return [uid for uid, (k, key) in self.stage.items()
                if k == kind and key == index and uid not in self.completed]

    def generation_in_flight(self, generation: int) -> int:
        """How many submitted-but-uncompleted uids were primed on
        ``generation`` — the swap waits for this to hit zero before
        retiring that generation's replicas."""
        return sum(1 for uid in self.stage
                   if uid not in self.completed
                   and self.uid_gen.get(uid, 0) == generation)

    # --------------------------------------------------------------- failure

    def fail_worker(self, role: str, index: int) -> list:
        """Mark a stage instance dead; returns the uids whose work it
        held (stage bookkeeping cleared, ready for re-dispatch or typed
        shedding).  Handles already relayed onward are NOT affected —
        their work left the dead process."""
        affected = []
        if role == "prefill":
            self.prefill_alive.discard(index)
            for uid, (kind, key) in self.stage.items():
                if kind == "prefill" and key == index:
                    affected.append(uid)
        else:
            self.replica_alive.discard(index)
            for uid, (kind, key) in self.stage.items():
                if kind == "replica" and key == index:
                    affected.append(uid)
            if index in self.outstanding:
                self.outstanding[index] = 0
            # a dead replica's cache died with it
            self.replica_digest.pop(index, None)
            self._optimistic.pop(index, None)
        return self.requeue(affected)

    def revive_worker(self, role: str, index: int) -> None:
        if role == "prefill":
            self.prefill_alive.add(index)
            self.prefill_load[index] = 0
            self.prefill_class_load[index] = {}
        else:
            self.replica_alive.add(index)
            self.outstanding[index] = 0

    # ------------------------------------------------------------ cache value

    def cache_summary(self, now: float) -> dict:
        """Per-replica cache VALUE for scale-down victim selection:

        - ``value``: sum over the replica's cached prefixes of
          ``refcount / holders`` — a page many in-flight requests share
          and no other replica holds is worth the most; an idle page
          duplicated fleet-wide is worth the least;
        - ``sole_hot``: the replica is the ONLY live holder of some HOT
          prefix (refcount >= 2, i.e. actively shared by in-flight
          work) — retiring it would force every future hit on that
          prefix to re-prime;
        - ``stale``: no digest fresher than ``digest_ttl`` — cache
          contents unknown, so the caller must not credit (or debit)
          this replica on cache grounds.
        """
        holders: dict = {}
        fresh: dict = {}
        for r in sorted(self.replica_alive):
            ent = self.replica_digest.get(r)
            if ent is None or now - ent["at"] > self.digest_ttl:
                continue
            fresh[r] = ent
            for k in ent["keys"]:
                holders[k] = holders.get(k, 0) + 1
        out: dict = {}
        for r in sorted(self.replica_alive):
            ent = fresh.get(r)
            if ent is None:
                out[r] = {"stale": True, "value": 0.0, "sole_hot": False}
                continue
            value = sum(ref / holders[k]
                        for k, ref in ent["keys"].items())
            sole_hot = any(ref >= 2 and holders[k] == 1
                           for k, ref in ent["keys"].items())
            out[r] = {"stale": False, "value": round(value, 6),
                      "sole_hot": sole_hot}
        return out

    # ----------------------------------------------------------------- stats

    def queued_by_class(self) -> dict:
        """Fleet-wide queued-at-prefill count per priority class — the
        control plane journals this with each decision so overload
        actions are attributable to the class that caused them."""
        agg: dict = {}
        for cl in self.prefill_class_load.values():
            for p, n in cl.items():
                agg[p] = agg.get(p, 0) + n
        return agg

    def stats(self) -> dict:
        return {
            "prefill_alive": sorted(self.prefill_alive),
            "replica_alive": sorted(self.replica_alive),
            "prefill_fenced": sorted(self.prefill_fenced),
            "replica_fenced": sorted(self.replica_fenced),
            "prefill_gen": dict(self.prefill_gen),
            "replica_gen": dict(self.replica_gen),
            "prefill_load": dict(self.prefill_load),
            "prefill_class_load": {w: dict(cl) for w, cl in
                                   self.prefill_class_load.items()},
            "queued_by_class": self.queued_by_class(),
            "outstanding_tokens": dict(self.outstanding),
            "max_prefill_queue": self.max_prefill_queue,
            "max_outstanding_tokens": self.max_outstanding,
            "open_batches": len(self.batches),
            "submitted": len(self.requests),
            "completed": len(self.completed),
            "route_by_cache": self.route_by_cache,
            "cache_routed": self.cache_routed,
            "cache_fallback": self.cache_fallback,
            "cache_overridden": self.cache_overridden,
            "replicas_with_digest": sorted(self.replica_digest),
        }
