"""Token shift: half the channels look one position back.

Contract (reference ``/root/reference/progen_transformer/progen.py:43-46``):
split channels in two with ``array_split`` semantics (first chunk gets the
extra channel when the dim is odd), shift the FIRST half forward by one
position (zero at position 0), concatenate back.  Applied at the top of both
the attention and feed-forward blocks after their pre-LayerNorm.

Batched: position axis is ``-2``, works for ``(B, L, D)`` or ``(L, D)``.
"""

from __future__ import annotations

import jax.numpy as jnp


def shift_tokens(x):
    d = x.shape[-1]
    split = d - d // 2  # array_split: first chunk takes the remainder
    x_shift, x_pass = x[..., :split], x[..., split:]
    pad = [(0, 0)] * (x.ndim - 2) + [(1, 0), (0, 0)]
    x_shift = jnp.pad(x_shift, pad)[..., :-1, :]
    return jnp.concatenate((x_shift, x_pass), axis=-1)
