"""Symmetric per-channel int8 weight quantization for the serving path.

Weight-only int8 (the Gemma-on-TPU serving recipe, PAPERS.md): each
weight matrix is quantized per OUTPUT channel with an absmax scale so a
single f32 multiply in the matmul epilogue recovers the full-precision
range.  The contraction itself runs int8-as-bf16 against the bf16
activations with ``preferred_element_type=f32`` — ``[-127, 127]`` is
exact in bf16 (8 mantissa bits), so the MXU accumulates the TRUE integer
products in f32 and the only loss is the rounding taken at quantization
time.  Scales never leave f32: multiplying them into a bf16 tensor would
round twice.

Three layers live here:

* array-level: ``quantize_w`` / ``dequantize_w`` / ``int8_matmul`` plus
  ``quantize_rows`` (per-row scaling for paged gate cache rows);
* module-level: ``QuantDense`` — a drop-in for the model's ``nn.Dense``
  sites that stores an int8 ``kernel`` in "params" and its f32 scale in
  a parallel ``"qscale"`` collection, keeping the params tree structure
  (leaf names, shapes-up-to-dtype) identical to the bf16 model so AOT
  warmup, handoff slabs and LoRA banks work unchanged;
* tree-level: ``quantize_params`` — walk a full-precision ProGen params
  tree and emit ``(qparams, scales)`` ready to bind as
  ``{"params": qparams, "qscale": scales}``.

``np_*`` twins are pure-numpy oracles for tests; they must stay
import-safe without jax.
"""

from __future__ import annotations

from typing import Mapping

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.core.precision import Policy

# int8 symmetric range: +-127 keeps the code symmetric around zero (-128
# is never produced) and both endpoints are exact in bf16.
QMAX = 127.0


def _scale_shape(ndim: int, channel_axis: int) -> list[int]:
    shape = [1] * ndim
    shape[channel_axis] = -1
    return shape


def quantize_w(w, channel_axis: int = -1):
    """Symmetric per-channel absmax int8 quantization.

    Returns ``(q, scale)``: ``q`` int8 with ``w``'s shape, ``scale`` f32
    of shape ``(w.shape[channel_axis],)``.  All-zero channels get scale
    1.0 so dequantization is well-defined (0 * 1.0 == 0.0 exactly).
    """
    w32 = jnp.asarray(w, jnp.float32)
    channel_axis = channel_axis % w32.ndim
    reduce_axes = tuple(a for a in range(w32.ndim) if a != channel_axis)
    absmax = jnp.max(jnp.abs(w32), axis=reduce_axes)
    scale = jnp.where(absmax > 0.0, absmax / QMAX, 1.0)
    s_b = scale.reshape(_scale_shape(w32.ndim, channel_axis))
    q = jnp.clip(jnp.round(w32 / s_b), -QMAX, QMAX).astype(jnp.int8)
    return q, scale


def dequantize_w(q, scale, channel_axis: int = -1):
    """Inverse of ``quantize_w`` up to rounding: f32 output."""
    channel_axis = channel_axis % q.ndim
    s_b = jnp.asarray(scale, jnp.float32).reshape(
        _scale_shape(q.ndim, channel_axis))
    return q.astype(jnp.float32) * s_b


def int8_matmul(x, q, scale):
    """``x @ dequantize(q, scale)`` with the dequant in the epilogue.

    ``x`` is the bf16 activation ``(..., Din)``, ``q`` the int8 kernel
    ``(Din, Dout)``, ``scale`` the f32 per-output-channel scale
    ``(Dout,)``.  The int8 kernel is cast to ``x.dtype`` (exact for
    ``[-127, 127]`` in bf16) so the contraction hits the MXU; the f32
    accumulator result is scaled per channel in f32 and returned in f32
    — callers cast once at the end.
    """
    y = jax.lax.dot_general(
        x, q.astype(x.dtype),
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return y * scale.astype(jnp.float32)


def quantize_rows(x):
    """Per-row (last-axis) absmax int8 quantization for cache rows.

    Returns ``(q, scale)`` with ``scale`` f32 of ``x.shape[:-1]``.  Used
    by the 8-bit paged gate cache: one scale per gate row rides next to
    the page in a parallel f32 pool.
    """
    x32 = jnp.asarray(x, jnp.float32)
    absmax = jnp.max(jnp.abs(x32), axis=-1)
    scale = jnp.where(absmax > 0.0, absmax / QMAX, 1.0)
    q = jnp.clip(jnp.round(x32 / scale[..., None]), -QMAX, QMAX)
    return q.astype(jnp.int8), scale


class QuantDense(nn.Module):
    """Drop-in for the model's ``nn.Dense`` sites under ``weights="int8"``.

    Same param names as ``nn.Dense`` ("kernel", "bias") so the quantized
    params tree has the structure of the bf16 tree with the kernel leaf
    re-typed int8; the per-output-channel scale lives in the ``"qscale"``
    collection as "kernel_scale".  Initialization yields zeros — real
    serving always binds the output of ``quantize_params``.
    """

    features: int
    use_bias: bool
    axes: tuple[str, str]
    policy: Policy

    @nn.compact
    def __call__(self, x):
        d_in = x.shape[-1]
        kernel = self.param(
            "kernel",
            nn.with_logical_partitioning(nn.initializers.zeros, self.axes),
            (d_in, self.features), jnp.int8)
        scale = self.variable(
            "qscale", "kernel_scale",
            lambda: jnp.ones((self.features,), jnp.float32)).value
        y = int8_matmul(x, kernel, scale).astype(self.policy.compute_dtype)
        if self.use_bias:
            bias = self.param(
                "bias",
                nn.with_logical_partitioning(
                    nn.initializers.zeros, (self.axes[-1],)),
                (self.features,), self.policy.param_dtype)
            y = y + bias.astype(self.policy.compute_dtype)
        return y


# kernels that must stay full precision: the logits head is the one
# dense site whose rounding error lands directly on the sampled
# distribution, and it is a single matmul per step — not worth it.
_SKIP_SCOPES = ("to_logits",)


def quantize_params(params):
    """Quantize a full-precision ProGen "params" tree in one walk.

    Returns ``(qparams, scales)``: ``qparams`` mirrors ``params`` with
    every dense "kernel" leaf (except under ``to_logits``) re-typed int8
    per output channel and each SGU "spatial_weights" leaf re-typed int8
    per ROW (the row scale folds into the spatial mix, which contracts
    over columns); embeddings, norms and biases pass through untouched.
    ``scales`` is a sparse parallel tree holding the f32 scales under
    "<leaf>_scale" names — bind both as
    ``{"params": qparams, "qscale": scales}``.
    """

    def walk(tree, skip):
        q, s = {}, {}
        for k, v in tree.items():
            if isinstance(v, Mapping):
                sub_q, sub_s = walk(v, skip or k in _SKIP_SCOPES)
                q[k] = sub_q
                if sub_s:
                    s[k] = sub_s
            elif k == "kernel" and not skip:
                q[k], s[k + "_scale"] = quantize_w(v, channel_axis=-1)
            elif k == "spatial_weights":
                q[k], s[k + "_scale"] = quantize_w(v, channel_axis=0)
            else:
                q[k] = v
        return q, s

    return walk(params, False)


# ------------------------------------------------------------ numpy oracle


def np_quantize_w(w, channel_axis: int = -1):
    """Pure-numpy twin of ``quantize_w`` (same rounding: half-to-even)."""
    w32 = np.asarray(w, np.float32)
    channel_axis = channel_axis % w32.ndim
    reduce_axes = tuple(a for a in range(w32.ndim) if a != channel_axis)
    absmax = np.max(np.abs(w32), axis=reduce_axes)
    scale = np.where(absmax > 0.0, absmax / QMAX, 1.0).astype(np.float32)
    s_b = scale.reshape(_scale_shape(w32.ndim, channel_axis))
    q = np.clip(np.round(w32 / s_b), -QMAX, QMAX).astype(np.int8)
    return q, scale


def np_dequantize_w(q, scale, channel_axis: int = -1):
    channel_axis = channel_axis % q.ndim
    s_b = np.asarray(scale, np.float32).reshape(
        _scale_shape(q.ndim, channel_axis))
    return q.astype(np.float32) * s_b


def np_int8_matmul(x, q, scale):
    """f32-exact oracle for ``int8_matmul`` (no bf16 cast of ``x``)."""
    y = np.asarray(x, np.float32) @ q.astype(np.float32)
    return y * np.asarray(scale, np.float32)
