"""Pallas TPU kernel for windowed local attention.

Why a kernel when XLA already fuses well here: the XLA path
(``ops/local_attention.py``) materializes the ``[previous ‖ own]`` key/value
concat — every k/v window is written to and re-read from HBM twice
(``concat_previous_window``).  This kernel instead maps each grid step
``(bh, j)`` onto the SAME k/v arrays through two BlockSpec index maps (one
shifted by -1), so each window is streamed from HBM once, and the mask +
f32 softmax + both matmuls run fused in VMEM on blocks shaped for the MXU
(wsz x d with d in {64, 128}).

Window-0 semantics match the reference exactly (``progen.py:90-95``): the
phantom previous window contributes ZERO logits (not -inf) over zero
values; implemented by zeroing the shifted block's contribution when
``j == 0`` (the index map clamps j-1 to 0, the kernel masks).

Forward-only kernel + ``jax.custom_vjp``: the backward pass recomputes
through the XLA path (standard flash-attention-style rematerialized
backward; the reference model's backward has no kernel to compare against).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from progen_tpu.ops.local_attention import ATTN_MASK_VALUE, local_attention


def _kernel(q_ref, kp_ref, ko_ref, vp_ref, vo_ref, o_ref, *, scale: float):
    j = pl.program_id(1)
    q = q_ref[0]            # (wsz, d)
    k_prev = kp_ref[0]      # (wsz, d) — window j-1 (clamped at 0)
    k_own = ko_ref[0]
    v_prev = vp_ref[0]
    v_own = vo_ref[0]
    wsz = q.shape[0]

    s_prev = jax.lax.dot_general(
        q, k_prev, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale
    s_own = jax.lax.dot_general(
        q, k_own, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ) * scale

    # window 0: phantom zero-pad previous window -> zero logits over zero
    # values (reference semantics), not -inf
    is_first = (j == 0)
    s_prev = jnp.where(is_first, 0.0, s_prev)

    # own-window causal mask: query i sees own keys <= i
    rows = jax.lax.broadcasted_iota(jnp.int32, (wsz, wsz), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (wsz, wsz), 1)
    s_own = jnp.where(rows >= cols, s_own, ATTN_MASK_VALUE)

    m = jnp.maximum(
        jnp.max(s_prev, axis=-1, keepdims=True),
        jnp.max(s_own, axis=-1, keepdims=True),
    )
    p_prev = jnp.exp(s_prev - m)
    p_own = jnp.exp(s_own - m)
    denom = jnp.sum(p_prev, -1, keepdims=True) + jnp.sum(p_own, -1, keepdims=True)

    v_prev_eff = jnp.where(is_first, jnp.zeros_like(v_prev), v_prev)
    acc = jax.lax.dot_general(
        p_prev.astype(v_prev.dtype), v_prev_eff, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    acc = acc + jax.lax.dot_general(
        p_own.astype(v_own.dtype), v_own, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[0] = (acc / denom).astype(o_ref.dtype)


def _forward(q, k, v, window_size: int, scale: float, interpret: bool):
    b, h, n, d = q.shape
    wsz = window_size
    w = n // wsz
    bh = b * h
    qf, kf, vf = (t.reshape(bh, n, d) for t in (q, k, v))

    block = (1, wsz, d)
    own = pl.BlockSpec(block, lambda bh_, j: (bh_, j, 0))
    prev = pl.BlockSpec(
        block, lambda bh_, j: (bh_, jnp.maximum(j - 1, 0), 0)
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale),
        grid=(bh, w),
        in_specs=[own, prev, own, prev, own],
        out_specs=own,
        out_shape=jax.ShapeDtypeStruct((bh, n, d), q.dtype),
        interpret=interpret,
    )(qf, kf, kf, vf, vf)
    return out.reshape(b, h, n, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_local_attention(q, k, v, window_size: int, scale: float | None = None,
                           interpret: bool | None = None):
    """Drop-in for :func:`~progen_tpu.ops.local_attention.local_attention`
    on ``(B, H, L, Dh)`` tensors.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (tests on CPU)."""
    b, h, n, d = q.shape
    if n % window_size != 0:
        raise ValueError(
            f"sequence length {n} must be divisible by window {window_size}"
        )
    scale_v = d ** -0.5 if scale is None else scale
    interp = jax.default_backend() != "tpu" if interpret is None else interpret
    return _forward(q, k, v, window_size, scale_v, interp)


def _fwd(q, k, v, window_size, scale, interpret):
    out = pallas_local_attention(q, k, v, window_size, scale, interpret)
    return out, (q, k, v)


def _bwd(window_size, scale, interpret, res, g):
    q, k, v = res
    # rematerialized backward through the XLA path (identical math)
    _, vjp = jax.vjp(
        lambda q_, k_, v_: local_attention(q_, k_, v_,
                                           window_size=window_size,
                                           scale=scale),
        q, k, v,
    )
    return vjp(g)


pallas_local_attention.defvjp(_fwd, _bwd)
