"""Pallas TPU kernels for windowed local attention — fused forward AND
backward.

Why a kernel when XLA already fuses well here: the XLA path
(``ops/local_attention.py``) materializes the ``[previous ‖ own]`` key/value
concat — every k/v window is written to and re-read from HBM twice
(``concat_previous_window``).  These kernels instead map each grid step
onto the SAME k/v arrays through shifted BlockSpec index maps, so each
window streams from HBM once and the mask + f32 softmax + matmuls run
fused in VMEM on MXU-shaped blocks (wsz x d, d in {64, 128}).

Layout: all kernels take EXTENDED key/value sequences ``(B, H, L+wsz, D)``
whose first window is the "previous window" of query window 0:

* single device: a ZERO window — which reproduces the reference's phantom
  zero-pad semantics (``progen.py:90-95``: zero logits in the softmax
  denominator, zero values) with no special-casing in the kernel;
* context parallel: the left neighbour's last window delivered by
  ``ppermute`` (``parallel/context.py``), zeros on the leftmost shard — the
  same phantom semantics fall out at the sequence edge.

Query window j then attends k_ext windows ``j`` (previous) and ``j+1``
(own).

The backward is flash-style: the forward saves the per-row logsumexp; the
backward recomputes probabilities blockwise in VMEM and runs two kernels —
dq over query windows, and dk/dv over key windows (key window i receives
grads from query windows i-1, which see it as "own", and i, which see it
as "previous").  No (L, 2wsz) probability tensor ever reaches HBM, unlike
the old rematerialize-through-XLA backward which re-paid the concat.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from progen_tpu.ops.local_attention import ATTN_MASK_VALUE


def _causal_own_mask(wsz: int):
    rows = jax.lax.broadcasted_iota(jnp.int32, (wsz, wsz), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (wsz, wsz), 1)
    return rows >= cols


def _dot_t(a, b):  # a @ b^T, f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot(a, b):  # a @ b, f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


# -- forward ------------------------------------------------------------------


def _fwd_kernel(q_ref, kp_ref, ko_ref, vp_ref, vo_ref, o_ref, lse_ref,
                *, scale: float):
    q = q_ref[0]          # (wsz, d)
    k_prev = kp_ref[0]    # k_ext window j   (= previous window of query j)
    k_own = ko_ref[0]     # k_ext window j+1 (= own window of query j)
    v_prev = vp_ref[0]
    v_own = vo_ref[0]
    wsz = q.shape[0]

    s_prev = _dot_t(q, k_prev) * scale
    s_own = _dot_t(q, k_own) * scale
    s_own = jnp.where(_causal_own_mask(wsz), s_own, ATTN_MASK_VALUE)

    m = jnp.maximum(
        jnp.max(s_prev, axis=-1, keepdims=True),
        jnp.max(s_own, axis=-1, keepdims=True),
    )
    p_prev = jnp.exp(s_prev - m)
    p_own = jnp.exp(s_own - m)
    denom = jnp.sum(p_prev, -1, keepdims=True) + jnp.sum(p_own, -1, keepdims=True)

    acc = _dot(p_prev.astype(v_prev.dtype), v_prev)
    acc = acc + _dot(p_own.astype(v_own.dtype), v_own)
    o_ref[0] = (acc / denom).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(denom)   # (wsz, 1)


def _forward_ext(q, k_ext, v_ext, window_size: int, scale: float,
                 interpret: bool):
    b, h, n, d = q.shape
    wsz = window_size
    w = n // wsz
    bh = b * h
    qf = q.reshape(bh, n, d)
    kf = k_ext.reshape(bh, n + wsz, d)
    vf = v_ext.reshape(bh, n + wsz, d)

    block = (1, wsz, d)
    q_spec = pl.BlockSpec(block, lambda bh_, j: (bh_, j, 0))
    prev = pl.BlockSpec(block, lambda bh_, j: (bh_, j, 0))
    own = pl.BlockSpec(block, lambda bh_, j: (bh_, j + 1, 0))
    # per-row scalars live as (bh, n, 1): Mosaic wants the last two block
    # dims divisible by (8, 128) OR equal to the array dims — (wsz, 1) is.
    lse_spec = pl.BlockSpec((1, wsz, 1), lambda bh_, j: (bh_, j, 0))
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale),
        grid=(bh, w),
        in_specs=[q_spec, prev, own, prev, own],
        out_specs=[q_spec, lse_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n, d), q.dtype),
            jax.ShapeDtypeStruct((bh, n, 1), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, kf, vf, vf)
    return out.reshape(b, h, n, d), lse.reshape(b, h, n)


# -- backward -----------------------------------------------------------------


def _dq_kernel(q_ref, kp_ref, ko_ref, vp_ref, vo_ref, do_ref, lse_ref,
               dd_ref, dq_ref, *, scale: float):
    q = q_ref[0]
    k_prev, k_own = kp_ref[0], ko_ref[0]
    v_prev, v_own = vp_ref[0], vo_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]    # (wsz, 1)
    dd = dd_ref[0]      # D = rowsum(do * o), (wsz, 1)
    wsz = q.shape[0]

    s_prev = _dot_t(q, k_prev) * scale
    s_own = _dot_t(q, k_own) * scale
    s_own = jnp.where(_causal_own_mask(wsz), s_own, ATTN_MASK_VALUE)
    p_prev = jnp.exp(s_prev - lse)
    p_own = jnp.exp(s_own - lse)

    dp_prev = _dot_t(do, v_prev)
    dp_own = _dot_t(do, v_own)
    ds_prev = p_prev * (dp_prev - dd)
    ds_own = p_own * (dp_own - dd)

    dq = (_dot(ds_prev.astype(k_prev.dtype), k_prev)
          + _dot(ds_own.astype(k_own.dtype), k_own)) * scale
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(k_ref, v_ref, qo_ref, qp_ref, doo_ref, dop_ref, lseo_ref,
                lsep_ref, ddo_ref, ddp_ref, dk_ref, dv_ref,
                *, scale: float, num_windows: int):
    # Key-extended window i: "own" user is query window i-1 (valid i >= 1),
    # "prev" user is query window i (valid i <= w-1, w = num query windows).
    i = pl.program_id(1)
    k = k_ref[0]
    v = v_ref[0]
    q_own, q_prev = qo_ref[0], qp_ref[0]      # query windows i-1, i (clamped)
    do_own, do_prev = doo_ref[0], dop_ref[0]
    lse_own = lseo_ref[0]     # (wsz, 1)
    lse_prev = lsep_ref[0]
    dd_own = ddo_ref[0]
    dd_prev = ddp_ref[0]
    wsz = k.shape[0]

    own_valid = i >= 1
    prev_valid = i <= num_windows - 1

    # own-window user: causal mask applies
    s_o = _dot_t(q_own, k) * scale
    s_o = jnp.where(_causal_own_mask(wsz), s_o, ATTN_MASK_VALUE)
    p_o = jnp.exp(s_o - lse_own)
    p_o = jnp.where(own_valid, p_o, 0.0)
    dp_o = _dot_t(do_own, v)
    ds_o = p_o * (dp_o - dd_own)

    # previous-window user: fully visible, no mask
    s_p = _dot_t(q_prev, k) * scale
    p_p = jnp.exp(s_p - lse_prev)
    p_p = jnp.where(prev_valid, p_p, 0.0)
    dp_p = _dot_t(do_prev, v)
    ds_p = p_p * (dp_p - dd_prev)

    dv = (_dot(p_o.astype(do_own.dtype).T, do_own)
          + _dot(p_p.astype(do_prev.dtype).T, do_prev))
    dk = (_dot(ds_o.astype(q_own.dtype).T, q_own)
          + _dot(ds_p.astype(q_prev.dtype).T, q_prev)) * scale
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _backward_ext(q, k_ext, v_ext, o, lse, do, window_size: int,
                  scale: float, interpret: bool):
    b, h, n, d = q.shape
    wsz = window_size
    w = n // wsz
    bh = b * h
    qf = q.reshape(bh, n, d)
    kf = k_ext.reshape(bh, n + wsz, d)
    vf = v_ext.reshape(bh, n + wsz, d)
    dof = do.reshape(bh, n, d)
    lsef = lse.reshape(bh, n, 1)
    # D_i = sum_j dO_ij * O_ij — cheap XLA elementwise+reduce, f32
    ddf = jnp.sum(
        dof.astype(jnp.float32) * o.reshape(bh, n, d).astype(jnp.float32),
        -1, keepdims=True,
    )

    block = (1, wsz, d)
    row = pl.BlockSpec((1, wsz, 1), lambda bh_, j: (bh_, j, 0))
    q_spec = pl.BlockSpec(block, lambda bh_, j: (bh_, j, 0))
    prev = pl.BlockSpec(block, lambda bh_, j: (bh_, j, 0))
    own = pl.BlockSpec(block, lambda bh_, j: (bh_, j + 1, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale),
        grid=(bh, w),
        in_specs=[q_spec, prev, own, prev, own, q_spec, row, row],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((bh, n, d), q.dtype),
        interpret=interpret,
    )(qf, kf, kf, vf, vf, dof, lsef, ddf)

    # grid over the w+1 EXTENDED key windows
    kv_spec = pl.BlockSpec(block, lambda bh_, i: (bh_, i, 0))
    q_own_spec = pl.BlockSpec(
        block, lambda bh_, i: (bh_, jnp.maximum(i - 1, 0), 0))
    q_prev_spec = pl.BlockSpec(
        block, lambda bh_, i: (bh_, jnp.minimum(i, w - 1), 0))
    row_own = pl.BlockSpec(
        (1, wsz, 1), lambda bh_, i: (bh_, jnp.maximum(i - 1, 0), 0))
    row_prev = pl.BlockSpec(
        (1, wsz, 1), lambda bh_, i: (bh_, jnp.minimum(i, w - 1), 0))
    dk_ext, dv_ext = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, num_windows=w),
        grid=(bh, w + 1),
        in_specs=[kv_spec, kv_spec, q_own_spec, q_prev_spec, q_own_spec,
                  q_prev_spec, row_own, row_prev, row_own, row_prev],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct((bh, n + wsz, d), k_ext.dtype),
            jax.ShapeDtypeStruct((bh, n + wsz, d), v_ext.dtype),
        ],
        interpret=interpret,
    )(kf, vf, qf, qf, dof, dof, lsef, lsef, ddf, ddf)

    return (
        dq.reshape(b, h, n, d),
        dk_ext.reshape(b, h, n + wsz, d),
        dv_ext.reshape(b, h, n + wsz, d),
    )


# -- public API ---------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def pallas_local_attention_ext(q, k_ext, v_ext, window_size: int,
                               scale: float, interpret: bool):
    """Windowed attention over ``q (B, H, L, D)`` against EXTENDED
    ``k_ext/v_ext (B, H, L+wsz, D)`` whose first window is query window 0's
    previous window (zeros, or a context-parallel halo)."""
    out, _ = _forward_ext(q, k_ext, v_ext, window_size, scale, interpret)
    return out


def _ext_fwd(q, k_ext, v_ext, window_size, scale, interpret):
    out, lse = _forward_ext(q, k_ext, v_ext, window_size, scale, interpret)
    return out, (q, k_ext, v_ext, out, lse)


def _ext_bwd(window_size, scale, interpret, res, do):
    q, k_ext, v_ext, out, lse = res
    return _backward_ext(q, k_ext, v_ext, out, lse, do, window_size, scale,
                         interpret)


pallas_local_attention_ext.defvjp(_ext_fwd, _ext_bwd)


def pallas_local_attention(q, k, v, window_size: int,
                           scale: float | None = None,
                           interpret: bool | None = None):
    """Drop-in for :func:`~progen_tpu.ops.local_attention.local_attention`
    on ``(B, H, L, Dh)`` tensors.  Prepends the phantom zero window to k/v
    and runs the extended kernels.  ``interpret=None`` auto-selects the
    Pallas interpreter off-TPU (tests on CPU)."""
    b, h, n, d = q.shape
    if n % window_size != 0:
        raise ValueError(
            f"sequence length {n} must be divisible by window {window_size}"
        )
    scale_v = d ** -0.5 if scale is None else scale
    interp = jax.default_backend() != "tpu" if interpret is None else interpret
    pad = [(0, 0), (0, 0), (window_size, 0), (0, 0)]
    k_ext = jnp.pad(k, pad)
    v_ext = jnp.pad(v, pad)
    return pallas_local_attention_ext(q, k_ext, v_ext, window_size, scale_v,
                                      interp)
