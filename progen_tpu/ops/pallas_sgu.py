"""Blocked lower-triangular Pallas SGU kernel — fused forward AND backward.

The SGU token-mixing matmul (``ops/sgu.py``) is a LEARNED causal ``(n, n)``
weight against the gate half of the gMLP hidden: ``mixed[m] = sum_{k<=m}
W[m, k] * gate[k] + bias[m]``, followed by the elementwise gate multiply
``out = res * mixed`` (``models/progen.py`` SGU).  The XLA path computes
the masked matmul DENSE — 2x the causal FLOPs plus an ``(n, n)`` mask (or
tril) materialization — and round-trips the ``(B, n, d)`` ``mixed`` tensor
through HBM between the matmul and the multiply.

These kernels recover both:

* **block skipping** — the ``(n, n)`` weights are tiled into square
  ``block x block`` tiles and the grid enumerates ONLY the lower-triangle
  tiles (``R(R+1)/2`` of ``R^2``), pairing row ``i`` with row ``R-1-i`` so
  the triangle flattens into an exactly rectangular ``(R/2, R+1)`` grid
  with integer-only index maps (no sqrt on the scalar core).  The tril
  mask is applied only INSIDE diagonal tiles; strictly-upper tiles are
  never fetched or multiplied, so the executed matmul FLOPs are
  ``(R+1)/(2R)`` of dense (0.53x at n=1024, block 64 — see
  :func:`sgu_block_flops`);
* **epilogue fusion** — the ``+ bias`` and the final ``res * mixed``
  multiply run in VMEM on the f32 accumulator before the single output
  write, so ``mixed`` never reaches HBM.

Backward (hand-written custom VJP, mirroring ``pallas_attention.py``'s
flash-style structure):

* ``d_res = dout * mixed`` — ``mixed`` is NOT saved by the forward; it is
  recomputed blockwise by the SAME forward kernel with ``dout`` standing
  in for ``res`` (``dout * (W_tril @ gate + b)``), so the only extra
  residual the VJP keeps is the gate input itself;
* ``d_gate = W_tril^T @ (dout * res)`` — a transposed triangle sweep
  (output column tile j consumes row tiles i >= j), same pairing trick;
* ``d_W = tril(sum_b (dout * res) @ gate^T)`` — triangle tiles only, batch
  as the innermost (accumulating) grid dimension; the strict upper
  triangle is hard-zeroed (matching the reference parameterization where
  masked weights get exactly-zero grads);
* ``d_bias`` — a plain XLA fused multiply+reduce (never materializes
  ``dout * res``).

All matmuls accumulate in f32 scratch; inputs/outputs stay in the compute
dtype.  ``interpret=None`` auto-selects the Pallas interpreter off-TPU so
the CPU test tier exercises the real kernel logic.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Square (block, block) weight tiles: 64 keeps the MXU fed (the existing
# attention kernel runs 64-lane blocks) while the block-granular causal
# hull stays within (R+1)/2R = 0.53x of dense at n=1024 — a 128 tile
# would land at 0.5625x and miss the <=0.55x FLOP target.
DEFAULT_BLOCK = 64


def _default_block(n: int) -> int:
    if n >= 2 * DEFAULT_BLOCK:
        return DEFAULT_BLOCK
    # tiny sequences (tests, short prefills): two row tiles with minimal
    # padding, sublane-aligned (8 for f32, and 16 | 2*block for bf16)
    return max(8, -(-(-(-n // 2)) // 8) * 8)


def _dot(a, b):  # a @ b, f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tl(a, b):  # a^T @ b, f32 accumulate
    return jax.lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )


def _dot_tr(a, b):  # a @ b^T, f32 accumulate
    return jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )


def _tile_tril(block: int):
    rows = jax.lax.broadcasted_iota(jnp.int32, (block, block), 0)
    cols = jax.lax.broadcasted_iota(jnp.int32, (block, block), 1)
    return rows >= cols


# -- triangle -> rectangle grid flattening ------------------------------------
#
# Lower-triangle tile rows have lengths 1..R.  Pairing row p (length p+1)
# with row R-1-p (length R-p) gives constant length R+1, so the grid is
# exactly (R/2, R+1) with R even (the wrappers pad to even R).  Row-major
# within a pair keeps each output tile's visits CONSECUTIVE — the Pallas
# revisiting/accumulation contract.


def _fwd_ij(p, c, nbr):
    """Grid step (p, c) -> weight-tile (i, j): pair p covers row i=p for
    c in [0, p] (j=c) then row i=nbr-1-p for c in [p+1, nbr] (j=c-p-1).
    In both segments j ascends to the DIAGONAL tile last."""
    in_a = c <= p
    i = jnp.where(in_a, p, nbr - 1 - p)
    j = jnp.where(in_a, c, c - p - 1)
    return i, j


def _dgate_ji(p, c, nbr):
    """Transposed sweep for d_gate: output COLUMN tile j consumes row
    tiles i >= j.  Column lengths are R-j, so pair column j=p (length
    nbr-p, c in [0, nbr-1-p], i=p+c) with column j=nbr-1-p (length p+1,
    c in [nbr-p, nbr], i=c-1).  Each segment STARTS at the diagonal."""
    in_a = c <= nbr - 1 - p
    j = jnp.where(in_a, p, nbr - 1 - p)
    i = jnp.where(in_a, p + c, c - 1)
    return i, j


# -- kernels ------------------------------------------------------------------


def _fwd_kernel(w_ref, g_ref, res_ref, b_ref, o_ref, acc_ref, *, nbr):
    p = pl.program_id(1)
    c = pl.program_id(2)
    first = jnp.logical_or(c == 0, c == p + 1)
    diag = jnp.logical_or(c == p, c == nbr)  # j == i: segment's LAST step

    @pl.when(first)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[...]  # (block, block) tile at (i, j)
    w = jnp.where(jnp.logical_and(diag, ~_tile_tril(w.shape[0])), 0, w)
    acc_ref[...] += _dot(w, g_ref[0])

    @pl.when(diag)
    def _():
        # epilogue matches the XLA path bit-for-bit in spirit: f32 mixed
        # (+bias) cast to the compute dtype, THEN multiplied by res
        mixed = acc_ref[...] + b_ref[...].astype(jnp.float32)
        o_ref[0] = res_ref[0] * mixed.astype(o_ref.dtype)


def _dgate_kernel(w_ref, do_ref, res_ref, dg_ref, acc_ref, *, nbr):
    p = pl.program_id(1)
    c = pl.program_id(2)
    diag = jnp.logical_or(c == 0, c == nbr - p)  # segment's FIRST step
    last = jnp.logical_or(c == nbr - 1 - p, c == nbr)

    @pl.when(diag)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    w = w_ref[...]
    w = jnp.where(jnp.logical_and(diag, ~_tile_tril(w.shape[0])), 0, w)
    dmix = do_ref[0] * res_ref[0]
    acc_ref[...] += _dot_tl(w, dmix)  # W^T @ dmix: (block_j, d)

    @pl.when(last)
    def _():
        dg_ref[0] = acc_ref[...].astype(dg_ref.dtype)


def _dw_kernel(do_ref, res_ref, g_ref, dw_ref, acc_ref, *, nbatch):
    b = pl.program_id(2)

    @pl.when(b == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    dmix = do_ref[0] * res_ref[0]  # (block_i, d)
    acc_ref[...] += _dot_tr(dmix, g_ref[0])  # dmix @ gate^T: (block_i, block_j)

    @pl.when(b == nbatch - 1)
    def _():
        # no in-tile mask: the wrapper tril's the whole (n, n) grad, which
        # also zeroes the never-visited strictly-upper tiles exactly
        dw_ref[...] = acc_ref[...].astype(dw_ref.dtype)


# -- padded launch helpers ----------------------------------------------------


def _prep(res, gate, weights, biases, block: int):
    """Flatten batch, pad n up to an EVEN number of tiles (zero rows/cols
    are exact: zero gate/res rows contribute and produce nothing)."""
    n = weights.shape[0]
    d = gate.shape[-1]
    lead = gate.shape[:-2]
    bsz = 1
    for s in lead:
        bsz *= s
    nbr = -(-n // block)
    nbr += nbr % 2  # pairing needs an even tile count
    npad = nbr * block - n
    g = gate.reshape(bsz, n, d)
    r = res.reshape(bsz, n, d)
    if npad:
        g = jnp.pad(g, ((0, 0), (0, npad), (0, 0)))
        r = jnp.pad(r, ((0, 0), (0, npad), (0, 0)))
        weights = jnp.pad(weights, ((0, npad), (0, npad)))
        biases = jnp.pad(biases, ((0, npad), (0, 0)))
    return g, r, weights, biases, bsz, nbr, lead


def _forward(res, gate, weights, biases, block: int, interpret: bool):
    n, d = weights.shape[0], gate.shape[-1]
    g, r, w, b, bsz, nbr, lead = _prep(res, gate, weights, biases, block)

    def wmap(bb, p, c):
        return _fwd_ij(p, c, nbr)

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, nbr=nbr),
        grid=(bsz, nbr // 2, nbr + 1),
        in_specs=[
            pl.BlockSpec((block, block), wmap),
            pl.BlockSpec((1, block, d),
                         lambda bb, p, c: (bb, _fwd_ij(p, c, nbr)[1], 0)),
            pl.BlockSpec((1, block, d),
                         lambda bb, p, c: (bb, _fwd_ij(p, c, nbr)[0], 0)),
            pl.BlockSpec((block, 1),
                         lambda bb, p, c: (_fwd_ij(p, c, nbr)[0], 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d),
                               lambda bb, p, c: (bb, _fwd_ij(p, c, nbr)[0], 0)),
        out_shape=jax.ShapeDtypeStruct((bsz, nbr * block, d), gate.dtype),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
        interpret=interpret,
    )(w, g, r, b)
    return out[:, :n].reshape(*lead, n, d)


def _backward_dgate(weights, dout, res, block: int, interpret: bool):
    n, d = weights.shape[0], dout.shape[-1]
    do, r, w, _b, bsz, nbr, lead = _prep(
        dout, res, weights, jnp.zeros((n, 1), weights.dtype), block)
    # _prep maps (res=dout, gate=res) -> (g=res? no: g is the FIRST tensor)
    # — name them explicitly to avoid confusion:
    do_p, res_p = do, r

    def wmap(bb, p, c):
        return _dgate_ji(p, c, nbr)

    dg = pl.pallas_call(
        functools.partial(_dgate_kernel, nbr=nbr),
        grid=(bsz, nbr // 2, nbr + 1),
        in_specs=[
            pl.BlockSpec((block, block), wmap),
            pl.BlockSpec((1, block, d),
                         lambda bb, p, c: (bb, _dgate_ji(p, c, nbr)[0], 0)),
            pl.BlockSpec((1, block, d),
                         lambda bb, p, c: (bb, _dgate_ji(p, c, nbr)[0], 0)),
        ],
        out_specs=pl.BlockSpec((1, block, d),
                               lambda bb, p, c: (bb, _dgate_ji(p, c, nbr)[1], 0)),
        out_shape=jax.ShapeDtypeStruct((bsz, nbr * block, d), dout.dtype),
        scratch_shapes=[pltpu.VMEM((block, d), jnp.float32)],
        interpret=interpret,
    )(w, do_p, res_p)
    return dg[:, :n].reshape(*lead, n, d)


def _backward_dw(dout, res, gate, weights_dtype, n: int, block: int,
                 interpret: bool):
    d = dout.shape[-1]
    do, r, _w, _b, bsz, nbr, _lead = _prep(
        dout, res, jnp.zeros((n, n), weights_dtype),
        jnp.zeros((n, 1), weights_dtype), block)
    g = gate.reshape(bsz, n, d)
    if nbr * block != n:
        g = jnp.pad(g, ((0, 0), (0, nbr * block - n), (0, 0)))

    dw = pl.pallas_call(
        functools.partial(_dw_kernel, nbatch=bsz),
        grid=(nbr // 2, nbr + 1, bsz),  # batch INNERMOST: accumulating dim
        in_specs=[
            pl.BlockSpec((1, block, d),
                         lambda p, c, bb: (bb, _fwd_ij(p, c, nbr)[0], 0)),
            pl.BlockSpec((1, block, d),
                         lambda p, c, bb: (bb, _fwd_ij(p, c, nbr)[0], 0)),
            pl.BlockSpec((1, block, d),
                         lambda p, c, bb: (bb, _fwd_ij(p, c, nbr)[1], 0)),
        ],
        out_specs=pl.BlockSpec((block, block),
                               lambda p, c, bb: _fwd_ij(p, c, nbr)),
        out_shape=jax.ShapeDtypeStruct((nbr * block, nbr * block),
                                       weights_dtype),
        scratch_shapes=[pltpu.VMEM((block, block), jnp.float32)],
        interpret=interpret,
    )(do, r, g)
    # hard-zero the masked parameterization's dead region: tril also
    # clears the strictly-upper tiles the grid never visited
    return jnp.tril(dw[:n, :n])


# -- custom VJP ---------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _sgu_fused(res, gate, weights, biases, block, interpret, reduce_axes):
    return _forward(res, gate, weights, biases, block, interpret)


def _sgu_fwd(res, gate, weights, biases, block, interpret, reduce_axes):
    out = _forward(res, gate, weights, biases, block, interpret)
    return out, (res, gate, weights, biases)


def _sgu_bwd(block, interpret, reduce_axes, saved, dout):
    res, gate, weights, biases = saved
    n = weights.shape[0]
    lead_axes = tuple(range(dout.ndim - 2))
    # d_res = dout * mixed — mixed recomputed by the forward kernel with
    # dout standing in for res (nothing beyond the inputs was saved)
    d_res = _forward(dout, gate, weights, biases, block, interpret)
    d_gate = _backward_dgate(weights, dout, res, block, interpret)
    d_w = _backward_dw(dout, res, gate, weights.dtype, n, block, interpret)
    # bias broadcast over batch and d: fused XLA multiply+reduce, f32
    d_b = jnp.sum(
        (dout * res).astype(jnp.float32), axis=lead_axes + (dout.ndim - 1,)
    ).reshape(n, 1).astype(biases.dtype)
    if reduce_axes:
        # full-manual shard_map: weights/biases enter replicated, so their
        # cotangents must be summed over the data-parallel and d-sharded
        # mesh axes explicitly (parallel/context.py passes the axis names)
        d_w = jax.lax.psum(d_w, reduce_axes)
        d_b = jax.lax.psum(d_b, reduce_axes)
    return d_res, d_gate, d_w, d_b


_sgu_fused.defvjp(_sgu_fwd, _sgu_bwd)


# -- public API ---------------------------------------------------------------


def pallas_spatial_gate(res, gate, weights, biases, *,
                        block_size: int | None = None,
                        interpret: bool | None = None,
                        reduce_axes: tuple = ()):
    """Fused blocked-causal SGU: ``res * (tril(weights) @ gate + biases)``.

    ``res``/``gate``: ``(..., n, d)`` (the two halves of the gMLP hidden,
    gate already LayerNormed); ``weights``: ``(n, n)``; ``biases``:
    ``(n, 1)``.  Drop-in for the XLA ``x * spatial_gate(gate, w, b)``
    composition in ``models/progen.py``.

    ``interpret=None`` auto-selects the Pallas interpreter off-TPU.
    ``reduce_axes`` is for the full-manual shard_map wrapper
    (``parallel/context.py``): mesh axis names whose devices hold
    replicated weights/biases — their grads are psummed in the VJP.
    """
    n = weights.shape[0]
    if weights.shape != (n, n):
        raise ValueError(f"weights must be square, got {weights.shape}")
    if gate.shape[-2] != n or res.shape != gate.shape:
        raise ValueError(
            f"res/gate {res.shape}/{gate.shape} must be (..., {n}, d) "
            f"matching weights {weights.shape}"
        )
    if biases.shape != (n, 1):
        raise ValueError(f"biases must be ({n}, 1), got {biases.shape}")
    block = _default_block(n) if block_size is None else block_size
    interp = jax.default_backend() != "tpu" if interpret is None else interpret
    return _sgu_fused(res, gate, weights, biases, block, interp,
                      tuple(reduce_axes))


def sgu_block_flops(n: int, d: int, block_size: int | None = None) -> dict:
    """Static FLOP accounting for one forward spatial matmul at seq ``n``,
    width ``d``: blocks executed x per-block FLOPs vs the dense einsum.
    The acceptance gate (tests/test_pallas_sgu.py) asserts
    ``ratio <= 0.55`` at n=1024 with the default block."""
    block = _default_block(n) if block_size is None else block_size
    nbr = -(-n // block)
    nbr += nbr % 2
    blocks_executed = nbr * (nbr + 1) // 2
    blocks_dense = nbr * nbr
    flops_per_block = 2 * block * block * d
    return {
        "block": block,
        "blocks_executed": blocks_executed,
        "blocks_dense": blocks_dense,
        "flops_executed": blocks_executed * flops_per_block,
        "flops_dense": 2 * n * n * d,
        "ratio": blocks_executed * flops_per_block / (2 * n * n * d),
    }
