from progen_tpu.ops.local_attention import (
    ATTN_MASK_VALUE,
    concat_previous_window,
    local_attention,
    window_mask,
)
from progen_tpu.ops.rotary import apply_rotary_pos_emb, fixed_pos_embedding, rotate_every_two
from progen_tpu.ops.sgu import spatial_gate
from progen_tpu.ops.shift import shift_tokens

__all__ = [
    "ATTN_MASK_VALUE",
    "concat_previous_window",
    "local_attention",
    "window_mask",
    "apply_rotary_pos_emb",
    "fixed_pos_embedding",
    "rotate_every_two",
    "spatial_gate",
    "shift_tokens",
]
