"""Rotary position embedding — interleaved (GPT-J) variant.

Behavioral contract (reference ``/root/reference/progen_transformer/progen.py:24-41``):

* frequencies ``1/10000^(2i/d)``, each repeated twice consecutively so the
  sin/cos tables have shape ``(n, d)`` with pairs of equal entries;
* rotation pairs ADJACENT channels: ``(x0, x1) -> (-x1, x0)``;
* applied to the first ``rot_dim`` channels only, the rest pass through
  (in the reference ``rot_dim == dim_head`` so the whole head rotates);
* unusually, the reference rotates q, k AND v (``progen.py:87``) — we keep
  that, it is load-bearing for behavior parity.

All functions are shape-polymorphic over leading batch/head dims; the
position axis is ``-2`` and the feature axis is ``-1``.
"""

from __future__ import annotations

import jax.numpy as jnp


def fixed_pos_embedding(n: int, dim: int, dtype=jnp.float32):
    """Sin/cos tables of shape ``(n, dim)`` (dim must be even).

    Built in float32 regardless of compute dtype — trig tables in bf16 lose
    position resolution at long context.
    """
    inv_freq = 1.0 / (10000 ** (jnp.arange(0, dim, 2, dtype=jnp.float32) / dim))
    angles = jnp.arange(n, dtype=jnp.float32)[:, None] * inv_freq[None, :]
    # repeat each frequency twice consecutively: (n, dim/2) -> (n, dim)
    angles = jnp.repeat(angles, 2, axis=-1)
    return jnp.sin(angles).astype(dtype), jnp.cos(angles).astype(dtype)


def rotate_every_two(x):
    """``(..., x0, x1, x2, x3, ...) -> (..., -x1, x0, -x3, x2, ...)``."""
    x = x.reshape(*x.shape[:-1], x.shape[-1] // 2, 2)
    x1, x2 = x[..., 0], x[..., 1]
    out = jnp.stack((-x2, x1), axis=-1)
    return out.reshape(*out.shape[:-2], -1)


def apply_rotary_pos_emb(x, sin, cos):
    """Rotate the first ``sin.shape[-1]`` channels of ``x``; pass the rest.

    ``sin``/``cos`` are ``(n, rot_dim)`` and broadcast over leading dims of
    ``x`` (``(..., n, d)``).
    """
    rot_dim = sin.shape[-1]
    sin = sin.astype(x.dtype)
    cos = cos.astype(x.dtype)
    x_rot, x_pass = x[..., :rot_dim], x[..., rot_dim:]
    x_rot = (x_rot * cos) + (rotate_every_two(x_rot) * sin)
    if x_pass.shape[-1] == 0:
        return x_rot
    return jnp.concatenate((x_rot, x_pass), axis=-1)
