"""Ragged paged decode kernel: per-row page-table walk over pooled state.

This is the decode-side companion of the paged serving subsystem
(``decode/paging.py``).  In this architecture the attention k/v cache is
already an O(2·window) ring per slot — the per-token state that actually
scales with request length (the thing a "paged KV cache" must page) is
the **SGU gate cache**: the spatial gating unit attends over ALL previous
token rows through the learned causal ``(n, n)`` weight, exactly the
all-past-tokens contraction that Ragged Paged Attention (PAPERS.md)
pages.  So the pooled resource here is gate rows and the ragged kernel
computes, for batch row ``b`` at position ``pos_b``::

    mixed[b] = sum_{i <= pos_b} W[pos_b, i] * pool[table[b, i // ps], i % ps]
               + bias[pos_b]

where ``pool`` is the global page pool ``(num_pages, page_size, d)`` and
``table`` is the per-row page table ``(B, pages_per_row)``.  Each batch
row walks ONLY its own pages: the grid is ``(B, pages_per_row)``, the
page axis is innermost (consecutive visits to the same output row, the
accumulation contract from ``pallas_sgu.py``), and pages past the row's
position are skipped entirely (``@pl.when`` — a short request touches
``pos // ps + 1`` pages, not the table width).  Bit-for-bit discipline:

* the per-page partial products accumulate in an f32 VMEM scratch;
* ``pos`` and the page table ride in as SCALAR-PREFETCH operands
  (``pltpu.PrefetchScalarGridSpec``): the index maps that choose the
  weight-row block (``pos_ref[b]``) and the pool page
  (``table_ref[b, p]``) are integer lookups into prefetched SMEM —
  no gather materialization, no float work on the scalar core;
* unowned table entries point at the all-zeros ``NULL_PAGE`` so reading
  them is harmless, and the in-kernel causal mask zeroes columns past
  ``pos`` so stale rows in reused pages contribute exact ±0.

The XLA fallback (``impl="xla"``) is a gather + the SAME masked einsum
the dense decode path uses, sliced to the dense row count — on CPU it is
bitwise identical to the fixed-slot engine's contraction, which is what
the engine-parity tier-1 tests pin.  ``interpret=None`` auto-selects the
Pallas interpreter off-TPU, mirroring ``pallas_sgu.py``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from progen_tpu.decode.paging import DUMP_PAGE, NULL_PAGE
from progen_tpu.ops.quant import quantize_rows


def _mix_kernel(pos_ref, table_ref, w_ref, pool_ref, bias_ref, o_ref,
                acc_ref, *, page_size, pages_per_row):
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]
    # pages strictly past the row's position hold no live rows: skip the
    # fetch-multiply entirely (ragged walk — work scales with pos, not
    # with the table width)
    @pl.when(p <= pos // page_size)
    def _accumulate():
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        w = jnp.where(col <= pos, w_ref[...].astype(jnp.float32), 0.0)
        acc_ref[...] += jax.lax.dot_general(
            w, pool_ref[0].astype(jnp.float32),
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(p == pages_per_row - 1)
    def _epilogue():
        o_ref[...] = acc_ref[...] + bias_ref[...].astype(jnp.float32)


def _mix_kernel_q8(pos_ref, table_ref, w_ref, pool_ref, bias_ref,
                   wscale_ref, pscale_ref, o_ref, acc_ref, *,
                   page_size, pages_per_row):
    """Quantized variant of :func:`_mix_kernel`: dequant in the epilogue.

    Int8 weight blocks and int8 pool pages are widened to f32 INSIDE the
    kernel and multiplied by their scales — the per-weight-ROW scalar
    (``wscale_ref``, indexed like the bias) and the per-pool-row scales
    riding next to the page (``pscale_ref``, indexed like the page) — so
    nothing 8-bit ever round-trips HBM at higher precision.  When one
    side is full precision its scale pool is all ones and the multiply
    is exact.
    """
    b = pl.program_id(0)
    p = pl.program_id(1)

    @pl.when(p == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    pos = pos_ref[b]

    @pl.when(p <= pos // page_size)
    def _accumulate():
        col = p * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        w = jnp.where(col <= pos, w_ref[...].astype(jnp.float32), 0.0)
        w = w * wscale_ref[0, 0]
        rows = pool_ref[0].astype(jnp.float32) * \
            pscale_ref[...].reshape(page_size, 1)
        acc_ref[...] += jax.lax.dot_general(
            w, rows,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(p == pages_per_row - 1)
    def _epilogue():
        o_ref[...] = acc_ref[...] + bias_ref[...].astype(jnp.float32)


def _pallas_mix(weights, biases, pool, table, pos, *, interpret):
    batch, pages_per_row = table.shape
    _, page_size, d = pool.shape
    n = weights.shape[0]
    span = pages_per_row * page_size
    if span > n:
        # the last page may run past the (n, n) weight square; pad the
        # column axis so every (1, page_size) block is in-bounds (the
        # causal mask kills the padded columns — and their pool rows are
        # real page rows, so the product is exact zero, not garbage)
        weights = jnp.pad(weights, ((0, 0), (0, span - n)))
    # biases come in as (n, 1) column vectors (ops/sgu.py layout)
    biases = biases.reshape(n, 1).T  # (1, n) -> block (1, 1) at [0, pos]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, pages_per_row),
        in_specs=[
            # weight row pos_b, column block p  (integer-only index maps:
            # scalar-prefetch refs indexed by grid coordinates)
            pl.BlockSpec((1, page_size),
                         lambda b, p, pos_ref, table_ref: (pos_ref[b], p)),
            # the pool page this row's table names for block p
            pl.BlockSpec((1, page_size, d),
                         lambda b, p, pos_ref, table_ref:
                         (table_ref[b, p], 0, 0)),
            pl.BlockSpec((1, 1),
                         lambda b, p, pos_ref, table_ref: (0, pos_ref[b])),
        ],
        out_specs=pl.BlockSpec((1, d),
                               lambda b, p, pos_ref, table_ref: (b, 0)),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
    )
    kernel = functools.partial(_mix_kernel, page_size=page_size,
                               pages_per_row=pages_per_row)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, d), jnp.float32),
        interpret=interpret,
    )(pos.astype(jnp.int32), table.astype(jnp.int32), weights, pool, biases)


def _pallas_mix_q8(weights, biases, pool, table, pos, w_scale, pool_scale,
                   *, interpret):
    """Quantized-path twin of :func:`_pallas_mix`: two extra scale
    operands, same grid/ragged-walk structure, dequant in the kernel
    epilogue (see :func:`_mix_kernel_q8`)."""
    batch, pages_per_row = table.shape
    num_pages, page_size, d = pool.shape
    n = weights.shape[0]
    span = pages_per_row * page_size
    if span > n:
        weights = jnp.pad(weights, ((0, 0), (0, span - n)))
    biases = biases.reshape(n, 1).T  # (1, n) -> block (1, 1) at [0, pos]
    # missing scales mean that side is full precision: all-ones is exact
    if w_scale is None:
        w_scale = jnp.ones((n,), jnp.float32)
    if pool_scale is None:
        pool_scale = jnp.ones((num_pages, page_size), jnp.float32)
    w_scale = w_scale.astype(jnp.float32).reshape(1, n)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(batch, pages_per_row),
        in_specs=[
            pl.BlockSpec((1, page_size),
                         lambda b, p, pos_ref, table_ref: (pos_ref[b], p)),
            pl.BlockSpec((1, page_size, d),
                         lambda b, p, pos_ref, table_ref:
                         (table_ref[b, p], 0, 0)),
            pl.BlockSpec((1, 1),
                         lambda b, p, pos_ref, table_ref: (0, pos_ref[b])),
            # the weight ROW's scale: scalar block, indexed like the bias
            pl.BlockSpec((1, 1),
                         lambda b, p, pos_ref, table_ref: (0, pos_ref[b])),
            # the pool page's per-row scales: indexed like the page
            pl.BlockSpec((1, page_size),
                         lambda b, p, pos_ref, table_ref:
                         (table_ref[b, p], 0)),
        ],
        out_specs=pl.BlockSpec((1, d),
                               lambda b, p, pos_ref, table_ref: (b, 0)),
        scratch_shapes=[pltpu.VMEM((1, d), jnp.float32)],
    )
    kernel = functools.partial(_mix_kernel_q8, page_size=page_size,
                               pages_per_row=pages_per_row)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((batch, d), jnp.float32),
        interpret=interpret,
    )(pos.astype(jnp.int32), table.astype(jnp.int32), weights, pool, biases,
      w_scale, pool_scale)


def _xla_mix(weights, biases, pool, table, pos, *, n_rows,
             w_scale=None, pool_scale=None):
    """Gather fallback, bit-matched to the dense decode contraction.

    Gathers each row's pages, slices to exactly ``n_rows`` (the dense
    engine's cache length) and runs the IDENTICAL masked f32 einsum the
    dense ``SGUDecode`` uses — stale rows in reused pages meet exact-zero
    causal weights, so the sums are bitwise those of the dense engine.
    Under quantization the int8 weight rows / pool rows dequantize in f32
    right after the gather (``w_scale`` per weight row, ``pool_scale``
    per pool row), so the contraction itself is unchanged.
    """
    batch, pages_per_row = table.shape
    _, page_size, d = pool.shape
    rows = pool[table].reshape(batch, pages_per_row * page_size, d)
    rows = rows[:, :n_rows].astype(jnp.float32)
    if pool_scale is not None:
        ps = pool_scale[table].reshape(batch, pages_per_row * page_size)
        rows = rows * ps[:, :n_rows, None]
    w_rows = weights.astype(jnp.float32)[pos][:, :n_rows]
    if w_scale is not None:
        w_rows = w_rows * w_scale.astype(jnp.float32)[pos][:, None]
    causal = jnp.arange(n_rows)[None, :] <= pos[:, None]
    w_rows = w_rows * causal.astype(jnp.float32)
    mixed = jnp.einsum("bnd,bn->bd", rows, w_rows,
                       preferred_element_type=jnp.float32)
    bias_m = biases.astype(jnp.float32)[pos]  # (B, 1), dense layout
    return mixed + bias_m


def paged_gate_mix(weights, biases, pool, table, pos, *, n_rows,
                   impl="xla", interpret=None, w_scale=None,
                   pool_scale=None):
    """Ragged paged spatial-gate contraction.

    Args:
      weights: ``(n, n)`` learned causal spatial weights (f32, or int8
        when ``w_scale`` is given).
      biases: ``(n, 1)`` spatial biases.
      pool: ``(num_pages, page_size, d)`` global gate-row pool (compute
        dtype, or int8 when ``pool_scale`` is given).
      table: ``(B, pages_per_row)`` int32 page table (NULL_PAGE for
        unowned entries).
      pos: ``(B,)`` int32 current positions.
      n_rows: dense cache length the XLA path slices to (the fixed-slot
        engine's ``decode_len``) — keeps the fallback bit-identical to
        the dense contraction.
      impl: ``"xla"`` (gather fallback) or ``"pallas"`` (ragged kernel).
      interpret: force/disable the Pallas interpreter; None auto-selects
        it off-TPU.
      w_scale: optional ``(n,)`` f32 per-row scale for int8 weights.
      pool_scale: optional ``(num_pages, page_size)`` f32 per-row scale
        pool for int8 gate pages.

    Returns:
      ``(B, d)`` f32 ``mixed + bias`` (caller casts to the compute dtype
      and applies the gate multiply, matching dense ``SGUDecode``).
    """
    if impl == "xla":
        return _xla_mix(weights, biases, pool, table, pos, n_rows=n_rows,
                        w_scale=w_scale, pool_scale=pool_scale)
    if impl != "pallas":
        raise ValueError(f"unknown paged gate impl: {impl!r}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if w_scale is None and pool_scale is None:
        # full precision keeps the ORIGINAL kernel: the bit-identity
        # contract of the default path must not depend on all-ones
        # multiplies optimizing away
        return _pallas_mix(weights, biases, pool, table, pos,
                           interpret=interpret)
    return _pallas_mix_q8(weights, biases, pool, table, pos,
                          w_scale, pool_scale, interpret=interpret)


def write_gate_row(pool, table, pos, gate, write_ok, scale=None):
    """Scatter each live row's freshly computed gate into its page.

    Rows with ``write_ok=False`` (done / inactive / paused) and rows
    whose table entry is still NULL are redirected to the write-sink
    ``DUMP_PAGE`` — the scatter stays dense and unpredicated, and the
    zero page plus read-only shared pages are never clobbered.

    With ``scale`` (the ``(num_pages, page_size)`` f32 scale pool of an
    int8 gate pool) the row is quantized per-row on scatter — the int8
    code and its f32 scale land through the SAME redirected target — and
    the call returns ``(pool, scale)`` instead of ``pool``.
    """
    page_size = pool.shape[1]
    tgt = jnp.take_along_axis(table, (pos // page_size)[:, None],
                              axis=1)[:, 0]
    tgt = jnp.where(write_ok & (tgt != NULL_PAGE), tgt, DUMP_PAGE)
    if scale is None:
        return pool.at[tgt, pos % page_size].set(gate)
    q, s = quantize_rows(gate)
    return (pool.at[tgt, pos % page_size].set(q),
            scale.at[tgt, pos % page_size].set(s))
