"""Spatial gating unit core op (gMLP token mixing).

Contract (reference ``/root/reference/progen_transformer/progen.py:166-185``):
the gate half is mixed across positions by a LEARNED causal ``(n, n)``
matrix: ``out[m] = sum_{n<=m} weights[m, n] * gate[n] + bias[m]``.  The
reference writes this as ``einsum('n d, m n -> m d')`` with a ``tril`` mask
on the weights.  This is dense O(n²) token mixing — on TPU it is a single
big MXU matmul, which is exactly where it wants to live.

The mask is applied to the weights (not the output), so gradients to the
upper triangle are hard zeros — matching the reference's parameterization.
"""

from __future__ import annotations

import jax.numpy as jnp


def causal_mask(n: int, dtype=jnp.float32):
    return jnp.tril(jnp.ones((n, n), dtype=dtype))


def spatial_gate(gate, weights, biases):
    """Mix ``gate`` ``(..., n, d)`` with causal ``weights`` ``(n, n)`` and
    ``biases`` ``(n, 1)``.

    Weight masking and the matmul accumulate in f32 (MXU accumulator) —
    the learned weights start at ~1e-6 scale (init U(±eps/n)), far below
    bf16 resolution around 1.0.
    """
    n = weights.shape[0]
    w = weights * causal_mask(n, weights.dtype)
    mixed = jnp.einsum(
        "...nd,mn->...md", gate, w, preferred_element_type=jnp.float32
    )
    mixed = mixed + biases
    return mixed.astype(gate.dtype)
