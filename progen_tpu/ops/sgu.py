"""Spatial gating unit core op (gMLP token mixing).

Contract (reference ``/root/reference/progen_transformer/progen.py:166-185``):
the gate half is mixed across positions by a LEARNED causal ``(n, n)``
matrix: ``out[m] = sum_{n<=m} weights[m, n] * gate[n] + bias[m]``.  The
reference writes this as ``einsum('n d, m n -> m d')`` with a ``tril`` mask
on the weights.  This is dense O(n²) token mixing — on TPU it is a single
big MXU matmul, which is exactly where it wants to live.

The mask is applied to the weights (not the output), so gradients to the
upper triangle are hard zeros — matching the reference's parameterization.
"""

from __future__ import annotations

import functools

import jax.numpy as jnp


@functools.lru_cache(maxsize=8)
def _causal_mask_f32(n: int):
    # one f32 mask per seq length for the whole process; callers cast —
    # rebuilding (and re-tril'ing) an (n, n) constant per call at the
    # weights' dtype was pure waste under jit too (fresh consts per trace)
    return jnp.tril(jnp.ones((n, n), dtype=jnp.float32))


def causal_mask(n: int, dtype=jnp.float32):
    m = _causal_mask_f32(n)
    return m if dtype == jnp.float32 else m.astype(dtype)


def spatial_gate(gate, weights, biases):
    """Mix ``gate`` ``(..., n, d)`` with causal ``weights`` ``(n, n)`` and
    ``biases`` ``(n, 1)``.

    Weight masking and the matmul accumulate in f32 (MXU accumulator) —
    the learned weights start at ~1e-6 scale (init U(±eps/n)), far below
    bf16 resolution around 1.0.
    """
    # tril directly on the weights: same hard-zero parameterization (upper
    # triangle grads stay exactly zero through the tril transpose) without
    # materializing a separate (n, n) mask operand in the step
    w = jnp.tril(weights)
    mixed = jnp.einsum(
        "...nd,mn->...md", gate, w, preferred_element_type=jnp.float32
    )
    mixed = mixed + biases
    return mixed.astype(gate.dtype)
