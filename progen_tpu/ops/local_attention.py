"""Windowed causal local attention — the model's hot op.

Behavioral contract (reference
``/root/reference/progen_transformer/progen.py:79-103``):

* ``seq_len % window_size == 0``; the sequence is reshaped into
  ``w = L / wsz`` windows;
* keys/values get a ZERO window prepended, then each query window attends
  over ``[previous window ‖ own window]`` = ``2*wsz`` keys;
* mask is ``tril(ones(wsz, 2*wsz), k=wsz)`` — causal within the own window,
  full visibility of the previous window; masked logits get ``-1e10``;
* scale ``dim_head ** -0.5``; softmax stabilized by max-subtraction.

TPU-first differences from the reference (same math, better mapping):

* natively batched ``(B, H, L, Dh)`` — no vmap wrapper;
* QK^T runs with ``preferred_element_type=float32`` so the MXU accumulates
  in f32, and the softmax runs in f32 even under bf16 compute;
* the mask is folded in with ``jnp.where`` on the f32 logits — XLA fuses
  mask+softmax into the matmul epilogue.

Effective receptive field per layer: ``wsz`` to ``2*wsz - 1`` tokens; depth
stacks extend context to the full sequence.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

ATTN_MASK_VALUE = -1e10


@functools.lru_cache(maxsize=None)
def _window_mask_np(window_size: int):
    import numpy as np

    return np.tril(np.ones((window_size, 2 * window_size), dtype=bool), k=window_size)


def window_mask(window_size: int) -> jax.Array:
    """``(wsz, 2*wsz)`` bool mask: query i sees keys j with j <= i + wsz."""
    return jnp.asarray(_window_mask_np(window_size))


def concat_previous_window(t):
    """``(..., W, n, d) -> (..., W, 2n, d)``: prepend a zero window, then
    pair each window with its predecessor."""
    pad = [(0, 0)] * (t.ndim - 3) + [(1, 0), (0, 0), (0, 0)]
    t = jnp.pad(t, pad)
    return jnp.concatenate((t[..., :-1, :, :], t[..., 1:, :, :]), axis=-2)


def local_attention(q, k, v, *, window_size: int, scale: float | None = None):
    """Windowed attention over ``(B, H, L, Dh)`` tensors -> ``(B, H, L, Dh)``.

    ``k``/``v`` may already be window-formatted ``(B, H, W, 2*wsz, Dh)`` (the
    context-parallel halo path builds them that way); otherwise they are
    ``(B, H, L, Dh)`` like ``q`` and the previous-window concat happens here.
    """
    b, h, n, d = q.shape
    wsz = window_size
    if n % wsz != 0:
        raise ValueError(f"sequence length {n} must be divisible by window {wsz}")
    w = n // wsz
    scale = d ** -0.5 if scale is None else scale

    qw = q.reshape(b, h, w, wsz, d)
    if k.ndim == 4:
        kw = concat_previous_window(k.reshape(b, h, w, wsz, d))
        vw = concat_previous_window(v.reshape(b, h, w, wsz, d))
    else:
        kw, vw = k, v

    sim = jnp.einsum(
        "bhwid,bhwjd->bhwij", qw, kw, preferred_element_type=jnp.float32
    ) * scale
    mask = window_mask(wsz)
    sim = jnp.where(mask, sim, ATTN_MASK_VALUE)
    attn = jax.nn.softmax(sim, axis=-1).astype(vw.dtype)
    out = jnp.einsum(
        "bhwij,bhwjd->bhwid", attn, vw, preferred_element_type=jnp.float32
    ).astype(vw.dtype)
    return out.reshape(b, h, n, d)
