"""Device-mesh construction — the substrate for every parallelism strategy.

The reference's only parallelism is a single-host ``pmap`` data-parallel flag
(``/root/reference/progen_transformer/utils.py:69-91``); its README leaves
"model parallelism with pjit" as an unchecked TODO
(``/root/reference/README.md:104``).  Here, every strategy — DP, FSDP, TP and
sequence/context parallelism — is a sharding rule over ONE logical mesh with
four axes:

    ('data', 'fsdp', 'tensor', 'seq')

* ``data``    — pure data parallelism (batch split, params replicated)
* ``fsdp``    — batch split AND params/optimizer-state sharded (ZeRO-3 style)
* ``tensor``  — megatron-style tensor parallelism inside each matmul
* ``seq``     — sequence/context parallelism (activations split along L,
                halo exchange for the local-attention window structure)

Axis sizes multiply to the device count; unused axes have size 1.  XLA lays
consecutive mesh dims onto ICI neighbours, so the innermost (most
communication-hungry) axes — ``tensor``/``seq`` — go last.
"""

from __future__ import annotations

import dataclasses
import math

import jax
import numpy as np
from jax.sharding import Mesh

MESH_AXES = ("data", "fsdp", "tensor", "seq")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Sizes for each logical mesh axis. ``-1`` on one axis means "absorb the
    remaining devices" (like a reshape wildcard)."""

    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1

    @classmethod
    def parse(cls, spec: str) -> "MeshConfig":
        """Parse a CLI mesh spec ``"data,fsdp,tensor,seq"`` (e.g.
        ``"-1,1,1,1"``; ``-1`` = absorb remaining devices)."""
        parts = spec.split(",")
        if len(parts) != 4:
            raise ValueError(
                f"mesh spec {spec!r} must have exactly 4 comma-separated "
                "sizes: data,fsdp,tensor,seq (e.g. '-1,1,1,1')"
            )
        try:
            sizes = [int(p) for p in parts]
        except ValueError as e:
            raise ValueError(
                f"mesh spec {spec!r}: every size must be an integer "
                "(data,fsdp,tensor,seq)"
            ) from e
        return cls(*sizes)

    def resolve(self, n_devices: int) -> tuple[int, int, int, int]:
        sizes = [self.data, self.fsdp, self.tensor, self.seq]
        wild = [i for i, s in enumerate(sizes) if s == -1]
        if len(wild) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(s for s in sizes if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {n_devices}"
            )
        return tuple(sizes)


def auto_factorize(n_devices: int, *, use_fsdp: bool = True,
                   use_tp: bool = True, use_sp: bool = True) -> MeshConfig:
    """Factor ``n_devices`` onto ``(data, fsdp, tensor, seq)`` innermost
    first: each enabled inner axis (seq, then tensor, then fsdp) absorbs one
    factor of 2 while the remainder stays even; whatever is left becomes the
    data axis.  This is the one canonical auto-factorization — the dryrun
    entry point and the mesh benchmark both use it, so "8 devices" always
    means the same ``(1, 2, 2, 2)`` mesh everywhere."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    sizes = {"seq": 1, "tensor": 1, "fsdp": 1}
    rem = n_devices
    for axis, enabled in (("seq", use_sp), ("tensor", use_tp),
                          ("fsdp", use_fsdp)):
        if enabled and rem % 2 == 0 and rem > 1:
            sizes[axis] = 2
            rem //= 2
    return MeshConfig(data=rem, fsdp=sizes["fsdp"], tensor=sizes["tensor"],
                      seq=sizes["seq"])


def process_batch_shards(mesh: Mesh) -> tuple[int, int]:
    """Group the processes behind ``mesh`` by the slice of the batch
    ('data','fsdp') super-axis their devices cover, and return
    ``(shard_count, shard_index)`` for THIS process.

    This is the data-loading contract for process-spanning meshes: the
    batch dim shards over ``('data','fsdp')`` only, so two processes whose
    devices sit at the same (data, fsdp) coordinates — e.g. the two halves
    of a process-spanning tensor axis — must load IDENTICAL rows, while
    processes at different batch coordinates load disjoint shards.  With a
    pure-dp mesh of one device per process this degenerates to
    ``(jax.process_count(), jax.process_index())``, the pre-mesh behavior.

    Raises when a process's devices straddle several distinct batch
    coverage patterns that other processes don't share exactly — a mesh
    layout the per-process feed (`make_array_from_process_local_data` with
    contiguous local rows) cannot express.
    """
    devs = np.asarray(mesh.devices)
    n_fsdp = devs.shape[1]
    coverage: dict[int, set[int]] = {}
    for idx in np.ndindex(*devs.shape):
        batch_coord = idx[0] * n_fsdp + idx[1]
        coverage.setdefault(devs[idx].process_index, set()).add(batch_coord)
    me = jax.process_index()
    if me not in coverage:
        raise ValueError(
            f"process {me} owns no devices in mesh {dict(mesh.shape)}"
        )
    # distinct coverage sets, ordered by their first batch coordinate; any
    # overlap between distinct sets means the grouping is ambiguous
    groups: list[frozenset[int]] = sorted(
        {frozenset(s) for s in coverage.values()}, key=min
    )
    claimed: set[int] = set()
    for g in groups:
        if claimed & g:
            raise ValueError(
                "mesh layout shards the batch axis inconsistently across "
                f"processes (coverage sets {sorted(map(sorted, groups))}); "
                "keep each process's devices at one contiguous (data, fsdp) "
                "block"
            )
        claimed |= g
    return len(groups), groups.index(frozenset(coverage[me]))


def make_mesh(config: MeshConfig | None = None, devices=None) -> Mesh:
    """Build the 4-axis mesh over the given (default: all) devices.

    ``jax.experimental.mesh_utils.create_device_mesh`` picks an ICI-friendly
    device order on real TPU slices; on CPU/virtual devices a plain reshape
    is used.
    """
    config = config or MeshConfig()
    devices = devices if devices is not None else jax.devices()
    sizes = config.resolve(len(devices))
    if devices[0].platform == "tpu":
        from jax.experimental import mesh_utils

        dev_array = mesh_utils.create_device_mesh(sizes, devices=devices)
    else:
        dev_array = np.asarray(devices).reshape(sizes)
    return Mesh(dev_array, MESH_AXES)


def single_device_mesh(device=None) -> Mesh:
    """A 1×1×1×1 mesh — lets every code path be mesh-driven, even one chip."""
    device = device or jax.devices()[0]
    return Mesh(np.asarray([device]).reshape(1, 1, 1, 1), MESH_AXES)


def initialize_distributed(coordinator_address: str | None = None,
                           num_processes: int | None = None,
                           process_id: int | None = None) -> None:
    """Multi-host runtime init (replaces: nothing — the reference is
    single-process only, ``utils.py:80`` uses ``jax.local_device_count``).

    On TPU pods with default env vars, ``jax.distributed.initialize()`` with
    no arguments autodetects everything.  Safe to call exactly once per
    process before any other JAX call.

    Connection attempts are retried with backoff: at pod bring-up the
    coordinator routinely comes up seconds after the workers (its
    UNAVAILABLE/DEADLINE_EXCEEDED gRPC errors classify as transient;
    "already initialized" is fatal and propagates immediately).
    Env-tunable via ``PROGEN_DIST_RETRY_*``.
    """
    from progen_tpu.resilience import faults
    from progen_tpu.resilience.retry import RetryPolicy, retry_call

    kwargs = {}
    if coordinator_address is not None:
        kwargs.update(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )

    def _init() -> None:
        faults.inject("dist.init")
        jax.distributed.initialize(**kwargs)

    retry_call(
        _init,
        policy=RetryPolicy.from_env("PROGEN_DIST_RETRY", base_delay=1.0,
                                    max_attempts=5, deadline=300.0),
        label="jax.distributed.initialize",
    )
