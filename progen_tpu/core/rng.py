"""Deterministic threaded RNG.

The reference monkeypatches ``jax.random.uniform``/``bernoulli`` with a
key-ignoring ``lax.rng_uniform`` for GPU speed
(``/root/reference/progen_transformer/utils.py:139-158``) and draws keys from
a stateful ``haiku.PRNGSequence``.  Neither survives on TPU-first design:
the monkeypatch breaks reproducibility and SPMD determinism, and stateful key
sequences don't jit.  This module is the conscious replacement — pure
``jax.random`` key threading with small helpers.
"""

from __future__ import annotations

from typing import Iterator

import jax


class KeySeq:
    """Host-side key sequence for driver loops (not for use inside jit).

    Drop-in for the reference's ``haiku.PRNGSequence(seed)`` usage at
    ``/root/reference/train.py:112`` / ``sample.py:50``.
    """

    def __init__(self, seed: int | jax.Array):
        if isinstance(seed, int):
            self._key = jax.random.key(seed)
        else:
            self._key = seed

    def __next__(self) -> jax.Array:
        self._key, sub = jax.random.split(self._key)
        return sub

    def __iter__(self) -> Iterator[jax.Array]:
        return self

    def take(self, n: int):
        keys = jax.random.split(self._key, n + 1)
        self._key = keys[0]
        return keys[1:]
