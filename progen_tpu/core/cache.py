"""Persistent XLA compilation cache.

A fresh process pays 20-40s to compile the train step and MINUTES for the
1024-step decode scan (measured ~4 min for ProGen-small's sampler on a
v5e).  JAX can persist compiled executables to disk; enabling it makes
restarts, resume-after-preemption and the sample CLI start in seconds.

Off by default inside the library (libraries should not write to disk
unasked); the CLIs call :func:`enable_compilation_cache` at startup.
``PROGEN_COMPILE_CACHE=0`` disables; ``PROGEN_COMPILE_CACHE=<dir>``
relocates.
"""

from __future__ import annotations

import os


def honor_env_platforms() -> None:
    """Apply ``JAX_PLATFORMS`` from the environment as a config update.

    This image's jax build hardwires its default platform list and
    ignores the env var; every CLI entrypoint calls this (before any
    backend initialization) so ``JAX_PLATFORMS=cpu`` behaves as users
    expect — e.g. driving the virtual 8-device CPU mesh."""
    if os.environ.get("JAX_PLATFORMS"):
        import jax

        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def enable_compilation_cache(default_dir: str = "~/.cache/progen_tpu/xla") -> str | None:
    """Turn on JAX's on-disk compilation cache (honoring the env knob).

    Returns the cache dir, or None when disabled.  Safe to call multiple
    times and before any backend initialization.
    """
    knob = os.environ.get("PROGEN_COMPILE_CACHE", "")
    if knob == "0":
        return None
    cache_dir = os.path.expanduser(knob or default_dir)

    import jax

    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything that took meaningful compile time; tiny
        # programs are cheaper to recompile than to hash+read
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return None  # unwritable dir / unsupported backend: run uncached
    return cache_dir
