from progen_tpu.core.cache import enable_compilation_cache
from progen_tpu.core.mesh import MESH_AXES, MeshConfig, make_mesh, single_device_mesh
from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.core.rng import KeySeq

__all__ = [
    "enable_compilation_cache",
    "MESH_AXES",
    "MeshConfig",
    "make_mesh",
    "single_device_mesh",
    "Policy",
    "make_policy",
    "KeySeq",
]
