"""Mixed-precision policy for TPU.

The reference opts into a jmp policy ``params=float32, compute=float16,
output=float32`` set class-wide on its Haiku model
(``/root/reference/progen_transformer/progen.py:235-241``).  On TPU the MXU
natively computes in bfloat16, so the TPU-first policy is
``params=float32, compute=bfloat16, output=float32`` — the reference README's
own TODO list records "bfloat16 on xla" as the intended TPU path
(``/root/reference/README.md:111``).

Instead of monkeypatching module classes (the jmp/Haiku approach), the policy
is a plain dataclass threaded explicitly through the model: params live in
``param_dtype``, blocks compute in ``compute_dtype`` via flax's ``dtype=``
promotion inside Embed/LayerNorm/Dense, and the final logits are cast to
``output_dtype``.  The policy is visible to XLA as ordinary
``convert_element_type`` ops it can fuse.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class Policy:
    param_dtype: jnp.dtype = jnp.float32
    compute_dtype: jnp.dtype = jnp.bfloat16
    output_dtype: jnp.dtype = jnp.float32

    def cast_to_output(self, x):
        return jnp.asarray(x, self.output_dtype)


def make_policy(mixed_precision: bool = True) -> Policy:
    """``mixed_precision=False`` computes in f32 end to end (parity/test mode).

    Mirrors the reference's ``ProGen(mixed_precision=...)`` kwarg
    (``progen.py:235``) but defaults to bf16 compute, the TPU-native choice.
    """
    if mixed_precision:
        return Policy(jnp.float32, jnp.bfloat16, jnp.float32)
    return Policy(jnp.float32, jnp.float32, jnp.float32)
