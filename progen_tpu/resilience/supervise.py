"""Stage supervision for the multi-process serving runtime.

The serving cluster (``progen_tpu/serve/``, docs/SERVING.md §7) runs
prefill workers and decode replicas as child processes.  When one dies
(EOF on its socket, stale heartbeat, or a poisoned frame stream), the
router asks the :class:`StageSupervisor` whether to restart it.  The
supervisor is pure host-side policy — a bounded restart budget per
stage instance — so the decision is auditable and a crash-looping
worker can't burn the cluster forever: past the budget the router sheds
the affected requests as typed ``FAILED_FAULT`` completions instead
(load shedding produces a COMPLETION, never an exception — the same
contract as the in-process engine).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class StageEvent:
    """One supervision decision, kept for the stats record."""

    role: str
    index: int
    granted: bool
    reason: str
    at: float


class StageSupervisor:
    """Bounded per-stage-instance restart budget.

    ``max_restarts`` is per ``(role, index)`` — one flapping prefill
    worker exhausting its budget does not consume the replicas'.
    ``min_interval_s`` rejects restarts that come faster than a real
    process could have done useful work (crash-loop detection).
    """

    def __init__(self, max_restarts: int = 1, min_interval_s: float = 0.0):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = max_restarts
        self.min_interval_s = min_interval_s
        self._counts: dict[tuple[str, int], int] = {}
        self._last: dict[tuple[str, int], float] = {}
        self.events: list[StageEvent] = []

    def request_restart(self, role: str, index: int,
                        reason: str = "") -> bool:
        """True iff the stage instance may be respawned; each grant
        consumes one unit of that instance's budget."""
        key = (role, index)
        now = time.perf_counter()
        used = self._counts.get(key, 0)
        if used >= self.max_restarts:
            granted, why = False, f"budget exhausted ({used})"
        elif now - self._last.get(key, -1e18) < self.min_interval_s:
            granted, why = False, "crash-looping (under min_interval_s)"
        else:
            granted, why = True, reason or "granted"
            self._counts[key] = used + 1
            self._last[key] = now
        self.events.append(StageEvent(role, index, granted, why, now))
        return granted

    def restarts_used(self, role: str, index: int) -> int:
        return self._counts.get((role, index), 0)

    # The control plane (serve/control.py) grows and shrinks the fleet:
    # a freshly scaled-up instance gets a FULL budget simply by being a
    # new (role, index) — indices are never reused — and a retired
    # instance is forgotten so its history can't be charged to a future
    # worker, nor linger in the stats of a long-lived cluster.

    def forget(self, role: str, index: int) -> None:
        """Drop all supervision state for a retired stage instance."""
        key = (role, index)
        self._counts.pop(key, None)
        self._last.pop(key, None)
        self.events.append(StageEvent(role, index, True, "retired",
                                      time.perf_counter()))

    def stats(self) -> dict:
        return {
            "max_restarts": self.max_restarts,
            "restarts": {f"{r}:{i}": n
                         for (r, i), n in sorted(self._counts.items())},
            "denied": sum(1 for e in self.events if not e.granted),
        }
