"""Stall watchdog + flight recorder for the training loop.

A hung collective on a TPU pod does not crash — it sits forever inside a
device sync while the job burns its reservation (the failure mode that
cost round 5 its dryrun artifact: rc=124 after a silent 870s hang).  The
watchdog turns "hangs forever" into "exits nonzero with a diagnosis":

* the train loop calls :meth:`Watchdog.beat` once per step;
* a monitor thread checks the heartbeat age; past ``timeout`` seconds it
  **dumps every thread's Python stack** (``sys._current_frames`` plus a
  ``faulthandler`` dump, which still works when a thread is wedged in a
  C extension) and the :class:`FlightRecorder` ring — the last N steps'
  losses, step times and checkpoint events — to the run directory, then
  exits nonzero (``os._exit``: a stuck collective blocks normal
  interpreter teardown, which is the very condition being escaped).

Both pieces are pure stdlib (no jax import) so data-prep workers and
tests can use them too.  ``exit_fn`` is injectable for in-process tests.
"""

from __future__ import annotations

import collections
import faulthandler
import json
import os
import sys
import threading
import time
import traceback
from typing import Any, Callable

WATCHDOG_EXIT_CODE = 42  # distinct from generic failure (1) and SIGKILL


class FlightRecorder:
    """Bounded ring of recent loop events, dumpable as JSON.

    Events are dicts with a ``kind`` plus whatever the caller attaches
    (step, loss, step seconds, checkpoint paths...).  Appends are O(1)
    and lock-free enough for one writer per thread (deque is
    thread-safe for append/iteration)."""

    def __init__(self, capacity: int = 64):
        self.capacity = capacity
        self._ring: collections.deque = collections.deque(maxlen=capacity)

    def record(self, kind: str, **fields: Any) -> None:
        event = {"t": time.time(), "kind": kind}
        event.update(fields)
        self._ring.append(event)

    def snapshot(self) -> list[dict]:
        return list(self._ring)

    def dump(self, path: str) -> str:
        with open(path, "w") as fh:
            json.dump({"capacity": self.capacity,
                       "events": self.snapshot()}, fh, indent=1)
        return path


def dump_all_stacks(fh) -> None:
    """Write every thread's Python stack to ``fh`` (readable form first,
    then faulthandler's, which also reaches threads wedged in C)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in frames.items():
        fh.write(f"\n--- thread {names.get(ident, '?')} ({ident}) ---\n")
        fh.write("".join(traceback.format_stack(frame)))
    fh.write("\n--- faulthandler ---\n")
    fh.flush()
    try:
        faulthandler.dump_traceback(file=fh, all_threads=True)
    except Exception:
        pass  # some file objects lack a usable fileno


class Watchdog:
    """Heartbeat monitor around a loop that must keep making progress.

    ``timeout``: max seconds between :meth:`beat` calls before tripping.
    ``out_dir``: where the stack/flight-recorder artifacts land.
    ``exit_fn``: called with :data:`WATCHDOG_EXIT_CODE` after the dump
    (default ``os._exit`` — see module docstring); tests inject a raiser.
    Use as a context manager, or ``start()``/``stop()``.
    """

    def __init__(
        self,
        timeout: float,
        out_dir: str = ".",
        recorder: FlightRecorder | None = None,
        exit_fn: Callable[[int], None] = os._exit,
        poll_interval: float | None = None,
        label: str = "train-loop",
    ):
        if timeout <= 0:
            raise ValueError(f"timeout must be > 0, got {timeout}")
        self.timeout = timeout
        self.out_dir = out_dir
        self.recorder = recorder
        self.label = label
        self._exit_fn = exit_fn
        self._poll = poll_interval if poll_interval is not None else min(
            1.0, timeout / 4.0)
        self._last_beat = time.monotonic()
        self._last_note: str | None = None
        self._stop = threading.Event()
        self._paused = 0
        self._lock = threading.Lock()
        self._thread: threading.Thread | None = None
        self.tripped = False
        self.artifacts: list[str] = []

    # -- heartbeat ----------------------------------------------------------

    def beat(self, note: str | None = None) -> None:
        with self._lock:
            self._last_beat = time.monotonic()
            if note is not None:
                self._last_note = note

    def paused(self):
        """Context manager suspending the stall check for a section that
        is legitimately slow (e.g. a cold jit compile)."""
        wd = self

        class _Paused:
            def __enter__(self):
                with wd._lock:
                    wd._paused += 1
                return wd

            def __exit__(self, *exc):
                with wd._lock:
                    wd._paused -= 1
                    wd._last_beat = time.monotonic()
                return False

        return _Paused()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self.beat()
        self._thread = threading.Thread(
            target=self._monitor, name="progen-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll * 4 + 1.0)
            self._thread = None

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- monitor ------------------------------------------------------------

    def _monitor(self) -> None:
        while not self._stop.wait(self._poll):
            with self._lock:
                if self._paused > 0:
                    continue
                age = time.monotonic() - self._last_beat
            if age > self.timeout:
                self._trip(age)
                return

    def _trip(self, age: float) -> None:
        self.tripped = True
        stamp = time.strftime("%Y%m%d-%H%M%S")
        os.makedirs(self.out_dir, exist_ok=True)
        stacks_path = os.path.join(
            self.out_dir, f"watchdog_stacks_{stamp}.txt")
        try:
            with open(stacks_path, "w") as fh:
                fh.write(
                    f"watchdog [{self.label}]: no heartbeat for {age:.1f}s "
                    f"(timeout {self.timeout:.1f}s); last note: "
                    f"{self._last_note!r}\n")
                dump_all_stacks(fh)
            self.artifacts.append(stacks_path)
        except Exception as e:
            print(f"watchdog: stack dump failed ({e!r})", file=sys.stderr)
        if self.recorder is not None:
            ring_path = os.path.join(
                self.out_dir, f"watchdog_flight_{stamp}.json")
            try:
                self.recorder.dump(ring_path)
                self.artifacts.append(ring_path)
            except Exception as e:
                print(f"watchdog: flight-recorder dump failed ({e!r})",
                      file=sys.stderr)
        # the span ring rides along when this process is tracing: the
        # last N spans before the stall are exactly the diagnosis a hung
        # serve/train loop needs (import stays lazy — observe.trace is
        # stdlib, but the observe package itself is not)
        try:
            from progen_tpu.observe.trace import get_tracer

            tracer = get_tracer()
            if tracer.enabled and tracer.ring():
                trace_path = os.path.join(
                    self.out_dir, f"watchdog_trace_{stamp}.json")
                tracer.dump(trace_path)
                self.artifacts.append(trace_path)
        except Exception as e:
            print(f"watchdog: trace-ring dump failed ({e!r})",
                  file=sys.stderr)
        print(
            f"watchdog [{self.label}]: stalled for {age:.1f}s "
            f"(> {self.timeout:.1f}s); dumped {self.artifacts} — exiting "
            f"{WATCHDOG_EXIT_CODE}",
            file=sys.stderr,
            flush=True,
        )
        self._exit_fn(WATCHDOG_EXIT_CODE)
