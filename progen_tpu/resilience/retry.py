"""Generic retry with exponential backoff — the I/O fault boundary.

At pod scale, transient failure is the steady state: GCS returns 503s,
the TPU tunnel drops mid-save, the coordination service takes a few
seconds to come up before ``jax.distributed.initialize`` can connect
(GSPMD-scale training treats preemption and flaky storage as routine,
arXiv 2105.04663 / 2204.06514).  Every storage/init seam in this stack —
checkpoint save/restore (``checkpoint/store.py``), tfrecord stream
opening (``data/tfrecord.py``), distributed init (``core/mesh.py``) —
routes its attempts through :func:`retry_call` so one policy decides
what is retried, how long, and with what backoff.

Design points:

* **classifier, not exception whitelist**: transient-vs-fatal is decided
  by :func:`default_classifier` (overridable per policy) from the
  exception TYPE and its MESSAGE — gRPC/absl-style errors surface as
  plain ``RuntimeError`` with a status word (``UNAVAILABLE``,
  ``DEADLINE_EXCEEDED``) in the text, and tensorflow/tensorstore error
  classes are matched by name so this module never imports them;
* **seeded jitter**: backoff delays are deterministic per
  ``RetryPolicy.seed`` — a retry schedule that tests can assert on
  exactly (decorrelated-jitter randomness without ``random``'s global
  state);
* **total deadline** caps the whole retry loop, and **per-attempt
  timeout** bounds a single hung attempt by running it on a daemon
  thread and abandoning it (a thread blocked in a C extension cannot be
  killed — abandonment + retry is the honest option, and the watchdog
  layer backstops a truly wedged process);
* every attempt is observable via ``on_retry`` (the trainer logs them).
"""

from __future__ import annotations

import dataclasses
import functools
import queue
import random
import threading
import time
from typing import Any, Callable, Iterator


class AttemptTimeout(Exception):
    """A single attempt exceeded ``RetryPolicy.attempt_timeout``.

    The attempt's thread is abandoned (daemon), not killed; the retry
    loop proceeds as if the attempt had raised a transient error.
    """


class RetryError(Exception):
    """All attempts exhausted (or deadline hit). ``__cause__`` is the
    last underlying exception."""

    def __init__(self, msg: str, attempts: int, elapsed: float):
        super().__init__(msg)
        self.attempts = attempts
        self.elapsed = elapsed


# Status words that mark an error text as transient.  These are the
# RPC-ish statuses GCS/tensorstore/gRPC/the JAX coordination service
# produce for conditions that a later attempt can outlive; config errors
# (NOT_FOUND, PERMISSION_DENIED, INVALID_ARGUMENT) are deliberately
# absent — retrying those only delays the real failure.
_TRANSIENT_MARKERS = (
    "UNAVAILABLE",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "RESOURCE_EXHAUSTED",  # GCS 429 rate limiting, not host OOM
    "connection reset",
    "connection refused",
    "temporarily unavailable",
    "timed out",
    "timeout",
    "broken pipe",
    "503",
    "502",
    "429",
)

# Exception class NAMES treated as transient without importing their
# packages (tf.errors.*, google.api_core, requests, tensorstore all
# surface one of these).
_TRANSIENT_TYPE_NAMES = frozenset({
    "UnavailableError",
    "DeadlineExceededError",
    "AbortedError",
    "ServiceUnavailable",
    "TooManyRequests",
    "RetryError",
    "ChunkedEncodingError",
})


def default_classifier(exc: BaseException) -> bool:
    """True when ``exc`` looks transient (worth retrying)."""
    if isinstance(exc, AttemptTimeout):
        return True
    if isinstance(exc, (ConnectionError, TimeoutError, InterruptedError)):
        return True
    # OSError covers flaky local/NFS/FUSE I/O, but NotADirectoryError /
    # FileNotFoundError / PermissionError subclasses are config errors
    if isinstance(exc, OSError) and not isinstance(
        exc, (FileNotFoundError, NotADirectoryError, IsADirectoryError,
              PermissionError)
    ):
        return True
    for klass in type(exc).__mro__:
        if klass.__name__ in _TRANSIENT_TYPE_NAMES:
            return True
    text = str(exc).lower()
    return any(m.lower() in text for m in _TRANSIENT_MARKERS)


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Backoff/limits for one retry loop.

    ``max_attempts`` counts the first try; ``base_delay * multiplier**k``
    capped at ``max_delay`` spaces attempts, each delay scaled by a
    seeded jitter factor in ``[1-jitter, 1+jitter]``.  ``deadline`` caps
    total wall time across attempts AND sleeps; ``attempt_timeout``
    bounds one attempt (None = unbounded).
    """

    max_attempts: int = 4
    base_delay: float = 0.5
    multiplier: float = 2.0
    max_delay: float = 8.0
    jitter: float = 0.25
    deadline: float | None = 120.0
    attempt_timeout: float | None = None
    seed: int = 0
    classifier: Callable[[BaseException], bool] = default_classifier

    @classmethod
    def from_env(cls, prefix: str = "PROGEN_RETRY", **overrides) -> "RetryPolicy":
        """Policy with knobs read from ``{prefix}_ATTEMPTS`` /
        ``_BASE_DELAY`` / ``_MAX_DELAY`` / ``_DEADLINE`` /
        ``_ATTEMPT_TIMEOUT`` env vars (unset = dataclass defaults)."""
        import os

        def num(name, cast, default):
            raw = os.environ.get(f"{prefix}_{name}")
            if raw is None or raw == "":
                return default
            return cast(raw)

        fields = dict(
            max_attempts=num("ATTEMPTS", int, cls.max_attempts),
            base_delay=num("BASE_DELAY", float, cls.base_delay),
            max_delay=num("MAX_DELAY", float, cls.max_delay),
            deadline=num("DEADLINE", float, cls.deadline),
            attempt_timeout=num("ATTEMPT_TIMEOUT", float,
                                cls.attempt_timeout),
        )
        fields.update(overrides)
        return cls(**fields)

    def delays(self) -> Iterator[float]:
        """The deterministic jittered backoff schedule (one delay per
        retry, i.e. ``max_attempts - 1`` values)."""
        rng = random.Random(self.seed)
        for k in range(max(0, self.max_attempts - 1)):
            raw = min(self.max_delay, self.base_delay * self.multiplier ** k)
            yield raw * (1.0 + self.jitter * (2.0 * rng.random() - 1.0))


def _run_with_timeout(fn: Callable[[], Any], timeout: float) -> Any:
    """Run ``fn`` on a daemon thread, abandoning it past ``timeout``."""
    out: queue.Queue = queue.Queue(maxsize=1)

    def target() -> None:
        try:
            out.put((True, fn()))
        except BaseException as e:  # delivered to the caller below
            out.put((False, e))

    t = threading.Thread(target=target, name="progen-retry-attempt",
                         daemon=True)
    t.start()
    try:
        ok, value = out.get(timeout=timeout)
    except queue.Empty:
        raise AttemptTimeout(
            f"attempt exceeded {timeout:.1f}s (worker thread abandoned)"
        ) from None
    if ok:
        return value
    raise value


def retry_call(
    fn: Callable[..., Any],
    *args: Any,
    policy: RetryPolicy | None = None,
    label: str | None = None,
    on_retry: Callable[[int, BaseException, float], None] | None = None,
    **kwargs: Any,
) -> Any:
    """Call ``fn(*args, **kwargs)`` under ``policy``.

    Retries only exceptions the policy's classifier deems transient;
    fatal exceptions propagate immediately.  Exhaustion raises
    :class:`RetryError` chained to the last failure.  ``on_retry(attempt,
    exc, delay)`` fires before each backoff sleep (default: print once
    per loop from a single process-wide seam, see ``_announce``).
    """
    policy = policy or RetryPolicy()
    name = label or getattr(fn, "__name__", "call")
    start = time.monotonic()
    delays = policy.delays()
    last: BaseException | None = None
    for attempt in range(1, max(1, policy.max_attempts) + 1):
        try:
            if policy.attempt_timeout is not None:
                return _run_with_timeout(
                    lambda: fn(*args, **kwargs), policy.attempt_timeout)
            return fn(*args, **kwargs)
        except BaseException as e:
            last = e
            if not policy.classifier(e):
                raise
            elapsed = time.monotonic() - start
            delay = next(delays, None)
            if delay is None or (
                policy.deadline is not None
                and elapsed + delay > policy.deadline
            ):
                break
            (on_retry or _announce)(attempt, e, delay)
            time.sleep(delay)
    elapsed = time.monotonic() - start
    raise RetryError(
        f"{name}: gave up after {attempt} attempt(s) in {elapsed:.1f}s: "
        f"{last!r}",
        attempts=attempt,
        elapsed=elapsed,
    ) from last


def _announce(attempt: int, exc: BaseException, delay: float) -> None:
    print(f"transient failure (attempt {attempt}): {exc!r}; "
          f"retrying in {delay:.2f}s", flush=True)


def retriable(policy: RetryPolicy | None = None, label: str | None = None):
    """Decorator form of :func:`retry_call`."""

    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return retry_call(fn, *args, policy=policy,
                              label=label or fn.__name__, **kwargs)

        return wrapper

    return deco
