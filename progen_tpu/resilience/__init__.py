"""Resilience layer: retry/backoff, fault injection, stall watchdog.

At pod scale preemptions, flaky storage and stuck collectives are the
steady state; this package is the one place the stack's answers to them
live.  See ``docs/RESILIENCE.md`` for the operator view.
"""

from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import (
    AttemptTimeout,
    RetryError,
    RetryPolicy,
    default_classifier,
    retriable,
    retry_call,
)
from progen_tpu.resilience.supervise import StageEvent, StageSupervisor
from progen_tpu.resilience.watchdog import (
    WATCHDOG_EXIT_CODE,
    FlightRecorder,
    Watchdog,
    dump_all_stacks,
)

__all__ = [
    "AttemptTimeout",
    "FlightRecorder",
    "RetryError",
    "RetryPolicy",
    "StageEvent",
    "StageSupervisor",
    "WATCHDOG_EXIT_CODE",
    "Watchdog",
    "default_classifier",
    "dump_all_stacks",
    "faults",
    "retriable",
    "retry_call",
]
