"""Deterministic fault injection at named points in the training stack.

Round 5's driver artifacts died to a transient TPU-tunnel outage that no
test had ever simulated (VERDICT.md): the resilience code paths —
checkpoint retry, data-stream reopen, preemption save, watchdog — were
exactly the ones nothing exercised.  This harness makes faults a test
input: production code declares **injection points** (``inject("ckpt.save")``)
that are zero-cost no-ops until a **fault plan** arms them, and the plan
is fully deterministic (counted hits + seeded RNG), so a fault test
reproduces bit-for-bit.

Plan syntax (env ``PROGEN_FAULTS``, ``train.py --inject-faults``, or
:func:`configure`): semicolon-separated entries ::

    <point>:<kind>[:opt=val[,opt=val...]]

kinds
    ``io_error``     raise a transient ``ConnectionResetError``
    ``unavailable``  raise ``RuntimeError('... UNAVAILABLE ...')`` — the
                     text shape of a dead backend/tunnel/gRPC peer
    ``fatal``        raise a non-transient ``ValueError`` (must NOT be
                     retried — tests pin the classifier with it)
    ``slow``         sleep ``delay`` seconds (default 1.0), then proceed
    ``hang``         sleep ``delay`` seconds (default 3600) — a stuck
                     step/collective for watchdog tests
    ``preempt``      send ``SIGTERM`` to this process — the real shape
                     of a TPU-VM preemption notice

options
    ``times=N``  fire on the first N hits of the point (default 1)
    ``at=K``     fire only on the K-th hit (1-based; overrides times)
    ``delay=S``  sleep length for slow/hang
    ``p=P``      fire with probability P per hit, drawn from a per-point
                 RNG seeded with ``seed ^ crc(point)`` — deterministic
                 across runs, independent across points

Example: ``ckpt.save:io_error:times=2;train.step:preempt:at=3``.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import threading
import time
import zlib


class InjectedFault(Exception):
    """Marker mixin so tests can assert a failure was injected."""


class InjectedIOError(InjectedFault, ConnectionResetError):
    pass


class InjectedUnavailable(InjectedFault, RuntimeError):
    pass


class InjectedFatal(InjectedFault, ValueError):
    pass


@dataclasses.dataclass
class _Rule:
    point: str
    kind: str
    times: int = 1
    at: int | None = None
    delay: float | None = None
    p: float | None = None
    fired: int = 0

    def should_fire(self, hit: int, rng: random.Random) -> bool:
        if self.p is not None:
            # the draw must happen on EVERY hit so the sequence of
            # outcomes is a pure function of (seed, point, hit index)
            if rng.random() >= self.p:
                return False
        if self.at is not None:
            return hit == self.at
        return self.fired < self.times


def parse_plan(spec: str) -> list[_Rule]:
    rules: list[_Rule] = []
    for entry in filter(None, (e.strip() for e in spec.split(";"))):
        parts = entry.split(":")
        if len(parts) < 2:
            raise ValueError(
                f"fault entry {entry!r}: want <point>:<kind>[:opt=val,...]")
        point, kind = parts[0], parts[1]
        if kind not in _KINDS:
            raise ValueError(
                f"fault entry {entry!r}: unknown kind {kind!r} "
                f"(have {sorted(_KINDS)})")
        rule = _Rule(point=point, kind=kind)
        for opt in filter(None, ",".join(parts[2:]).split(",")):
            key, _, val = opt.partition("=")
            if key == "times":
                rule.times = int(val)
            elif key == "at":
                rule.at = int(val)
            elif key == "delay":
                rule.delay = float(val)
            elif key == "p":
                rule.p = float(val)
            else:
                raise ValueError(f"fault entry {entry!r}: unknown option "
                                 f"{key!r} (times/at/delay/p)")
        rules.append(rule)
    return rules


class FaultInjector:
    """A parsed fault plan plus per-point hit counters (thread-safe:
    injection points fire from data/checkpoint worker threads too)."""

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec
        self.seed = seed
        self._rules = parse_plan(spec)
        self._hits: dict[str, int] = {}
        self._rngs: dict[str, random.Random] = {}
        self._lock = threading.Lock()
        self.log: list[tuple[str, str, int]] = []  # (point, kind, hit)

    def active(self) -> bool:
        return bool(self._rules)

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def fired(self, point: str | None = None) -> int:
        with self._lock:
            return len([e for e in self.log
                        if point is None or e[0] == point])

    def inject(self, point: str) -> None:
        """Count a hit of ``point``; execute any armed fault."""
        to_fire: list[tuple[_Rule, int]] = []
        with self._lock:
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            for rule in self._rules:
                if rule.point != point:
                    continue
                rng = self._rngs.get(point)
                if rng is None:
                    rng = self._rngs[point] = random.Random(
                        self.seed ^ zlib.crc32(point.encode()))
                if rule.should_fire(hit, rng):
                    rule.fired += 1
                    self.log.append((point, rule.kind, hit))
                    to_fire.append((rule, hit))
        for rule, hit in to_fire:
            _KINDS[rule.kind](rule, point, hit)


def _k_io_error(rule: _Rule, point: str, hit: int) -> None:
    raise InjectedIOError(
        f"injected transient I/O error at {point} (hit {hit})")


def _k_unavailable(rule: _Rule, point: str, hit: int) -> None:
    raise InjectedUnavailable(
        f"injected failure at {point} (hit {hit}): backend UNAVAILABLE")


def _k_fatal(rule: _Rule, point: str, hit: int) -> None:
    raise InjectedFatal(f"injected fatal error at {point} (hit {hit})")


def _k_slow(rule: _Rule, point: str, hit: int) -> None:
    time.sleep(rule.delay if rule.delay is not None else 1.0)


def _k_hang(rule: _Rule, point: str, hit: int) -> None:
    time.sleep(rule.delay if rule.delay is not None else 3600.0)


def _k_preempt(rule: _Rule, point: str, hit: int) -> None:
    os.kill(os.getpid(), signal.SIGTERM)


_KINDS = {
    "io_error": _k_io_error,
    "unavailable": _k_unavailable,
    "fatal": _k_fatal,
    "slow": _k_slow,
    "hang": _k_hang,
    "preempt": _k_preempt,
}


# ---------------------------------------------------------------------------
# process-wide injector (what production injection points consult)

_injector: FaultInjector | None = None
_env_checked = False


def configure(spec: str, seed: int = 0) -> FaultInjector:
    """Arm the process-wide plan (``spec=''`` disarms)."""
    global _injector, _env_checked
    _env_checked = True
    _injector = FaultInjector(spec, seed) if spec else None
    return _injector or FaultInjector("")


def reset() -> None:
    """Disarm and forget any env-derived plan (tests)."""
    global _injector, _env_checked
    _injector = None
    _env_checked = False


def get() -> FaultInjector | None:
    """The active injector (lazily armed from ``PROGEN_FAULTS`` once)."""
    global _injector, _env_checked
    if not _env_checked:
        _env_checked = True
        spec = os.environ.get("PROGEN_FAULTS", "")
        if spec:
            _injector = FaultInjector(
                spec, int(os.environ.get("PROGEN_FAULTS_SEED", "0")))
    return _injector


def inject(point: str) -> None:
    """Production-side injection point: free when no plan is armed."""
    inj = get()
    if inj is not None:
        inj.inject(point)
