from progen_tpu.data.tokenizer import (
    OFFSET,
    PAD_ID,
    VOCAB_SIZE,
    decode_token,
    decode_tokens,
    encode_token,
    encode_tokens,
)
from progen_tpu.data.tfrecord import (
    collate,
    count_sequences,
    iterator_from_tfrecords_folder,
    list_shards,
    parse_shard_filename,
    shard_filename,
    write_tfrecord,
)

__all__ = [
    "OFFSET",
    "PAD_ID",
    "VOCAB_SIZE",
    "decode_token",
    "decode_tokens",
    "encode_token",
    "encode_tokens",
    "collate",
    "count_sequences",
    "iterator_from_tfrecords_folder",
    "list_shards",
    "parse_shard_filename",
    "shard_filename",
    "write_tfrecord",
]
