"""FASTA -> tfrecords data preparation.

Behavioral contract (reference ``/root/reference/generate_data.py``):

* stream a (Uniref50-style) FASTA, filter records with sequence length
  ``<= max_seq_len``, take the first ``num_samples`` (``:94-99``);
* per record emit 1-2 training strings (``:45-74``): always ``"# SEQ"``;
  additionally, when a ``Tax=`` annotation parses from the description
  (regex ``Tax=([a-zA-Z\\s]*)\\s[a-zA-Z\\=]``, ``:37``), emit
  ``"[tax=X] # SEQ"`` with the (annotation, sequence) pair order inverted
  with probability ``prob_invert_seq_annotation`` (``:63-64``) — the ``#``
  separator doubles as the sampling-prime convention;
* shuffle, split off ``fraction_valid_data`` for validation, shard into
  files of ``num_sequences_per_file``, write GZIP tfrecords named by the
  shard filename protocol, optionally wipe-and-upload GCS (``:107-153``).

Structural changes (SURVEY.md §7.7, all conscious):

* the reference's Prefect 2-task DAG and pyfaidx index are replaced by a
  plain streaming parser + a ``multiprocessing`` pool (the reference README
  itself lists "utilize all cores" as a TODO, ``README.md:109``);
* no ``./.tmp`` staging of one-gzip-file-per-sequence (the reference writes
  N tiny files to disk and reads them back, ``:76-79,145-149``) — strings
  go straight to the shard writer;
* randomness is seeded and reproducible (the reference uses the global
  ``random``/``np.random`` state unseeded).
"""

from __future__ import annotations

import gzip
import math
import re
from pathlib import Path
from typing import Iterable, Iterator

import numpy as np

from progen_tpu.data.tfrecord import shard_filename, write_tfrecord

TAX_RE = re.compile(r"Tax=([a-zA-Z\s]*)\s[a-zA-Z\=]")
# GO terms as they appear in UniProt/UniRef-derived descriptions: the
# canonical 7-digit accession ``GO:0016021``, one or many (e.g. a custom
# export's ``GO=GO:0016021; GO:0005886`` field).  The reference extracts
# only Tax= (/root/reference/generate_data.py:36-43); GO conditioning is
# the BASELINE.json ProGen-large capability ("+ GO annotation
# conditioning") the same mechanism extends to.
GO_RE = re.compile(r"(?<!\d)GO:(\d{7})(?!\d)")  # digit-bounded: GO:00160215 is NOT a GO term


def _extract_tax(description: str) -> str | None:
    m = TAX_RE.findall(description)
    return m[0] if m else None


def _extract_go(description: str) -> str | None:
    terms = GO_RE.findall(description)
    if not terms:
        return None
    # deduplicate, keep first-seen order: "GO:0016021,GO:0005886"
    seen = dict.fromkeys(terms)
    return ",".join(f"GO:{t}" for t in seen)


# config-driven extractor set: each key becomes a ``[key=value]`` prefix
# token when its extractor finds a value in the FASTA description
EXTRACTORS = {"tax": _extract_tax, "go": _extract_go}


def parse_fasta(path: str) -> Iterator[tuple[str, str]]:
    """Stream ``(description, sequence)`` pairs; transparently handles
    ``.gz``.  Sequences are upper-cased (the reference's
    ``sequence_always_upper=True``)."""
    opener = gzip.open if str(path).endswith(".gz") else open
    with opener(path, "rt") as f:
        desc = None
        chunks: list[str] = []
        for line in f:
            line = line.strip()
            if not line:
                continue
            if line.startswith(">"):
                if desc is not None:
                    yield desc, "".join(chunks).upper()
                desc = line[1:]
                chunks = []
            else:
                chunks.append(line)
        if desc is not None:
            yield desc, "".join(chunks).upper()


def annotations_from_description(
    description: str, annotations: tuple[str, ...] = ("tax",)
) -> dict[str, str]:
    """Extract the requested annotation keys from a FASTA description.

    ``annotations`` selects from :data:`EXTRACTORS` (``"tax"``, ``"go"``);
    the default matches the reference's Tax-only behavior
    (``/root/reference/generate_data.py:36-43``)."""
    out = {}
    for key in annotations:
        value = EXTRACTORS[key](description)
        if value is not None:
            out[key] = value
    return out


def sequence_strings(
    description: str,
    seq: str,
    rng: np.random.Generator,
    prob_invert: float = 0.5,
    sort_annotations: bool = True,
    annotation_keys: tuple[str, ...] = ("tax",),
) -> list[bytes]:
    """1-2 encoded training strings per FASTA record (reference ``:45-74``).

    With multiple annotation keys the prefix is multi-token, e.g.
    ``"[go=GO:0016021] [tax=Escherichia coli] # SEQ"`` — same sort/invert
    semantics as the reference's single-key case (sorted keys unless
    ``sort_annotations=False`` shuffles them; the whole annotation block
    swaps sides with the sequence with probability ``prob_invert``)."""
    out: list[bytes] = []
    annotations = annotations_from_description(description, annotation_keys)
    if annotations:
        keys = sorted(annotations) if sort_annotations else list(annotations)
        if not sort_annotations:
            rng.shuffle(keys)
        annot_str = " ".join(f"[{k}={annotations[k]}]" for k in keys)
        pair = (annot_str, seq)
        if rng.random() <= prob_invert:
            pair = tuple(reversed(pair))
        out.append(" # ".join(pair).encode("utf-8"))
    out.append(f"# {seq}".encode("utf-8"))
    return out


def _format_record(args: tuple) -> list[bytes]:
    """Pool worker: format one FASTA record into its 1-2 training strings.

    The rng is derived from ``(seed, record_index)`` so the output is
    deterministic and IDENTICAL regardless of worker count or scheduling
    (the serial path uses the same derivation).
    """
    idx, desc, seq, prob_invert, sort_annotations, annotation_keys, seed = args
    rng = np.random.default_rng([seed, idx])
    return sequence_strings(desc, seq, rng, prob_invert, sort_annotations,
                            annotation_keys)


def _filtered_records(
    read_from: str, max_seq_len: int, num_samples: int | None
) -> Iterator[tuple[int, str, str]]:
    taken = 0
    for desc, seq in parse_fasta(read_from):
        # empty sequences would collate to an all-zero row, which the eval
        # step's real-row mask (train/step.py) treats as batch padding and
        # silently drops — exclude them here so that heuristic stays sound
        # (every emitted string then contains at least "# " + content)
        if not seq or len(seq) > max_seq_len:
            continue
        yield taken, desc, seq
        taken += 1
        if num_samples is not None and taken >= num_samples:
            return


def generate_tfrecords(
    read_from: str,
    write_to: str,
    *,
    max_seq_len: int = 1024,
    num_samples: int | None = None,
    fraction_valid_data: float = 0.025,
    num_sequences_per_file: int = 1000,
    prob_invert_seq_annotation: float = 0.5,
    sort_annotations: bool = True,
    annotations: tuple[str, ...] = ("tax",),
    seed: int = 0,
    num_workers: int | None = None,
) -> dict[str, int]:
    """Run the full prep: returns ``{"train": n, "valid": m}`` counts.

    ``annotations``: which :data:`EXTRACTORS` keys to mine from each FASTA
    description (default Tax-only, the reference behavior; add ``"go"``
    for GO-term conditioning — BASELINE.json's ProGen-large capability).

    ``num_workers``: size of the ``multiprocessing`` pool used for record
    formatting and shard compression (the reference README's "utilize all
    cores" TODO, ``README.md:109``).  ``None`` -> ``os.cpu_count()``; ``0``
    or ``1`` -> serial.  Output bytes are identical for every worker count:
    per-record randomness is keyed by ``(seed, record_index)``, not by a
    shared stream.

    Workers use the ``spawn`` start method, so the caller's ``__main__``
    must be importable (a real script/module with an ``if __name__ ==
    '__main__'`` guard — true of ``generate_data.py`` and pytest; a
    stdin-piped ``python -`` session must pass ``num_workers<=1``).
    """
    import os

    if num_workers is None:
        num_workers = os.cpu_count() or 1

    # Spool encoded strings to one on-disk file, keeping only (offset, len)
    # per string in RAM — full-corpus Uniref50 emits tens of GB of strings,
    # which must not be held in memory (the reference staged one tmp gzip
    # file PER STRING, generate_data.py:76-79; one spool file is kinder to
    # the filesystem).
    import tempfile

    offsets: list[int] = []
    lengths: list[int] = []
    with tempfile.TemporaryFile() as spool:
        unknown = set(annotations) - set(EXTRACTORS)
        if unknown:
            raise ValueError(
                f"unknown annotation keys {sorted(unknown)}; "
                f"available: {sorted(EXTRACTORS)}")
        args = (
            (idx, desc, seq, prob_invert_seq_annotation, sort_annotations,
             tuple(annotations), seed)
            for idx, desc, seq in _filtered_records(
                read_from, max_seq_len, num_samples)
        )
        if num_workers > 1:
            # spawn (not fork): the parent may hold live JAX/TF runtimes
            # whose locks do not survive fork; workers only import
            # numpy + this module, so spawn startup is cheap.
            import multiprocessing as mp

            ctx = mp.get_context("spawn")
            with ctx.Pool(num_workers) as pool:
                string_lists = pool.imap(_format_record, args, chunksize=256)
                pos = _spool_strings(spool, string_lists, offsets, lengths)
        else:
            pos = _spool_strings(
                spool, map(_format_record, args), offsets, lengths)

        def read_string(i: int) -> bytes:
            spool.seek(offsets[i])
            return spool.read(lengths[i])

        n_strings = len(offsets)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(n_strings)
        num_valid = math.ceil(fraction_valid_data * n_strings)
        valid_idx, train_idx = perm[:num_valid], perm[num_valid:]
        return _write_splits(
            write_to, read_string, train_idx, valid_idx,
            num_sequences_per_file, num_workers,
        )


def _spool_strings(spool, string_lists, offsets, lengths) -> int:
    pos = 0
    for strings in string_lists:
        for s in strings:
            spool.write(s)
            offsets.append(pos)
            lengths.append(len(s))
            pos += len(s)
    return pos


def _write_shard(args: tuple) -> None:
    """Pool worker: gzip-compress and write one complete shard file."""
    path, payloads = args
    write_tfrecord(path, payloads)


def _write_splits(write_to, read_string, train_idx, valid_idx,
                  num_sequences_per_file, num_workers=1):
    is_gcs = write_to.startswith("gs://")
    if is_gcs:
        from etils import epath

        out_dir = epath.Path(write_to)
        if out_dir.exists():
            out_dir.rmtree()
        out_dir.mkdir(parents=True, exist_ok=True)
        local_stage = Path("/tmp/progen_tfrecords")
        local_stage.mkdir(parents=True, exist_ok=True)
    else:
        out_dir = Path(write_to)
        if out_dir.exists():
            import shutil

            shutil.rmtree(out_dir)
        out_dir.mkdir(parents=True, exist_ok=True)

    counts = {}
    staged_uploads: list[tuple] = []

    def shard_tasks():
        for split, idx in (("train", train_idx), ("valid", valid_idx)):
            counts[split] = len(idx)
            if len(idx) == 0:
                continue
            num_shards = math.ceil(len(idx) / num_sequences_per_file)
            for file_index, shard_idx in enumerate(
                np.array_split(idx, num_shards)
            ):
                name = shard_filename(file_index, len(shard_idx), split)
                payloads = [read_string(int(i)) for i in shard_idx]
                if is_gcs:
                    staged = local_stage / name
                    staged_uploads.append((staged, out_dir / name))
                    yield str(staged), payloads
                else:
                    yield str(out_dir / name), payloads

    if num_workers > 1:
        import multiprocessing as mp

        ctx = mp.get_context("spawn")
        with ctx.Pool(num_workers) as pool:
            # imap over the lazy generator: at most ~num_workers shards'
            # payloads are pickled/in flight at once, never the full corpus
            for _ in pool.imap(_write_shard, shard_tasks(), chunksize=1):
                pass
    else:
        for task in shard_tasks():
            _write_shard(task)

    for staged, dest in staged_uploads:
        dest.write_bytes(staged.read_bytes())
    return counts
