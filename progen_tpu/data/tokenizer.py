"""Byte-level tokenizer.

Contract (reference ``/root/reference/progen_transformer/data.py:76-88``):
token id = ``ord(ch) + 1``; id 0 is reserved and triple-duty as
pad / BOS / EOS; decoding subtracts the offset and drops ids that map
below zero (i.e. the 0s).  Vocabulary of 256 covers shifted bytes 0-254.
"""

from __future__ import annotations

import numpy as np

PAD_ID = 0
OFFSET = 1
VOCAB_SIZE = 256


def encode_token(ch: str) -> int:
    return ord(ch) + OFFSET


def encode_tokens(s: str) -> list[int]:
    return [encode_token(ch) for ch in s]


def decode_token(tok: int, offset: int = OFFSET) -> str:
    t = int(tok) - offset
    if t < 0:
        return ""
    return chr(t)


def decode_tokens(tokens, offset: int = OFFSET) -> str:
    tokens = np.asarray(tokens).astype(np.int32)
    return "".join(decode_token(t, offset) for t in tokens)
