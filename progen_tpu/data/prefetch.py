"""Double-buffered host->device input feed.

The reference feeds the accelerator synchronously — ``next(train_dataset)``
then the jitted call, every micro-step (``/root/reference/train.py:191-193``)
— so the device idles while the host runs tf.data + the NumPy collate and
the PCIe/tunnel transfer.  Measured on this framework's 500-step v5e run,
that serialization costs ~10% of steady-state throughput
(``runs/90b685bbc4d5``: 76.7k tokens/sec fed synchronously vs 85.3k for
``bench.py`` on device-resident batches).

:class:`DevicePrefetcher` moves the feed off the critical path: a daemon
thread pulls host batches and STARTS their device transfer (JAX transfers
are async — the returned array is a future) while the current step
executes, keeping ``depth`` batches in flight.  The training loop's
``next()`` then usually returns a batch whose transfer already completed.

Thread-safety: the worker calls only ``next(iterator)`` and ``to_device``
(``jax.device_put``/``make_array_from_process_local_data``), both safe off
the main thread; all jitted-step dispatch stays on the caller's thread.
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterator

import numpy as np


class _End:
    pass


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


class DevicePrefetcher:
    """Wrap ``iterator`` so device transfers overlap step execution.

    ``to_device``: host batch -> device array (its transfer may be async).
    ``depth``: batches buffered ahead (2 = classic double buffering; more
    only helps when the host feed is bursty).
    """

    def __init__(
        self,
        iterator: Iterator[Any],
        to_device: Callable[[Any], Any],
        depth: int = 2,
    ):
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._iterator = iterator
        self._to_device = to_device
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name="progen-prefetch", daemon=True
        )
        self._thread.start()

    def _worker(self) -> None:
        try:
            while not self._stop.is_set():
                try:
                    batch = next(self._iterator)
                except StopIteration:
                    self._put(_End())
                    return
                self._put(self._to_device(batch))
        except BaseException as e:  # surfaced on the consumer thread
            self._put(_Raised(e))

    def _put(self, item) -> None:
        # bounded put that gives up when the consumer is shutting down
        # (otherwise a full queue would wedge the daemon thread forever)
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def __iter__(self):
        return self

    def __next__(self):
        item = self._q.get()
        if isinstance(item, _End):
            raise StopIteration
        if isinstance(item, _Raised):
            raise item.exc
        return item

    def close(self) -> None:
        """Stop the worker and drop buffered batches (idempotent).

        The wrapped iterator is OWNED by the prefetcher from construction
        on: the worker may be blocked inside ``next(iterator)`` (e.g.
        tf.data waiting on a slow source), in which case it survives the
        bounded join as an orphaned daemon and may still consume one more
        item when the source unblocks.  Never hand the underlying iterator
        to another consumer after wrapping it."""
        self._stop.set()
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        if self._thread.is_alive():
            import warnings

            warnings.warn(
                "DevicePrefetcher worker did not exit within 5s (blocked in "
                "next() on the wrapped iterator?); it remains attached to "
                "the iterator and may consume one more batch",
                RuntimeWarning,
                stacklevel=2,
            )


class SuperbatchStager:
    """Stage ``(k, accum, B, L)`` superbatches for the fused multi-step
    train loop (``TrainFunctions.train_multi_step``).

    A background thread keeps up to ``depth`` supersteps' worth of host
    micro-batches pulled ahead (reusing :class:`DevicePrefetcher` with an
    identity transform as the host-side buffer); :meth:`get` stacks the
    next ``k * accum`` of them into one contiguous array and hands it to
    ``to_device`` — a JAX transfer is asynchronous, so the copy streams to
    HBM while the PREVIOUS superstep is still executing, and the returned
    array is fresh every call (safe for the step's buffer donation).

    ``k`` may vary per call (the trainer shrinks the final superstep
    before a hook boundary) up to the ``k_max`` the stager was sized for.
    """

    def __init__(
        self,
        iterator: Iterator[Any],
        to_device: Callable[[Any], Any],
        accum: int,
        k_max: int,
        depth: int = 2,
    ):
        if accum < 1:
            raise ValueError(f"accum must be >= 1, got {accum}")
        if k_max < 1:
            raise ValueError(f"k_max must be >= 1, got {k_max}")
        self._accum = accum
        self._k_max = k_max
        self._to_device = to_device
        self._host = DevicePrefetcher(
            iterator,
            lambda batch: batch,  # host-side buffering only
            depth=max(1, depth) * k_max * accum,
        )

    def get(self, k: int):
        """The next ``k`` optimizer steps' data as one ``(k, accum, B, L)``
        device array (its transfer may still be in flight — JAX arrays are
        futures).  Raises ``StopIteration`` when the wrapped iterator
        cannot supply a full superbatch (the trainer feeds a looping
        stream, so this only surfaces on finite test iterators)."""
        if not 1 <= k <= self._k_max:
            raise ValueError(f"k must be in [1, {self._k_max}], got {k}")
        need = k * self._accum
        micro = [next(self._host) for _ in range(need)]
        stacked = np.stack(micro).reshape(
            (k, self._accum) + np.shape(micro[0]))
        return self._to_device(stacked)

    def close(self) -> None:
        """Stop the host prefetch worker and drop buffered batches."""
        self._host.close()
