"""TFRecord protein-sequence pipeline — SPMD-aware reader + writer.

Format contract (reference ``/root/reference/progen_transformer/data.py``):

* records are GZIP TFRecords with ONE bytes feature ``'seq'`` holding the
  raw UTF-8 sequence string (``data.py:9-21``);
* filename protocol ``{file_index}.{num_sequences}.{train|valid}.tfrecord.gz``;
  the reader derives corpus size by summing the ``num_sequences`` field
  (``data.py:46``);
* collation (``data.py:30-35,64-70``): bytes -> ints, truncate to
  ``seq_len``, +1 tokenizer offset applied AT COLLATE TIME (tfrecords store
  raw bytes), right-pad with 0, prepend a zero BOS column ->
  ``(B, seq_len + 1)``;
* resume-by-skip: ``skip`` consumed records before batching
  (``data.py:56``) — correct across batch-size changes.

TPU/SPMD additions (no counterpart in the single-process reference):

* ``process_count``/``process_index`` shard the RECORD stream across hosts
  (record-level round-robin, so per-host skip arithmetic stays exact for
  ANY global cursor: host h skips ``ceil((skip - h) / P)`` of its records —
  every host must be fed the same global skip);
* batches come out int32 (TPU-native index dtype) rather than uint16.

TensorFlow is imported lazily and used only for file IO (tf.data never
touches the accelerator; ``tf.config.set_visible_devices([], 'GPU'|'TPU')``
guards against it grabbing the chip).
"""

from __future__ import annotations

import functools
from pathlib import Path
from typing import Iterator

import numpy as np

from progen_tpu.data.tokenizer import OFFSET
from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import RetryPolicy, retry_call


@functools.lru_cache(maxsize=1)
def _retry_policy() -> RetryPolicy:
    """Stream-open retry: a GCS glob or the first record fetch hitting a
    503 must not kill a run (env-tunable: PROGEN_DATA_RETRY_*)."""
    return RetryPolicy.from_env("PROGEN_DATA_RETRY", base_delay=0.5,
                                deadline=60.0)


@functools.lru_cache(maxsize=1)
def _tf():
    import tensorflow as tf

    # tf.data must never claim the accelerator.
    for kind in ("GPU", "TPU"):
        try:
            tf.config.set_visible_devices([], kind)
        except Exception:
            pass
    return tf


# ---------------------------------------------------------------------------
# writing


def shard_filename(file_index: int, num_sequences: int, data_type: str) -> str:
    """The reference's filename protocol (generate_data.py:142)."""
    return f"{file_index}.{num_sequences}.{data_type}.tfrecord.gz"


def parse_shard_filename(name: str) -> int:
    """Number of sequences encoded in a shard filename (data.py:46)."""
    return int(name.split(".")[-4])


def _varint(n: int) -> bytes:
    """Protobuf base-128 varint."""
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def encode_example(payload: bytes) -> bytes:
    """Hand-encoded ``tf.train.Example`` proto wire bytes for
    ``features { feature { key: "seq" value { bytes_list { value: [payload]
    } } } }`` — the reference's record schema
    (``/root/reference/progen_transformer/data.py:9-14``).

    Encoding by hand keeps the writer pure Python: data-prep worker
    processes never import TensorFlow (a multi-second import each), and the
    bytes are verified against ``tf.io.parse_single_example`` by the
    round-trip tests.  Wire format: every level is field 1
    (length-delimited, tag ``0x0a``) except the map entry's value, field 2
    (tag ``0x12``).
    """
    bytes_list = b"\x0a" + _varint(len(payload)) + payload
    feature = b"\x0a" + _varint(len(bytes_list)) + bytes_list
    entry = b"\x0a\x03seq" + b"\x12" + _varint(len(feature)) + feature
    features = b"\x0a" + _varint(len(entry)) + entry
    return b"\x0a" + _varint(len(features)) + features


def _masked_crc32c(data: bytes) -> int:
    """TFRecord framing checksum: crc32c rotated right 15 and offset."""
    import google_crc32c

    crc = google_crc32c.value(data)
    return ((crc >> 15 | crc << 17) + 0xA282EAD8) & 0xFFFFFFFF


def write_tfrecord(path: str, payloads) -> int:
    """Write raw byte payloads as GZIP TFRecords with the 'seq' feature.

    Local paths use a pure-Python writer (proto + crc32c framing + gzip; no
    TensorFlow import, safe and fast inside multiprocessing workers);
    ``gs://`` paths go through ``tf.io.TFRecordWriter``, which speaks GCS
    natively.  Returns the number of records written.
    """
    import struct

    if str(path).startswith("gs://"):
        tf = _tf()
        options = tf.io.TFRecordOptions(compression_type="GZIP")
        n = 0
        with tf.io.TFRecordWriter(str(path), options=options) as writer:
            for payload in payloads:
                writer.write(encode_example(payload))
                n += 1
        return n

    import gzip

    n = 0
    # fileobj + mtime=0: the gzip header embeds neither filename nor
    # timestamp, so identical payloads produce byte-identical shards
    # (prep determinism is tested across worker counts); compresslevel 6
    # matches TFRecordOptions("GZIP")'s zlib default — Python's default 9
    # is ~3x slower for ~1% smaller shards
    with open(str(path), "wb") as raw, gzip.GzipFile(
        fileobj=raw, mode="wb", compresslevel=6, mtime=0
    ) as f:
        for payload in payloads:
            data = encode_example(payload)
            length = struct.pack("<Q", len(data))
            f.write(length)
            f.write(struct.pack("<I", _masked_crc32c(length)))
            f.write(data)
            f.write(struct.pack("<I", _masked_crc32c(data)))
            n += 1
    return n


# ---------------------------------------------------------------------------
# reading


def list_shards(folder: str, data_type: str = "train") -> list[str]:
    """Shard files for a split, local or ``gs://`` (sorted for determinism;
    the reference relies on glob order, which is unstable — sorting is a
    conscious fix)."""
    def _glob() -> list[str]:
        faults.inject("data.glob")
        if folder.startswith("gs://"):
            tf = _tf()
            return tf.io.gfile.glob(f"{folder}/*.{data_type}.tfrecord.gz")
        return [str(p)
                for p in Path(folder).glob(f"**/*.{data_type}.tfrecord.gz")]

    return sorted(retry_call(_glob, policy=_retry_policy(),
                             label="data.glob"))


def count_sequences(folder: str, data_type: str = "train") -> int:
    return sum(parse_shard_filename(n) for n in list_shards(folder, data_type))


def collate(raw_seqs: list[bytes], seq_len: int, offset: int = OFFSET) -> np.ndarray:
    """Raw byte strings -> ``(B, seq_len + 1)`` int32 with BOS column."""
    batch = np.zeros((len(raw_seqs), seq_len + 1), dtype=np.int32)
    for i, raw in enumerate(raw_seqs):
        toks = np.frombuffer(raw, dtype=np.uint8)[:seq_len].astype(np.int32) + offset
        batch[i, 1 : 1 + len(toks)] = toks
    return batch


def iterator_from_tfrecords_folder(
    folder: str,
    data_type: str = "train",
):
    """Returns ``(num_seqs, iter_fn)`` — the reference's reader factory
    signature (``data.py:37-72``) with multi-host kwargs added to
    ``iter_fn``.
    """
    filenames = list_shards(folder, data_type)
    num_seqs = sum(parse_shard_filename(n) for n in filenames)

    def iter_fn(
        seq_len: int,
        batch_size: int,
        skip: int = 0,
        loop: bool = False,
        process_count: int = 1,
        process_index: int = 0,
        shuffle_buffer: int = 0,
        seed: int = 0,
    ) -> Iterator[np.ndarray]:
        tf = _tf()
        ds = tf.data.TFRecordDataset(filenames, compression_type="GZIP")
        if process_count > 1:
            ds = ds.shard(process_count, process_index)
        if loop:
            # TPU-first ragged-batch fix: repeat the RECORD stream before
            # skip/batch, so every batch is full and statically shaped (no
            # jit retrace / sharded-batch divisibility failure at corpus
            # boundaries — batches simply straddle them), nothing is
            # dropped, and records before a resume skip reappear in later
            # passes.  The reference repeats after batching
            # (data.py:54-62), which emits a short batch every epoch AND
            # permanently loses the skipped prefix on resume.
            ds = ds.repeat()
        # Per-host skip for a GLOBAL record cursor under round-robin
        # sharding: host h owns records {h, h+P, h+2P, ...}; of the first
        # `skip` global records it owns ceil((skip - h) / P).  For any
        # cursor value — aligned or not (an epoch-boundary wrap can leave
        # next_seq_index % P != 0) — the union of the hosts' next batches
        # is exactly records [skip, skip + P*batch), so resume stays
        # record-exact.
        if process_count > 1:
            per_host_skip = max(
                0, -(-(skip - process_index) // process_count)
            )
        else:
            per_host_skip = skip
        if not shuffle_buffer:
            # unshuffled: skip raw records BEFORE parsing (cheaper)
            ds = ds.skip(per_host_skip)
        ds = ds.map(
            lambda rec: tf.io.parse_single_example(
                rec, {"seq": tf.io.FixedLenFeature([], tf.string)}
            )["seq"],
            num_parallel_calls=tf.data.AUTOTUNE,
        )
        if shuffle_buffer:
            # Under loop=True the repeated stream is ONE infinite iteration,
            # so reshuffle_each_iteration never fires: mixing across epoch
            # boundaries comes from the sliding buffer itself (intentional);
            # the flag only matters for finite re-iterated datasets.
            ds = ds.shuffle(shuffle_buffer, seed=seed, reshuffle_each_iteration=True)
            # Deterministic shuffled resume: the seeded shuffle is a pure
            # function of its input stream, so replaying it from the start
            # and skipping the already-consumed OUTPUTS continues the
            # uninterrupted run's record order exactly.  (Skipping before
            # the shuffle instead would feed the buffer a shifted stream
            # and re-order records near the cursor.)  Same O(cursor) resume
            # cost as the raw skip — tf.data decompresses skipped records
            # either way.
            ds = ds.skip(per_host_skip)
        # an infinite stream never has a remainder; finite (loop=False)
        # streams keep the reference's trailing short batch
        ds = ds.batch(batch_size, drop_remainder=loop)
        ds = ds.prefetch(tf.data.AUTOTUNE)

        # tf.data opens the shard files lazily at the FIRST next(); retry
        # the open+first-fetch as one unit (a fresh numpy iterator per
        # attempt — no records have been consumed yet, so re-opening is
        # exact).  Mid-stream failures are NOT retried here: the stream
        # position would be lost, and the trainer's resume loop
        # (re-restore + cursor skip) is the correct recovery at that
        # level.
        def _open():
            faults.inject("data.open")
            np_it = ds.as_numpy_iterator()
            try:
                return np_it, next(np_it)
            except StopIteration:
                return np_it, None

        np_it, first = retry_call(_open, policy=_retry_policy(),
                                  label="data.open")
        if first is None:
            return
        yield collate(list(first), seq_len)
        for raw in np_it:
            yield collate(list(raw), seq_len)

    return num_seqs, iter_fn
