"""QoS scheduling queue: priority classes, weighted-fair tenancy, EDF.

:class:`QoSQueue` replaces the serving engine's plain FIFO admission
deque (``docs/SERVING.md`` §10).  It is deque-compatible on the surface
the engine actually uses — ``append`` / ``appendleft`` / ``popleft`` /
``remove`` / ``len`` / iteration / ``[0]`` peek — but orders requests by
a three-level policy instead of arrival alone:

1. **Priority class** (``Request.priority``, higher = more urgent):
   classes are served strictly in descending order.  A starved low
   class is relieved only by deadline sheds — strict priority is the
   point, and the engine's preemption path (``_maybe_preempt``) uses the
   same ordering to claim slots back from lower classes.
2. **Deficit-weighted round robin across tenants** inside a class:
   every tenant carries a configurable weight (default 1.0); each visit
   of the rotation grants ``weight`` credit and serving a request costs
   1.0, so long-run throughput shares converge to the weight ratios.
   Any tenant with a **nonzero weight is starvation-free** — its credit
   accumulates every rotation until it must be served.  Zero-weight
   tenants are background: they are served only when no positive-weight
   tenant in the class has queued work (work-conserving, never ahead).
3. **EDF within a tenant**: earliest deadline (``deadline``/``ttl``)
   first; deadline-less requests order FIFO after every deadlined one
   with the same key, via a monotone enqueue sequence number.

With one tenant, one class and no deadlines the whole policy degrades
to exact FIFO, so pre-QoS engine semantics (and tests) are unchanged.

``appendleft`` bypasses the policy entirely: it pushes onto a LIFO
*front stack* consulted before any class — the engine uses it for
restart-and-replay requeues and pool-starvation evictions, where
"re-admit exactly this work next" is the invariant that keeps replay
deterministic.  Policy re-enqueue (priority preemption) goes through
``append``, which preserves the request's original sequence number: a
preempted request resumes *ahead of same-class peers that arrived after
it*, but behind the higher class that displaced it.

Peek (``q[0]``) and ``popleft`` run the same deterministic selection,
so the engine's peek-then-pop admission loops admit exactly what they
inspected.  Everything here is host-side bookkeeping — no jax imports,
mirroring ``decode/paging.py``.
"""

from __future__ import annotations

import heapq
import math
from typing import Any

__all__ = ["QoSQueue"]


def _deadline_key(r) -> float:
    """EDF sort key: absolute deadline instant, ``inf`` when unbounded
    (mirrors ``ServingEngine._deadline_of`` — ``deadline`` wins over
    ``ttl``)."""
    if getattr(r, "deadline", None) is not None:
        return r.deadline
    ttl = getattr(r, "ttl", None)
    if ttl is not None:
        return r.submit_time + ttl
    return math.inf


class QoSQueue:
    """Priority / DWRR / EDF scheduling queue (see module docstring).

    ``weights`` maps tenant id -> relative share (non-negative floats;
    missing tenants default to 1.0).  The mapping is read live, so a
    served config change applies to the next selection.
    """

    def __init__(self, weights: dict | None = None):
        self._weights: dict[int, float] = {}
        if weights:
            for t, w in weights.items():
                w = float(w)
                if w < 0:
                    raise ValueError(
                        f"qos weight for tenant {t} must be >= 0, got {w}")
                self._weights[int(t)] = w
        self._front: list = []      # LIFO replay stack; pops before policy
        # priority class -> tenant -> heap of (deadline_key, seq, request)
        self._classes: dict[int, dict[int, list]] = {}
        self._deficit: dict[int, dict[int, float]] = {}
        self._rr_at: dict[int, int] = {}       # class -> pointer tenant
        self._rr_charged: dict[int, bool] = {}  # pointer already credited
        self._seq = 0
        self._len = 0
        self.served_by_class: dict[int, int] = {}
        self.served_by_tenant: dict[int, int] = {}

    # ------------------------------------------------------------- enqueue

    def append(self, r) -> None:
        """Policy enqueue.  A request re-appended after preemption keeps
        its original sequence number (queue seniority survives the round
        trip through a slot)."""
        if getattr(r, "_qos_home", None) != id(self):
            r._qos_seq = self._seq
            r._qos_home = id(self)
            self._seq += 1
        cls = int(getattr(r, "priority", 0))
        tenant = int(getattr(r, "tenant", 0))
        heap = self._classes.setdefault(cls, {}).setdefault(tenant, [])
        heapq.heappush(heap, (_deadline_key(r), r._qos_seq, r))
        self._len += 1

    def appendleft(self, r) -> None:
        """Front-of-queue enqueue, bypassing the policy: the next pop
        returns ``r`` regardless of class or tenant.  Reserved for
        deterministic-replay requeues (engine restart, pool-starvation
        eviction) where admission order IS the correctness contract."""
        self._front.append(r)
        self._len += 1

    # --------------------------------------------------------------- serve

    def popleft(self):
        if self._front:
            self._len -= 1
            r = self._front.pop()
            self._note_served(r)
            return r
        if not self._classes:
            raise IndexError("pop from an empty QoSQueue")
        cls = max(self._classes)
        tenant = self._select(cls, commit=True)
        heap = self._classes[cls][tenant]
        _, _, r = heapq.heappop(heap)
        if not heap:
            del self._classes[cls][tenant]
            self._deficit.get(cls, {}).pop(tenant, None)
            if not self._classes[cls]:
                del self._classes[cls]
                self._deficit.pop(cls, None)
                self._rr_at.pop(cls, None)
                self._rr_charged.pop(cls, None)
        self._len -= 1
        self._note_served(r)
        return r

    def _note_served(self, r) -> None:
        cls = int(getattr(r, "priority", 0))
        tenant = int(getattr(r, "tenant", 0))
        self.served_by_class[cls] = self.served_by_class.get(cls, 0) + 1
        self.served_by_tenant[tenant] = (
            self.served_by_tenant.get(tenant, 0) + 1)

    def _peek(self):
        if self._front:
            return self._front[-1]
        if not self._classes:
            raise IndexError("peek into an empty QoSQueue")
        cls = max(self._classes)
        tenant = self._select(cls, commit=False)
        return self._classes[cls][tenant][0][2]

    def _select(self, cls: int, commit: bool) -> int:
        """DWRR tenant selection within ``cls``.  ``commit=False`` is a
        pure peek: it simulates on overlays and mutates nothing, so peek
        and the following pop agree by construction."""
        qs = self._classes[cls]
        tenants = sorted(qs)
        if len(tenants) == 1:
            t = tenants[0]
            if commit:
                self._rr_at[cls] = t
                self._rr_charged[cls] = False
            return t
        deficit = self._deficit.setdefault(cls, {})
        weights = {t: self._weights.get(t, 1.0) for t in tenants}
        positive = [w for w in weights.values() if w > 0.0]
        cur = self._rr_at.get(cls)
        charged = self._rr_charged.get(cls, False)
        if cur not in qs:
            # the pointer's tenant drained away: resume the rotation at
            # the next tenant after it (wrapping), credit not yet granted
            later = [t for t in tenants if cur is not None and t > cur]
            cur = later[0] if later else tenants[0]
            charged = False
        i = tenants.index(cur)
        order = tenants[i:] + tenants[:i]
        if not positive:
            # every queued tenant is zero-weight background: plain RR
            if commit:
                self._rr_at[cls] = order[0]
                self._rr_charged[cls] = False
            return order[0]
        over: dict[int, float] = {}  # peek overlay over ``deficit``

        def dget(t):
            return over.get(t, deficit.get(t, 0.0))

        def dset(t, v):
            if commit:
                deficit[t] = v
            else:
                over[t] = v

        # a tenant of weight w accumulates 1.0 credit within ceil(1/w)
        # rotations, so the scan is bounded (+1 absorbs float slack)
        rounds = int(math.ceil(1.0 / min(positive))) + 1
        for k in range(rounds * len(order)):
            t = order[k % len(order)]
            w = weights[t]
            if w > 0.0:
                if not charged:
                    dset(t, dget(t) + w)
                if dget(t) >= 1.0:
                    dset(t, dget(t) - 1.0)
                    if commit:
                        self._rr_at[cls] = t
                        self._rr_charged[cls] = True
                    return t
            charged = False
        # unreachable for positive weights; serve the rotation head
        if commit:
            self._rr_at[cls] = order[0]
            self._rr_charged[cls] = False
        return order[0]

    # ----------------------------------------------------------- shed hook

    def shed_victim(self):
        """The request shed-oldest should drop: lowest priority class,
        oldest enqueue within it (None when empty).  The engine compares
        its priority against the incoming request's, so a strictly
        higher-priority queued request is never shed in favor of a lower
        one."""
        best = None
        best_key = None
        for r in self._front:
            key = (int(getattr(r, "priority", 0)),
                   getattr(r, "_qos_seq", -1))
            if best_key is None or key < best_key:
                best, best_key = r, key
        for cls, by_tenant in self._classes.items():
            for heap in by_tenant.values():
                for _, seq, r in heap:
                    key = (cls, seq)
                    if best_key is None or key < best_key:
                        best, best_key = r, key
        return best

    # ------------------------------------------------------------ plumbing

    def remove(self, r) -> None:
        for i in range(len(self._front) - 1, -1, -1):
            if self._front[i] is r:
                del self._front[i]
                self._len -= 1
                return
        cls = int(getattr(r, "priority", 0))
        tenant = int(getattr(r, "tenant", 0))
        heap = self._classes.get(cls, {}).get(tenant)
        if heap is not None:
            for i, (_, _, q) in enumerate(heap):
                if q is r:
                    heap[i] = heap[-1]
                    heap.pop()
                    heapq.heapify(heap)
                    if not heap:
                        del self._classes[cls][tenant]
                        self._deficit.get(cls, {}).pop(tenant, None)
                        if not self._classes[cls]:
                            del self._classes[cls]
                            self._deficit.pop(cls, None)
                            self._rr_at.pop(cls, None)
                            self._rr_charged.pop(cls, None)
                    self._len -= 1
                    return
        raise ValueError("QoSQueue.remove(r): request not queued")

    def __len__(self) -> int:
        return self._len

    def __bool__(self) -> bool:
        return self._len > 0

    def __iter__(self):
        """Scheduling-intent order: front stack (next-to-pop first),
        then classes descending, tenants ascending, EDF/FIFO within —
        a deterministic flatten, not an exact pop-order simulation (the
        DWRR rotation interleaves tenants)."""
        for r in reversed(self._front):
            yield r
        for cls in sorted(self._classes, reverse=True):
            for tenant in sorted(self._classes[cls]):
                for _, _, r in sorted(self._classes[cls][tenant],
                                      key=lambda e: (e[0], e[1])):
                    yield r

    def __getitem__(self, i) -> Any:
        if i == 0:
            return self._peek()
        items = list(self)
        return items[i]

    # ----------------------------------------------------------------- obs

    def stats(self) -> dict:
        """Host-only QoS bookkeeping for status()/robustness_counters():
        live queue depths plus cumulative scheduling (pop) tallies."""
        by_class: dict[int, int] = {}
        by_tenant: dict[int, int] = {}
        for r in self._front:
            cls = int(getattr(r, "priority", 0))
            tenant = int(getattr(r, "tenant", 0))
            by_class[cls] = by_class.get(cls, 0) + 1
            by_tenant[tenant] = by_tenant.get(tenant, 0) + 1
        for cls, by_t in self._classes.items():
            for tenant, heap in by_t.items():
                by_class[cls] = by_class.get(cls, 0) + len(heap)
                by_tenant[tenant] = by_tenant.get(tenant, 0) + len(heap)
        return {
            "queue_by_class": dict(by_class),
            "queue_by_tenant": dict(by_tenant),
            "served_by_class": dict(self.served_by_class),
            "served_by_tenant": dict(self.served_by_tenant),
            "weights": dict(self._weights),
        }
