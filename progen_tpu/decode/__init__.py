from progen_tpu.decode.engine import Completion, Request, ServingEngine
from progen_tpu.decode.incremental import ProGenDecodeStep, init_caches
from progen_tpu.decode.prefill import (
    harvest_caches,
    make_prefiller,
    pad_prime_length,
)
from progen_tpu.decode.sampler import (
    gumbel_topk_sample,
    gumbel_topk_sample_batched,
    make_chunked_sampler,
    make_sampler,
    teacher_forced_logits,
    truncate_after_eos,
)

__all__ = [
    "Completion",
    "ProGenDecodeStep",
    "Request",
    "ServingEngine",
    "gumbel_topk_sample",
    "gumbel_topk_sample_batched",
    "harvest_caches",
    "init_caches",
    "make_chunked_sampler",
    "make_prefiller",
    "make_sampler",
    "pad_prime_length",
    "teacher_forced_logits",
    "truncate_after_eos",
]
