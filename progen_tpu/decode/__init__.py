from progen_tpu.decode.engine import Completion, Request, ServingEngine
from progen_tpu.decode.incremental import (
    ProGenDecodeStep,
    ProGenPagedDecodeStep,
    init_caches,
    init_gate_pool,
)
from progen_tpu.decode.paging import (
    DUMP_PAGE,
    NULL_PAGE,
    PagePool,
    SlotPages,
    pages_for_span,
    prefix_key,
)
from progen_tpu.decode.prefill import (
    harvest_caches,
    harvest_gate_pages,
    make_prefiller,
    pad_prime_length,
)
from progen_tpu.decode.sampler import (
    gumbel_topk_sample,
    gumbel_topk_sample_batched,
    make_chunked_sampler,
    make_sampler,
    teacher_forced_logits,
    truncate_after_eos,
)

__all__ = [
    "Completion",
    "DUMP_PAGE",
    "NULL_PAGE",
    "PagePool",
    "ProGenDecodeStep",
    "ProGenPagedDecodeStep",
    "Request",
    "ServingEngine",
    "SlotPages",
    "gumbel_topk_sample",
    "gumbel_topk_sample_batched",
    "harvest_caches",
    "harvest_gate_pages",
    "init_caches",
    "init_gate_pool",
    "make_chunked_sampler",
    "make_prefiller",
    "make_sampler",
    "pad_prime_length",
    "pages_for_span",
    "prefix_key",
    "teacher_forced_logits",
    "truncate_after_eos",
]
