from progen_tpu.decode.incremental import ProGenDecodeStep, init_caches
from progen_tpu.decode.sampler import (
    gumbel_topk_sample,
    make_sampler,
    teacher_forced_logits,
    truncate_after_eos,
)

__all__ = [
    "ProGenDecodeStep",
    "init_caches",
    "gumbel_topk_sample",
    "make_sampler",
    "teacher_forced_logits",
    "truncate_after_eos",
]
