"""Incremental (single-token) decode step with O(window) attention cache.

The reference samples by re-running the FULL forward over the whole padded
sequence for every generated token (``/root/reference/progen_transformer/
utils.py:106-135``) — O(L) full forwards, O(L²·w) total attention work.
SURVEY.md §2.c calls for a scan-based cached decoder; this module is the
per-token step, designed around the model's three kinds of sequence state:

* **token shift** needs the previous position's POST-NORM activations in
  each block -> one ``(B, dim)`` carry per block;
* **local windowed attention** at position i attends keys in
  ``[prev_window_start(i), i]`` — at most ``2*window`` positions -> a RING
  BUFFER of post-rotary k/v per layer, slot ``pos % (2*window)``.  Which
  slots are valid is closed-form from (pos, slot), no position cache:
  slot s holds ``p_s = pos - ((pos - s) mod 2w)``; it is attendable iff
  ``p_s >= window_start(pos) - window`` (negative p_s = the reference's
  phantom zero-pad window before position 0, reproduced by the zero-
  initialized ring slots);
* **SGU/gMLP** mixes ALL previous positions through a learned causal row
  -> a ``(B, seq_len, hidden/2)`` cache of normed gate activations per
  gMLP layer; step m contracts the cache with weight row m (masked to
  ``n <= m``).

Module/parameter names exactly mirror ``progen_tpu.models.progen.ProGen``
(``attn{i}``/``ff{i}``/``embed``/``norm_out``/``to_logits`` with identical
submodule names), so trained parameters bind directly to the decode graph.

Speculative decoding (``decode/spec.py``) reuses this step for BOTH the
target and the tiny draft model (a second ``ProGenDecodeStep`` over
``draft_config_for``'s shrunk config); callers that run a step on a
throwaway cache copy past a row's logical end must clamp positions to
``[0, decode_len)`` themselves — the step trusts ``pos`` to index the
SGU weight rows, it never bounds-checks it.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.models.progen import ProGenConfig, _dense, _norm, apply_lora
from progen_tpu.ops.local_attention import ATTN_MASK_VALUE
from progen_tpu.ops.rotary import fixed_pos_embedding, rotate_every_two


def _shift_with_carry(h, prev):
    """Token shift at one position: the first ceil(d/2) channels come from
    the previous position (``ops/shift.py`` semantics, incremental)."""
    d = h.shape[-1]
    split = d - d // 2
    return jnp.concatenate([prev[..., :split], h[..., split:]], axis=-1)


def _rotate_at(x, sin_row, cos_row):
    """Rotary for one position per row: ``x (B, h, d)``, table rows
    ``(B, d)`` (each row at its own position)."""
    sin_row = sin_row[:, None, :]
    cos_row = cos_row[:, None, :]
    return x * cos_row + rotate_every_two(x) * sin_row


def _update_rows(cache, update, idx, axis):
    """Per-row ``dynamic_update_index_in_dim``: write ``update[b]`` into
    ``cache[b]`` at row ``idx[b]`` along ``axis`` (of the per-row view)."""
    return jax.vmap(
        lambda c, u, i: jax.lax.dynamic_update_index_in_dim(c, u, i, axis)
    )(cache, update, idx)


def init_caches(config: ProGenConfig, batch_size: int,
                policy: Policy | None = None,
                decode_len: int | None = None,
                with_sgu: bool = True) -> dict:
    """Zero caches for a fresh decode (a plain pytree, scan-friendly).

    ``decode_len``: positions the decode will actually visit (default
    ``seq_len``).  The attention ring is O(window) regardless; the SGU gate
    cache — the one seq_len-sized buffer — shrinks to ``decode_len`` rows,
    so a 200-token sample from a 4096-seq_len config allocates (and
    contracts per step) 200 rows, not 4096.  Exact because SGU row ``pos``
    is causally masked to columns ``<= pos < decode_len``.

    ``with_sgu=False`` drops the per-slot gate cache entirely — the paged
    engine keeps gate rows in a global page pool (see
    :func:`init_gate_pool`) instead of ``batch x n_rows`` dense slabs.
    """
    c = config
    pol = policy or make_policy()
    dt = pol.compute_dtype
    ring = 2 * c.window_size
    n_rows = min(decode_len or c.seq_len, c.seq_len)
    return {
        "attn_prev": [jnp.zeros((batch_size, c.dim), dt) for _ in range(c.depth)],
        "ff_prev": [jnp.zeros((batch_size, c.dim), dt) for _ in range(c.depth)],
        "k": [jnp.zeros((batch_size, c.heads, ring, c.dim_head), dt)
              for _ in range(c.depth)],
        "v": [jnp.zeros((batch_size, c.heads, ring, c.dim_head), dt)
              for _ in range(c.depth)],
        "sgu_gate": {
            str(i): jnp.zeros((batch_size, n_rows, (c.dim * c.ff_mult) // 2), dt)
            for i in range(c.depth) if c.layer_uses_gmlp(i)
        } if with_sgu else {},
    }


def init_gate_pool(config: ProGenConfig, num_pages: int, page_size: int,
                   policy: Policy | None = None,
                   gate_dtype: str = "bf16") -> dict:
    """Zero global gate-row pool, one ``(num_pages, page_size, hidden/2)``
    array per gMLP layer (keyed like ``sgu_gate``).  Page 0 is the
    all-zeros NULL page and stays zero forever (reads of unowned table
    entries land here and match the dense engine's zero-initialized
    cache); page 1 is the write-sink DUMP page.

    ``gate_dtype="int8"`` allocates the pool in int8 (the 8-bit page
    format); rows are quantized per-row on scatter against the parallel
    f32 scale pool from :func:`init_gate_scale`.  NULL-page reads stay
    exact zeros (0 * scale == 0.0)."""
    c = config
    pol = policy or make_policy()
    if gate_dtype == "int8":
        dt = jnp.int8
    elif gate_dtype == "bf16":
        dt = pol.compute_dtype
    else:
        raise ValueError(f"unknown gate_dtype {gate_dtype!r}; "
                         "use 'bf16' or 'int8'")
    half = (c.dim * c.ff_mult) // 2
    return {
        str(i): jnp.zeros((num_pages, page_size, half), dt)
        for i in range(c.depth) if c.layer_uses_gmlp(i)
    }


def init_gate_scale(config: ProGenConfig, num_pages: int,
                    page_size: int) -> dict:
    """Per-row f32 scale pool for the int8 gate pages: one
    ``(num_pages, page_size)`` array per gMLP layer, mirroring
    :func:`init_gate_pool`'s page layout.  Ones-initialized so a
    never-written row dequantizes to exact zeros."""
    c = config
    return {
        str(i): jnp.ones((num_pages, page_size), jnp.float32)
        for i in range(c.depth) if c.layer_uses_gmlp(i)
    }


class LocalAttentionDecode(nn.Module):
    """One-position attention against the k/v ring buffer."""

    dim: int
    window_size: int
    heads: int
    dim_head: int
    shift: bool
    policy: Policy
    weights: str = "bf16"

    @nn.compact
    def __call__(self, x, sin_row, cos_row, slot, valid, prev, k_cache, v_cache,
                 adapters=None, tenant=None):
        h, d = self.heads, self.dim_head
        inner = h * d
        b = x.shape[0]

        normed = _norm(self.policy, name="norm")(x)
        new_prev = normed
        if self.shift:
            normed = _shift_with_carry(normed, prev)

        qkv = _dense(inner * 3, use_bias=False, axes=("embed", "qkv"),
                     policy=self.policy, name="to_qkv",
                     weights=self.weights)(normed)
        if adapters is not None:
            qkv = apply_lora(qkv, normed, adapters["qkv"], tenant)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q, k, v = (t.reshape(b, h, d) for t in (q, k, v))
        q, k, v = (_rotate_at(t, sin_row, cos_row) for t in (q, k, v))

        # per-row ring slot (rows may sit at different positions — the
        # continuous-batching engine drives one step with a (B,) pos vector)
        k_cache = _update_rows(k_cache, k, slot, axis=1)
        v_cache = _update_rows(v_cache, v, slot, axis=1)

        sim = jnp.einsum("bhd,bhsd->bhs", q, k_cache,
                         preferred_element_type=jnp.float32) * (d ** -0.5)
        sim = jnp.where(valid[:, None, :], sim, ATTN_MASK_VALUE)
        attn = jax.nn.softmax(sim, axis=-1).astype(v_cache.dtype)
        out = jnp.einsum(
            "bhs,bhsd->bhd", attn, v_cache,
            preferred_element_type=jnp.float32,
        ).astype(v_cache.dtype).reshape(b, inner)
        proj = _dense(self.dim, use_bias=True, axes=("qkv", "embed"),
                      policy=self.policy, name="to_out",
                      weights=self.weights)(out)
        if adapters is not None:
            proj = apply_lora(proj, out, adapters["out"], tenant)
        return proj, new_prev, k_cache, v_cache


class SGUDecode(nn.Module):
    """One-position spatial gate: contract the gate cache with weight row m."""

    seq_len: int
    dim_out: int
    policy: Policy
    eps: float = 1e-3
    weights: str = "bf16"

    @nn.compact
    def __call__(self, x, pos, gate_cache, adapters=None, tenant=None):
        n = self.seq_len
        x, gate = jnp.split(x, 2, axis=-1)
        gate = _norm(self.policy, name="norm")(gate)

        init_scale = self.eps / n

        def symmetric_uniform(key, shape, dtype):
            return jax.random.uniform(key, shape, dtype,
                                      minval=-init_scale, maxval=init_scale)

        if self.weights == "int8":
            weights = self.param("spatial_weights", nn.initializers.zeros,
                                 (n, n), jnp.int8)
            w_scale = self.variable(
                "qscale", "spatial_weights_scale",
                lambda: jnp.ones((n,), jnp.float32)).value
        else:
            weights = self.param("spatial_weights", symmetric_uniform, (n, n),
                                 self.policy.param_dtype)
            w_scale = None
        biases = self.param("spatial_biases", nn.initializers.ones, (n, 1),
                            self.policy.param_dtype)

        # the cache may be shorter than seq_len (short-decode fast path);
        # only weight columns < n_cache can be causally live since pos
        # stays < n_cache for the whole decode.  ``pos`` is (B,): each row
        # reads its own weight row / bias and masks at its own position.
        n_cache = gate_cache.shape[1]
        gate_cache = _update_rows(gate_cache, gate, pos, axis=0)
        w_rows = weights.astype(jnp.float32)[pos][:, :n_cache]  # (B, n_cache)
        if w_scale is not None:
            # per-ROW scale: each batch row reads weight row pos[b]
            w_rows = w_rows * w_scale[pos][:, None]
        causal = (jnp.arange(n_cache)[None, :] <= pos[:, None])
        w_rows = w_rows * causal.astype(jnp.float32)
        mixed = jnp.einsum("bnd,bn->bd", gate_cache.astype(jnp.float32),
                           w_rows, preferred_element_type=jnp.float32)
        bias_m = biases.astype(jnp.float32)[pos]  # (B, 1)
        mixed = (mixed + bias_m).astype(x.dtype)

        x = x * mixed
        out = _dense(self.dim_out, use_bias=True, axes=("mlp_in", "mlp"),
                     policy=self.policy, name="proj_out",
                     weights=self.weights)(x)
        if adapters is not None:
            out = apply_lora(out, x, adapters, tenant)
        return out, gate_cache


class FeedForwardDecode(nn.Module):
    dim: int
    seq_len: int
    ff_mult: int
    glu: bool
    use_sgu: bool
    shift: bool
    policy: Policy
    weights: str = "bf16"

    @nn.compact
    def __call__(self, x, pos, prev, gate_cache, adapters=None, tenant=None):
        hidden = self.dim * self.ff_mult * (2 if self.glu else 1)

        normed = _norm(self.policy, name="norm")(x)
        new_prev = normed
        if self.shift:
            normed = _shift_with_carry(normed, prev)

        h = _dense(hidden, use_bias=True, axes=("embed", "mlp"),
                   policy=self.policy, name="proj_in",
                   weights=self.weights)(normed)
        if self.glu:
            h, gate = jnp.split(h, 2, axis=-1)
            h = h * nn.gelu(gate)
        else:
            h = nn.gelu(h)

        if self.use_sgu:
            h, gate_cache = SGUDecode(
                seq_len=self.seq_len, dim_out=hidden // 2,
                policy=self.policy, weights=self.weights, name="sgu",
            )(h, pos, gate_cache,
              None if adapters is None else adapters["sgu"], tenant)

        out = _dense(self.dim, use_bias=True, axes=("mlp", "embed"),
                     policy=self.policy, name="proj_out",
                     weights=self.weights)(h)
        return out, new_prev, gate_cache


class ProGenDecodeStep(nn.Module):
    """One decode step: ``(tok (B,), pos, caches) -> (logits (B, V), caches)``.

    ``pos`` is a traced scalar OR a ``(B,)`` vector — the serving engine
    steps a batch of slots each at its OWN position (continuous batching);
    a scalar broadcasts to all rows.  Every shape is static, so the step
    nests under ``lax.scan``/``jit`` without retracing.
    """

    config: ProGenConfig
    policy: Policy = dataclasses.field(default_factory=make_policy)
    weights: str = "bf16"

    @nn.compact
    def __call__(self, tok, pos, caches, adapters=None, tenant=None):
        cfg, pol = self.config, self.policy
        wsz = cfg.window_size
        ring = 2 * wsz
        b = tok.shape[0]

        x = nn.Embed(
            cfg.num_tokens, cfg.dim,
            dtype=pol.compute_dtype, param_dtype=pol.param_dtype,
            embedding_init=nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0),
            name="embed",
        )(tok)

        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
        sin_t, cos_t = fixed_pos_embedding(cfg.seq_len, cfg.dim_head)
        sin_row = sin_t[pos].astype(pol.compute_dtype)  # (B, dim_head)
        cos_row = cos_t[pos].astype(pol.compute_dtype)
        slot = pos % ring

        s = jnp.arange(ring)[None, :]
        p_s = pos[:, None] - jnp.mod(pos[:, None] - s, ring)
        w_start = ((pos // wsz) * wsz)[:, None]
        # NOTE no ``p_s >= 0`` clause: in window 0 the reference attends a
        # phantom ZERO-pad previous window (progen.py:90-95) whose keys
        # contribute exp(0 - max) to the softmax denominator; ring slots
        # with negative p_s are untouched zeros, which reproduces that
        # exactly.
        valid = p_s >= w_start - wsz  # (B, ring)

        new: dict[str, Any] = {
            "attn_prev": list(caches["attn_prev"]),
            "ff_prev": list(caches["ff_prev"]),
            "k": list(caches["k"]),
            "v": list(caches["v"]),
            "sgu_gate": dict(caches["sgu_gate"]),
        }

        for i in range(cfg.depth):
            use_gmlp = cfg.layer_uses_gmlp(i)
            attn_ad = None if adapters is None else adapters.get(f"attn{i}")
            ff_ad = None if adapters is None else adapters.get(f"ff{i}")
            attn_out, new["attn_prev"][i], new["k"][i], new["v"][i] = (
                LocalAttentionDecode(
                    dim=cfg.dim, window_size=wsz, heads=cfg.heads,
                    dim_head=cfg.dim_head, shift=cfg.shift_tokens,
                    policy=pol, weights=self.weights, name=f"attn{i}",
                )(x, sin_row, cos_row, slot, valid,
                  caches["attn_prev"][i], caches["k"][i], caches["v"][i],
                  attn_ad, tenant)
            )
            x = x + attn_out

            gate_cache = caches["sgu_gate"].get(str(i))
            ff_out, new["ff_prev"][i], gate_cache = FeedForwardDecode(
                dim=cfg.dim, seq_len=cfg.seq_len, ff_mult=cfg.ff_mult,
                glu=(not use_gmlp) and cfg.ff_glu, use_sgu=use_gmlp,
                shift=cfg.shift_tokens, policy=pol, weights=self.weights,
                name=f"ff{i}",
            )(x, pos, caches["ff_prev"][i],
              gate_cache if gate_cache is not None else jnp.zeros(()),
              ff_ad, tenant)
            x = x + ff_out
            if str(i) in new["sgu_gate"]:
                new["sgu_gate"][str(i)] = gate_cache

        h = _norm(pol, name="norm_out")(x)
        logits = _dense(cfg.num_tokens, use_bias=True, axes=("embed", "vocab"),
                        policy=pol, name="to_logits")(h)
        return pol.cast_to_output(logits), new


class SGUDecodePaged(nn.Module):
    """One-position spatial gate against the global page pool.

    Identical math and parameter names to :class:`SGUDecode` (trained
    params bind to either graph); the per-slot ``(B, n_rows, d)`` gate
    cache is replaced by a pooled ``(num_pages, page_size, d)`` array plus
    a per-row page table.  The freshly normed gate row is scattered into
    the row's current page (``write_ok`` redirects paused/done/inactive
    rows to the DUMP page), then the ragged paged contraction reproduces
    the dense masked einsum (see ``ops/pallas_paged_attention.py``).
    """

    seq_len: int
    dim_out: int
    n_rows: int
    policy: Policy
    impl: str = "xla"
    eps: float = 1e-3
    weights: str = "bf16"
    gate_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x, pos, pool, table, write_ok, pool_scale=None,
                 adapters=None, tenant=None):
        from progen_tpu.ops.pallas_paged_attention import (
            paged_gate_mix, write_gate_row)

        n = self.seq_len
        x, gate = jnp.split(x, 2, axis=-1)
        gate = _norm(self.policy, name="norm")(gate)

        init_scale = self.eps / n

        def symmetric_uniform(key, shape, dtype):
            return jax.random.uniform(key, shape, dtype,
                                      minval=-init_scale, maxval=init_scale)

        if self.weights == "int8":
            weights = self.param("spatial_weights", nn.initializers.zeros,
                                 (n, n), jnp.int8)
            w_scale = self.variable(
                "qscale", "spatial_weights_scale",
                lambda: jnp.ones((n,), jnp.float32)).value
        else:
            weights = self.param("spatial_weights", symmetric_uniform, (n, n),
                                 self.policy.param_dtype)
            w_scale = None
        biases = self.param("spatial_biases", nn.initializers.ones, (n, 1),
                            self.policy.param_dtype)

        if self.gate_dtype == "int8":
            # quantize-on-scatter: the row's int8 code and its f32 scale
            # land in twin pools through the same dump-redirected target
            pool, pool_scale = write_gate_row(pool, table, pos, gate,
                                              write_ok, scale=pool_scale)
        else:
            pool = write_gate_row(pool, table, pos, gate, write_ok)
        mixed = paged_gate_mix(weights, biases, pool, table, pos,
                               n_rows=self.n_rows, impl=self.impl,
                               w_scale=w_scale, pool_scale=pool_scale)
        mixed = mixed.astype(x.dtype)

        x = x * mixed
        out = _dense(self.dim_out, use_bias=True, axes=("mlp_in", "mlp"),
                     policy=self.policy, name="proj_out",
                     weights=self.weights)(x)
        if adapters is not None:
            out = apply_lora(out, x, adapters, tenant)
        return out, pool, pool_scale


class FeedForwardDecodePaged(nn.Module):
    """gMLP feed-forward step over the paged gate pool (parameter-name
    compatible with :class:`FeedForwardDecode`)."""

    dim: int
    seq_len: int
    ff_mult: int
    n_rows: int
    shift: bool
    policy: Policy
    impl: str = "xla"
    weights: str = "bf16"
    gate_dtype: str = "bf16"

    @nn.compact
    def __call__(self, x, pos, prev, pool, table, write_ok, pool_scale=None,
                 adapters=None, tenant=None):
        hidden = self.dim * self.ff_mult

        normed = _norm(self.policy, name="norm")(x)
        new_prev = normed
        if self.shift:
            normed = _shift_with_carry(normed, prev)

        h = _dense(hidden, use_bias=True, axes=("embed", "mlp"),
                   policy=self.policy, name="proj_in",
                   weights=self.weights)(normed)
        h = nn.gelu(h)

        h, pool, pool_scale = SGUDecodePaged(
            seq_len=self.seq_len, dim_out=hidden // 2, n_rows=self.n_rows,
            policy=self.policy, impl=self.impl, weights=self.weights,
            gate_dtype=self.gate_dtype, name="sgu",
        )(h, pos, pool, table, write_ok, pool_scale,
          None if adapters is None else adapters["sgu"], tenant)

        out = _dense(self.dim, use_bias=True, axes=("mlp", "embed"),
                     policy=self.policy, name="proj_out",
                     weights=self.weights)(h)
        return out, new_prev, pool, pool_scale


class ProGenPagedDecodeStep(nn.Module):
    """One paged decode step: ``(tok, pos, caches, table, write_ok) ->
    (logits, caches)``.

    Same graph as :class:`ProGenDecodeStep` except gMLP layers read/write
    the global gate-row pool (``caches["sgu_pool"]``) through the per-row
    page ``table`` instead of a per-slot dense cache.  ``write_ok`` masks
    the pool scatter only — ring/carry writes are merged by liveness in
    the engine's chunk body (a paused row must not clobber its carries
    with a speculative step's values, since its ``pos`` does not advance).
    """

    config: ProGenConfig
    n_rows: int
    policy: Policy = dataclasses.field(default_factory=make_policy)
    impl: str = "xla"
    weights: str = "bf16"
    gate_dtype: str = "bf16"

    @nn.compact
    def __call__(self, tok, pos, caches, table, write_ok, adapters=None,
                 tenant=None):
        cfg, pol = self.config, self.policy
        wsz = cfg.window_size
        ring = 2 * wsz
        b = tok.shape[0]

        x = nn.Embed(
            cfg.num_tokens, cfg.dim,
            dtype=pol.compute_dtype, param_dtype=pol.param_dtype,
            embedding_init=nn.initializers.variance_scaling(
                1.0, "fan_in", "normal", out_axis=0),
            name="embed",
        )(tok)

        pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (b,))
        sin_t, cos_t = fixed_pos_embedding(cfg.seq_len, cfg.dim_head)
        sin_row = sin_t[pos].astype(pol.compute_dtype)
        cos_row = cos_t[pos].astype(pol.compute_dtype)
        slot = pos % ring

        s = jnp.arange(ring)[None, :]
        p_s = pos[:, None] - jnp.mod(pos[:, None] - s, ring)
        w_start = ((pos // wsz) * wsz)[:, None]
        valid = p_s >= w_start - wsz  # (B, ring); see ProGenDecodeStep

        new: dict[str, Any] = {
            "attn_prev": list(caches["attn_prev"]),
            "ff_prev": list(caches["ff_prev"]),
            "k": list(caches["k"]),
            "v": list(caches["v"]),
            "sgu_pool": dict(caches["sgu_pool"]),
        }
        if self.gate_dtype == "int8":
            new["sgu_pool_scale"] = dict(caches["sgu_pool_scale"])

        for i in range(cfg.depth):
            use_gmlp = cfg.layer_uses_gmlp(i)
            attn_ad = None if adapters is None else adapters.get(f"attn{i}")
            ff_ad = None if adapters is None else adapters.get(f"ff{i}")
            attn_out, new["attn_prev"][i], new["k"][i], new["v"][i] = (
                LocalAttentionDecode(
                    dim=cfg.dim, window_size=wsz, heads=cfg.heads,
                    dim_head=cfg.dim_head, shift=cfg.shift_tokens,
                    policy=pol, weights=self.weights, name=f"attn{i}",
                )(x, sin_row, cos_row, slot, valid,
                  caches["attn_prev"][i], caches["k"][i], caches["v"][i],
                  attn_ad, tenant)
            )
            x = x + attn_out

            if use_gmlp:
                pool_scale = (caches["sgu_pool_scale"][str(i)]
                              if self.gate_dtype == "int8" else None)
                ff_out, new["ff_prev"][i], new_pool, new_scale = (
                    FeedForwardDecodePaged(
                        dim=cfg.dim, seq_len=cfg.seq_len, ff_mult=cfg.ff_mult,
                        n_rows=self.n_rows, shift=cfg.shift_tokens,
                        policy=pol, impl=self.impl, weights=self.weights,
                        gate_dtype=self.gate_dtype, name=f"ff{i}",
                    )(x, pos, caches["ff_prev"][i],
                      caches["sgu_pool"][str(i)], table, write_ok,
                      pool_scale, ff_ad, tenant)
                )
                new["sgu_pool"][str(i)] = new_pool
                if self.gate_dtype == "int8":
                    new["sgu_pool_scale"][str(i)] = new_scale
            else:
                ff_out, new["ff_prev"][i], _ = FeedForwardDecode(
                    dim=cfg.dim, seq_len=cfg.seq_len, ff_mult=cfg.ff_mult,
                    glu=cfg.ff_glu, use_sgu=False,
                    shift=cfg.shift_tokens, policy=pol, weights=self.weights,
                    name=f"ff{i}",
                )(x, pos, caches["ff_prev"][i], jnp.zeros(()), ff_ad, tenant)
            x = x + ff_out

        h = _norm(pol, name="norm_out")(x)
        logits = _dense(cfg.num_tokens, use_bias=True, axes=("embed", "vocab"),
                        policy=pol, name="to_logits")(h)
        return pol.cast_to_output(logits), new
