"""Autoregressive sampler: one ``lax.scan`` over positions, cached decode.

Capability parity with the reference sampler (``/root/reference/
progen_transformer/utils.py:97-135`` and call sites ``train.py:219-228``,
``sample.py:64-73``): prime teacher-forcing, optional prepended BOS, top-k
gumbel-max sampling, truncation after the second zero (position 0's
BOS/pad counts as the first).  Structural differences, both conscious:

* the reference runs a host-driven Python loop of FULL forwards (O(L) model
  applies over the whole padded sequence); this is a single jitted scan of
  cached single-token steps — same trajectory semantics, O(L·window)
  attention instead of O(L²·window);
* the reference zeroes non-top-k logits and multiplies the gumbel noise by
  the mask (``utils.py:97-100,121-123``), which can leak a masked token
  when every top-k entry is negative; here masked entries are ``-inf``
  (standard top-k gumbel-max).  Temperature generalizes the reference's
  implicit temperature=1 (pass ``temperature=0`` for greedy).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.decode.incremental import ProGenDecodeStep, init_caches
from progen_tpu.models.progen import ProGenConfig


def gumbel_topk_sample(key, logits, top_k: int | None, temperature: float = 1.0):
    """Sample token ids ``(B,)`` from logits ``(B, V)``."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    noise = jax.random.gumbel(key, logits.shape, dtype=logits.dtype)
    return jnp.argmax(logits + noise, axis=-1)


def truncate_after_eos(seq, pad_id: int = 0):
    """Zero everything after the SECOND zero (reference ``utils.py:131-134``:
    the BOS/pad at position 0 is the first; the next zero is the learned
    EOS, which is kept)."""
    after = jnp.cumsum(seq == pad_id, axis=-1) > 1
    return seq * (~after)


def _constrain_caches(caches, mesh: Mesh, strategies: Sequence[str]):
    """Pin the decode caches' layouts over the mesh.

    Only tensor parallelism shards real decode state: the k/v rings split
    on heads and the SGU gate cache on its channel half, matching the tp
    rule table (``parallel/sharding.py``) so the per-step attention and
    gate contractions stay local to each tensor shard.  Everything else
    (tiny per-block carries) replicates — decode batches are small and
    fsdp's win is the PARAMS staying sharded, which they do via
    ``params_shardings``.
    """
    if "tp" not in strategies or mesh.shape.get("tensor", 1) <= 1:
        return caches
    wsc = jax.lax.with_sharding_constraint
    kv = NamedSharding(mesh, PartitionSpec(None, "tensor", None, None))
    gate = NamedSharding(mesh, PartitionSpec(None, None, "tensor"))
    return {
        **caches,
        "k": [wsc(x, kv) for x in caches["k"]],
        "v": [wsc(x, kv) for x in caches["v"]],
        "sgu_gate": {k: wsc(v, gate) for k, v in caches["sgu_gate"].items()},
    }


def make_sampler(config: ProGenConfig, policy: Policy | None = None,
                 mesh: Mesh | None = None,
                 strategies: Sequence[str] = ("dp",),
                 params_shardings=None):
    """Build ``sample(params, key, prime, length, ...)``.

    ``prime``: ``(B, P)`` int tokens (already encoded).  ``length`` must be
    ≤ ``config.seq_len`` (the learned (seq_len, seq_len) gMLP weights have
    no rows past that — true of the reference too).  Short decodes are
    cheap: every cache and the scan are sized to ``length``, not seq_len.
    Returns ``(B, length)`` sequences, EOS-truncated.

    Mesh-aware decode (BASELINE.md's XL row is "fully-sharded params +
    generation"): pass ``mesh`` (+ ``strategies`` and the params'
    ``params_shardings``, e.g. ``TrainFunctions.state_shardings.params``)
    and the decode runs as one SPMD program — params STAY in their
    training shardings (never gathered to one chip), tp shards the per-
    step contractions and caches, and the sampled tokens come out
    replicated so every host can fetch them.
    """
    policy = policy or make_policy()
    step_model = ProGenDecodeStep(config=config, policy=policy)

    if mesh is not None:
        from progen_tpu.parallel.sharding import logical_rules

        rules = logical_rules(strategies)
        repl = NamedSharding(mesh, PartitionSpec())
        # params shardings are applied via an explicit device_put in the
        # wrapper below (a no-op when the caller's params already live
        # there) — jit's in_shardings would reject the static kwargs
        jit_kwargs = {"out_shardings": repl}

        def trace_ctx():
            # rules + mesh must be active while flax TRACES the decode
            # step (same pattern as train/step.py's apply_model)
            stack = contextlib.ExitStack()
            stack.enter_context(mesh)
            stack.enter_context(nn.logical_axis_rules(rules))
            return stack
    else:
        jit_kwargs = {}
        trace_ctx = contextlib.ExitStack

    @partial(jax.jit, static_argnames=("length", "top_k", "add_bos", "temperature"),
             **jit_kwargs)
    def sample(params, key, prime, length, top_k=None, add_bos=False,
               temperature=1.0):
        if prime.ndim != 2:
            raise ValueError(f"prime must be (B, P), got {prime.shape}")
        b, p = prime.shape
        if add_bos:
            prime = jnp.concatenate(
                [jnp.zeros((b, 1), prime.dtype), prime[:, : length - 1]], axis=1
            )
            p = min(p + 1, length)
        start_pos = p
        if not (0 < start_pos <= length <= config.seq_len):
            raise ValueError(
                f"need 0 < prime length {start_pos} <= length {length} <= "
                f"seq_len {config.seq_len}"
            )

        seq = jnp.zeros((b, length), jnp.int32)
        seq = jax.lax.dynamic_update_slice(seq, prime.astype(jnp.int32), (0, 0))

        with trace_ctx():
            caches = init_caches(config, b, policy, decode_len=length)
            if mesh is not None:
                caches = _constrain_caches(caches, mesh, strategies)

            def body(carry, pos):
                seq, caches, key = carry
                tok = jax.lax.dynamic_index_in_dim(seq, pos, axis=1,
                                                   keepdims=False)
                logits, caches = step_model.apply(params, tok, pos, caches)
                key, sub = jax.random.split(key)
                nxt = gumbel_topk_sample(sub, logits.astype(jnp.float32), top_k,
                                         temperature).astype(jnp.int32)
                write = (pos + 1 >= start_pos) & (pos + 1 < length)
                cur = jax.lax.dynamic_index_in_dim(
                    seq, jnp.minimum(pos + 1, length - 1), axis=1,
                    keepdims=False)
                val = jnp.where(write, nxt, cur)
                seq = jax.lax.dynamic_update_index_in_dim(
                    seq, val, jnp.minimum(pos + 1, length - 1), axis=1
                )
                return (seq, caches, key), None

            (seq, _, _), _ = jax.lax.scan(
                body, (seq, caches, key), jnp.arange(length)
            )
        return truncate_after_eos(seq)

    if params_shardings is None:
        return sample

    def sharded_sample(params, key, prime, length, top_k=None, add_bos=False,
                       temperature=1.0):
        params = jax.device_put(params, {"params": params_shardings})
        return sample(params, key, prime, length, top_k=top_k,
                      add_bos=add_bos, temperature=temperature)

    sharded_sample.lower = sample.lower  # AOT warm-compile hook
    return sharded_sample


def teacher_forced_logits(config: ProGenConfig, params, tokens,
                          policy: Policy | None = None):
    """Run the cached decode step over a FIXED token sequence and return all
    logits ``(B, L, V)`` — the decode-vs-parallel parity oracle (tests) and
    a scoring utility."""
    policy = policy or make_policy()
    step_model = ProGenDecodeStep(config=config, policy=policy)
    b, n = tokens.shape
    caches = init_caches(config, b, policy, decode_len=n)

    def body(caches, pos):
        tok = jax.lax.dynamic_index_in_dim(tokens, pos, axis=1, keepdims=False)
        logits, caches = step_model.apply(params, tok, pos, caches)
        return caches, logits

    _, logits = jax.lax.scan(body, caches, jnp.arange(n))
    return jnp.transpose(logits, (1, 0, 2))
