"""Autoregressive sampler: one ``lax.scan`` over positions, cached decode.

Capability parity with the reference sampler (``/root/reference/
progen_transformer/utils.py:97-135`` and call sites ``train.py:219-228``,
``sample.py:64-73``): prime teacher-forcing, optional prepended BOS, top-k
gumbel-max sampling, truncation after the second zero (position 0's
BOS/pad counts as the first).  Structural differences, both conscious:

* the reference runs a host-driven Python loop of FULL forwards (O(L) model
  applies over the whole padded sequence); this is a single jitted scan of
  cached single-token steps — same trajectory semantics, O(L·window)
  attention instead of O(L²·window);
* the reference zeroes non-top-k logits and multiplies the gumbel noise by
  the mask (``utils.py:97-100,121-123``), which can leak a masked token
  when every top-k entry is negative; here masked entries are ``-inf``
  (standard top-k gumbel-max).  Temperature generalizes the reference's
  implicit temperature=1 (pass ``temperature=0`` for greedy).
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.decode.incremental import ProGenDecodeStep, init_caches
from progen_tpu.decode.prefill import (
    _constrain_caches,
    make_prefiller,
    pad_prime_length,
)
from progen_tpu.models.progen import ProGenConfig


def apply_logit_mask(logits, mask):
    """The one ``-inf`` masking idiom: keep ``logits`` where ``mask`` is
    true, ``-inf`` elsewhere.  Both the top-k cut and the infilling
    alphabet constraints route through here, so "never emits a masked
    token" is a property of a single expression.  An all-true mask
    returns ``logits`` bit-identically (``jnp.where`` selects, never
    recomputes)."""
    return jnp.where(mask, logits, -jnp.inf)


def gumbel_topk_sample(key, logits, top_k: int | None, temperature: float = 1.0,
                       mask=None):
    """Sample token ids ``(B,)`` from logits ``(B, V)``.

    Runs in f32 regardless of the logits dtype: bf16 logits under a tiny
    temperature overflow to inf (and the ``-inf`` top-k mask then yields
    ``inf - inf = NaN`` rows), so the division, masking and gumbel noise
    all happen after an f32 cast.

    ``mask`` (optional, broadcastable to ``logits``, bool): tokens with a
    false entry can never be emitted — applied before the greedy branch so
    ``temperature=0`` respects it too.  Masked entries survive the top-k
    cut as ``-inf`` (``-inf >= kth`` only when ``kth`` is itself ``-inf``,
    which keeps them ``-inf``), so top-k and constraints compose.
    """
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = apply_logit_mask(logits, mask)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    logits = logits / temperature
    if top_k is not None:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = apply_logit_mask(logits, logits >= kth)
    noise = jax.random.gumbel(key, logits.shape, dtype=logits.dtype)
    return jnp.argmax(logits + noise, axis=-1)


def gumbel_topk_sample_batched(keys, logits, top_k, temperature, mask=None):
    """Per-row sampling for the serving engine: each row has its own key,
    top-k and temperature.

    ``keys``: ``(B,)`` typed PRNG keys; ``logits``: ``(B, V)``; ``top_k``:
    ``(B,)`` int32, ``0`` disables top-k for that row; ``temperature``:
    ``(B,)`` f32, ``0.0`` means greedy for that row.  Dynamic per-row k
    uses a full sort instead of ``lax.top_k`` (whose k is static) — V is
    small (vocab 256) so the sort is noise next to the model step.

    ``mask`` (optional ``(B, V)`` bool): per-row allowed-token constraint,
    applied before the greedy argmax so greedy rows respect it too.  A
    ``-inf``-masked entry divides to ``-inf``, survives the per-row k cut
    as ``-inf`` and loses every argmax, so constraints compose with
    per-row top-k exactly as in :func:`gumbel_topk_sample`.
    """
    logits = logits.astype(jnp.float32)
    if mask is not None:
        logits = apply_logit_mask(logits, mask)
    v = logits.shape[-1]
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temperature, 1e-8)[:, None]
    k_eff = jnp.where(top_k > 0, jnp.clip(top_k, 1, v), v)
    srt = jnp.sort(scaled, axis=-1)  # ascending
    kth = jnp.take_along_axis(srt, (v - k_eff)[:, None], axis=-1)
    masked = apply_logit_mask(scaled, scaled >= kth)
    noise = jax.vmap(
        lambda k: jax.random.gumbel(k, (v,), jnp.float32))(keys)
    sampled = jnp.argmax(masked + noise, axis=-1)
    return jnp.where(temperature == 0.0, greedy, sampled)


def split_keys_batched(key_data):
    """Advance a batch of raw uint32 key data one split: returns
    ``(next_key_data, subkeys)``.  The serving engine's per-slot key
    chains live as RAW key data (``jax.random.key_data``) so they can
    ride through jitted state dicts; every consumer of the chain — the
    decode chunk bodies, the speculative draft-propose and target-verify
    scans — must derive subkeys the same way, or bit-exactness between
    the speculative and plain paths breaks.  This helper is that one
    way."""
    keys = jax.random.wrap_key_data(key_data)
    split = jax.vmap(jax.random.split)(keys)  # (B, 2) keys
    return jax.random.key_data(split[:, 0]), split[:, 1]


def truncate_after_eos(seq, pad_id: int = 0):
    """Zero everything after the SECOND zero (reference ``utils.py:131-134``:
    the BOS/pad at position 0 is the first; the next zero is the learned
    EOS, which is kept)."""
    after = jnp.cumsum(seq == pad_id, axis=-1) > 1
    return seq * (~after)


# _constrain_caches moved to decode/prefill.py (shared by the prefill
# harvest, the chunked sampler and the serving engine); re-exported here
# for back-compat.


def make_sampler(config: ProGenConfig, policy: Policy | None = None,
                 mesh: Mesh | None = None,
                 strategies: Sequence[str] = ("dp",),
                 params_shardings=None):
    """Build ``sample(params, key, prime, length, ...)``.

    ``prime``: ``(B, P)`` int tokens (already encoded).  ``length`` must be
    ≤ ``config.seq_len`` (the learned (seq_len, seq_len) gMLP weights have
    no rows past that — true of the reference too).  Short decodes are
    cheap: every cache and the scan are sized to ``length``, not seq_len.
    Returns ``(B, length)`` sequences, EOS-truncated.

    Mesh-aware decode (BASELINE.md's XL row is "fully-sharded params +
    generation"): pass ``mesh`` (+ ``strategies`` and the params'
    ``params_shardings``, e.g. ``TrainFunctions.state_shardings.params``)
    and the decode runs as one SPMD program — params STAY in their
    training shardings (never gathered to one chip), tp shards the per-
    step contractions and caches, and the sampled tokens come out
    replicated so every host can fetch them.
    """
    policy = policy or make_policy()
    step_model = ProGenDecodeStep(config=config, policy=policy)

    if mesh is not None:
        from progen_tpu.parallel.sharding import logical_rules

        rules = logical_rules(strategies)
        repl = NamedSharding(mesh, PartitionSpec())
        # params shardings are applied via an explicit device_put in the
        # wrapper below (a no-op when the caller's params already live
        # there) — jit's in_shardings would reject the static kwargs
        jit_kwargs = {"out_shardings": repl}

        def trace_ctx():
            # rules + mesh must be active while flax TRACES the decode
            # step (same pattern as train/step.py's apply_model)
            stack = contextlib.ExitStack()
            stack.enter_context(mesh)
            stack.enter_context(nn.logical_axis_rules(rules))
            return stack
    else:
        jit_kwargs = {}
        trace_ctx = contextlib.ExitStack

    @partial(jax.jit, static_argnames=("length", "top_k", "add_bos", "temperature"),
             **jit_kwargs)
    def sample(params, key, prime, length, top_k=None, add_bos=False,
               temperature=1.0):
        if prime.ndim != 2:
            raise ValueError(f"prime must be (B, P), got {prime.shape}")
        b, p = prime.shape
        if add_bos:
            prime = jnp.concatenate(
                [jnp.zeros((b, 1), prime.dtype), prime[:, : length - 1]], axis=1
            )
            p = min(p + 1, length)
        start_pos = p
        if not (0 < start_pos <= length <= config.seq_len):
            raise ValueError(
                f"need 0 < prime length {start_pos} <= length {length} <= "
                f"seq_len {config.seq_len}"
            )

        seq = jnp.zeros((b, length), jnp.int32)
        seq = jax.lax.dynamic_update_slice(seq, prime.astype(jnp.int32), (0, 0))

        with trace_ctx():
            caches = init_caches(config, b, policy, decode_len=length)
            if mesh is not None:
                caches = _constrain_caches(caches, mesh, strategies)

            def body(carry, pos):
                seq, caches, key = carry
                tok = jax.lax.dynamic_index_in_dim(seq, pos, axis=1,
                                                   keepdims=False)
                logits, caches = step_model.apply(params, tok, pos, caches)
                key, sub = jax.random.split(key)
                nxt = gumbel_topk_sample(sub, logits.astype(jnp.float32), top_k,
                                         temperature).astype(jnp.int32)
                write = (pos + 1 >= start_pos) & (pos + 1 < length)
                cur = jax.lax.dynamic_index_in_dim(
                    seq, jnp.minimum(pos + 1, length - 1), axis=1,
                    keepdims=False)
                val = jnp.where(write, nxt, cur)
                seq = jax.lax.dynamic_update_index_in_dim(
                    seq, val, jnp.minimum(pos + 1, length - 1), axis=1
                )
                return (seq, caches, key), None

            (seq, _, _), _ = jax.lax.scan(
                body, (seq, caches, key), jnp.arange(length)
            )
        return truncate_after_eos(seq)

    if params_shardings is None:
        return sample

    def sharded_sample(params, key, prime, length, top_k=None, add_bos=False,
                       temperature=1.0):
        params = jax.device_put(params, {"params": params_shardings})
        return sample(params, key, prime, length, top_k=top_k,
                      add_bos=add_bos, temperature=temperature)

    sharded_sample.lower = sample.lower  # AOT warm-compile hook
    return sharded_sample


def make_chunked_sampler(config: ProGenConfig, policy: Policy | None = None,
                         mesh: Mesh | None = None,
                         strategies: Sequence[str] = ("dp",),
                         params_shardings=None, chunk_size: int = 64):
    """Build the serving-grade sampler: one-pass prefill + early-exit
    chunked decode.  Same signature and trajectory semantics as
    :func:`make_sampler` — same key ⇒ same sampled tokens — but:

    * the prime is processed by ONE batched parallel forward
      (``decode/prefill.py``) instead of P sequential decode steps;
    * decode runs in fixed-size chunks (static shapes — exactly one
      compiled chunk program, position passed dynamically); between
      chunks the HOST checks a per-row done-mask and stops as soon as
      every row has emitted EOS, so cost tracks emitted tokens, not
      ``length``.

    The done bookkeeping mirrors ``truncate_after_eos``: a row is done
    once it holds two zeros (BOS/pad + learned EOS); later steps for that
    row write pad.  The returned function exposes ``last_num_chunks``
    (chunks executed by the most recent call) for tests/benchmarks.
    """
    policy = policy or make_policy()
    step_model = ProGenDecodeStep(config=config, policy=policy)
    prefiller = make_prefiller(config, policy, mesh=mesh, strategies=strategies)

    if mesh is not None:
        from progen_tpu.parallel.sharding import logical_rules

        rules = logical_rules(strategies)

        def trace_ctx():
            stack = contextlib.ExitStack()
            stack.enter_context(mesh)
            stack.enter_context(nn.logical_axis_rules(rules))
            return stack
    else:
        trace_ctx = contextlib.ExitStack

    @partial(jax.jit,
             static_argnames=("length", "start_pos", "top_k", "temperature"))
    def start_state(key, prime, last_logits, length, start_pos, top_k,
                    temperature, first_mask=None):
        b = prime.shape[0]
        seq = jnp.zeros((b, length), jnp.int32)
        seq = jax.lax.dynamic_update_slice(seq, prime.astype(jnp.int32), (0, 0))
        # burn the key splits the sequential sampler spends on the prime
        # positions so the trajectory is bit-identical to make_sampler
        if start_pos > 1:
            def burn(k, _):
                return jax.random.split(k)[0], None
            key, _ = jax.lax.scan(burn, key, None, length=start_pos - 1)
        key, sub = jax.random.split(key)
        first = gumbel_topk_sample(sub, last_logits, top_k,
                                   temperature, mask=first_mask).astype(
                                       jnp.int32)
        zcount = jnp.sum(prime == 0, axis=1).astype(jnp.int32)
        if start_pos < length:
            val = jnp.where(zcount > 1, 0, first)
            seq = seq.at[:, start_pos].set(val)
            zcount = zcount + (val == 0)
        return seq, key, zcount

    @partial(jax.jit,
             static_argnames=("length", "start_pos", "top_k", "temperature"))
    def decode_chunk(params, seq, caches, key, zcount, pos0, length,
                     start_pos, top_k, temperature, logit_mask=None):
        with trace_ctx():
            if mesh is not None:
                caches = _constrain_caches(caches, mesh, strategies)

            def body(carry, i):
                seq, caches, key, zcount = carry
                pos = jnp.minimum(pos0 + i, length - 1)
                tok = jax.lax.dynamic_index_in_dim(seq, pos, axis=1,
                                                   keepdims=False)
                logits, caches = step_model.apply(params, tok, pos, caches)
                key, sub = jax.random.split(key)
                raw = pos0 + i + 1
                write = (raw >= start_pos) & (raw < length)
                idx = jnp.minimum(raw, length - 1)
                # the mask row for the position being WRITTEN (absolute
                # index), same gather the serving engine does per slot
                mrow = None
                if logit_mask is not None:
                    mrow = jax.lax.dynamic_index_in_dim(
                        logit_mask, idx, axis=1, keepdims=False)
                nxt = gumbel_topk_sample(sub, logits, top_k,
                                         temperature, mask=mrow).astype(
                                             jnp.int32)
                val = jnp.where(zcount > 1, 0, nxt)
                cur = jax.lax.dynamic_index_in_dim(seq, idx, axis=1,
                                                   keepdims=False)
                out = jnp.where(write, val, cur)
                seq = jax.lax.dynamic_update_index_in_dim(seq, out, idx,
                                                          axis=1)
                zcount = zcount + jnp.where(write, (out == 0).astype(
                    jnp.int32), 0)
                return (seq, caches, key, zcount), None

            (seq, caches, key, zcount), _ = jax.lax.scan(
                body, (seq, caches, key, zcount), jnp.arange(chunk_size))
        return seq, caches, key, zcount, jnp.all(zcount > 1)

    def sample(params, key, prime, length, top_k=None, add_bos=False,
               temperature=1.0, logit_mask=None):
        if prime.ndim != 2:
            raise ValueError(f"prime must be (B, P), got {prime.shape}")
        if params_shardings is not None:
            params = jax.device_put(params, {"params": params_shardings})
        b, p = prime.shape
        prime = jnp.asarray(prime, jnp.int32)
        if add_bos:
            prime = jnp.concatenate(
                [jnp.zeros((b, 1), prime.dtype), prime[:, : length - 1]],
                axis=1)
            p = min(p + 1, length)
        start_pos = p
        if not (0 < start_pos <= length <= config.seq_len):
            raise ValueError(
                f"need 0 < prime length {start_pos} <= length {length} <= "
                f"seq_len {config.seq_len}"
            )
        if logit_mask is not None:
            logit_mask = jnp.asarray(logit_mask, bool)
            if logit_mask.shape != (b, length, config.num_tokens):
                raise ValueError(
                    f"logit_mask must be (B={b}, length={length}, "
                    f"V={config.num_tokens}), got {logit_mask.shape}"
                )

        p_pad = pad_prime_length(start_pos, config.window_size, config.seq_len)
        tokens = jnp.pad(prime, ((0, 0), (0, p_pad - start_pos)))
        lengths = jnp.full((b,), start_pos, jnp.int32)
        last_logits, caches = prefiller(params, tokens, lengths,
                                        decode_len=length)
        first_mask = None
        if logit_mask is not None and start_pos < length:
            first_mask = logit_mask[:, start_pos]
        seq, key, zcount = start_state(
            key, prime, last_logits, length, start_pos, top_k, temperature,
            first_mask)

        n_chunks = 0
        pos = start_pos
        while pos < length:
            seq, caches, key, zcount, done = decode_chunk(
                params, seq, caches, key, zcount, pos, length, start_pos,
                top_k, temperature, logit_mask)
            n_chunks += 1
            pos += chunk_size
            if bool(done):
                break
        sample.last_num_chunks = n_chunks
        return truncate_after_eos(seq)

    sample.last_num_chunks = 0
    sample.chunk_size = chunk_size
    return sample


def teacher_forced_logits(config: ProGenConfig, params, tokens,
                          policy: Policy | None = None):
    """Run the cached decode step over a FIXED token sequence and return all
    logits ``(B, L, V)`` — the decode-vs-parallel parity oracle (tests) and
    a scoring utility."""
    policy = policy or make_policy()
    step_model = ProGenDecodeStep(config=config, policy=policy)
    b, n = tokens.shape
    caches = init_caches(config, b, policy, decode_len=n)

    def body(caches, pos):
        tok = jax.lax.dynamic_index_in_dim(tokens, pos, axis=1, keepdims=False)
        logits, caches = step_model.apply(params, tok, pos, caches)
        return caches, logits

    _, logits = jax.lax.scan(body, caches, jnp.arange(n))
    return jnp.transpose(logits, (1, 0, 2))
