"""Continuous-batching serving engine: slots, chunked decode, refill.

The batch-synchronous sampler (``decode/sampler.py``) is the wrong shape
for serving: every request in a batch waits for the slowest one, and a
new request waits for the whole batch to drain.  This engine serves a
request QUEUE through a fixed set of SLOTS (vLLM/Ragged-Paged-Attention
style, PAPERS.md), with all device programs compiled once:

* **slots** — a fixed-size batch of per-slot state (sequence row, decode
  caches, position, done flag, RNG key, top-k/temperature).  Slots are
  independent: the decode step takes a ``(S,)`` position VECTOR
  (``ProGenDecodeStep``), so slot 3 can be at position 900 while slot 4
  is at position 12;
* **chunked decode** — ``chunk_size`` single-token steps per device
  program (one compile; position/done are data, not shape).  Rows that
  finish mid-chunk stop advancing; the host sees the done-mask between
  chunks, so cost is bounded by emitted tokens plus at most one chunk of
  slack per row;
* **refill** — between chunks, finished slots are harvested (completion
  callbacks fire) and refilled from the queue via the one-pass parallel
  prefill (``decode/prefill.py``): queued primes are padded into a
  ``(S, P_pad)`` ragged batch (``P_pad`` bucketed to ``window ·
  2^k`` so admission compiles O(log) programs, then cached), prefilled
  in ONE forward, and scattered into the free slots while live slots'
  state rides through untouched.

Determinism: each request carries its own seed; a request's token
trajectory depends only on (params, prime, seed, sampling knobs), never
on which slot it lands in or what else is in flight — asserted by
``tests/test_serving.py``.

Mesh-aware: pass ``mesh``/``strategies``/``params_shardings`` and the
engine runs SPMD with params left in their training shardings and
tp-sharded caches (``_constrain_caches``), same as the samplers.

EOS convention: primes are served verbatim (no BOS prepend); generation
stops at the first sampled pad/EOS token (id 0) or after
``max_new_tokens``.  The reference's "second zero" truncation is a
sampler-level concern; a serving request's prime is explicit.

Disaggregated mode (``disagg=True``, docs/SERVING.md §6) splits the step
into an explicit PREFILL stage (a worker program per bucket producing
cache HANDLES into a bounded handoff queue, ``decode/handoff.py``) and a
DECODE stage that admits from the queue via a donating merge program —
decode chunks dispatch BEFORE the round's prefill, so a long prefill
never stalls in-flight decode.  Speculative mode (``spec=True``,
``decode/spec.py``) replaces the chunk's sequential target steps with
draft-propose/target-verify rounds whose output is token-identical to
plain decoding for any draft.

Robustness (docs/RESILIENCE.md): every serving phase runs behind a named
fault-injection point (``serve.submit`` / ``serve.admit`` /
``serve.prefill`` / ``serve.decode_chunk`` / ``serve.harvest`` /
``serve.page_alloc``, plus ``serve.handoff`` for the disaggregated merge
and ``serve.verify`` replacing ``serve.decode_chunk`` under speculative
decoding).  Because each phase is FUNCTIONAL — state in,
state out, ``self.state`` replaced only on success — a transient fault is
contained by re-running the failed dispatch in place; a fatal fault sheds
only the requests whose work was lost, as typed completions
(``FAILED_FAULT``) rather than exceptions.  Requests carry optional
deadlines (``deadline``/``ttl`` → ``SHED_DEADLINE``), admission is
bounded (``max_queue`` → ``SHED_QUEUE_FULL``), and the lifecycle is
crash-safe: ``snapshot()`` persists host-side request state only (prompt,
sampling params, seeds — never device caches), and ``restore()`` +
seed-determinism replays in-flight requests token-identically
(:func:`run_with_restarts`).
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import os
import time
from collections import deque
from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.observe import metrics as _metrics
from progen_tpu.observe import trace as _obs_trace
from progen_tpu.observe.robustness import RobustnessCounters
from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import RetryError, default_classifier
from progen_tpu.resilience.watchdog import Watchdog
from progen_tpu.decode.incremental import (
    ProGenDecodeStep,
    ProGenPagedDecodeStep,
    init_caches,
    init_gate_pool,
    init_gate_scale,
)
from progen_tpu.decode.paging import (
    DUMP_PAGE,
    NULL_PAGE,
    RESERVED_PAGES,
    PagePool,
    SlotPages,
    pages_for_span,
    prefix_key,
)
from progen_tpu.decode.handoff import Handle, HandoffQueue
from progen_tpu.decode.qos import QoSQueue
from progen_tpu.decode.prefill import (
    _constrain_caches,
    harvest_caches,
    harvest_gate_pages,
    make_embedder,
    pad_prime_length,
    prime_buckets,
    scatter_gate_rows,
)
from progen_tpu.decode.sampler import (
    gumbel_topk_sample_batched,
    split_keys_batched,
)
from progen_tpu.decode.spec import check_draft_config, spec_round
from progen_tpu.models.progen import ProGen, ProGenConfig

EOS_ID = 0

# typed Completion.status values — sheds are COMPLETIONS, not exceptions,
# so callbacks/benchmarks see every request exactly once either way
STATUS_OK = "ok"
SHED_QUEUE_FULL = "shed_queue_full"
SHED_DEADLINE = "shed_deadline"
FAILED_FAULT = "failed_fault"
DRAIN_TIMEOUT = "drain_timeout"

# consecutive rounds a phase may defer (fatal-fault containment) before
# the engine concludes the fault is permanent and gives up
_MAX_DEFER_STREAK = 16


def _host_fetch(tree):
    """Batched device→host fetch that also handles PROCESS-SPANNING
    arrays (tp-group engines, docs/SERVING.md §13): ``jax.device_get``
    refuses an array with non-addressable shards, but every host-read
    engine output is replicated across the group — the local shard IS
    the global value.  A non-replicated process-spanning leaf falls
    back to a collective re-gather, which is safe because every group
    member runs the same fetch at the same point in lockstep."""

    def _one(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            if x.sharding.is_fully_replicated:
                return np.asarray(x.addressable_data(0))
            from jax.experimental import multihost_utils

            return multihost_utils.process_allgather(x, tiled=True)
        return x

    return jax.device_get(jax.tree_util.tree_map(_one, tree))


class _ContainedFault(Exception):
    """Internal: a phase failed NON-transiently; the caller sheds the
    affected requests per its containment rule.  ``__cause__`` is the
    underlying fault."""

    def __init__(self, point: str):
        super().__init__(f"non-transient fault at {point}")
        self.point = point


@dataclasses.dataclass
class Request:
    """One generation request.

    ``tokens``: the prime, served verbatim (encode + add BOS upstream if
    desired); must be non-empty and leave room for at least one new
    token.  ``top_k=None`` disables top-k; ``temperature=0`` is greedy.

    SLO knobs: ``deadline`` is an absolute ``time.perf_counter()``
    instant, ``ttl`` a budget in seconds from ``submit_time``
    (``deadline`` wins when both are set).  Past it the request is shed
    with a ``SHED_DEADLINE`` completion — queued requests before they
    cost a prefill, in-flight ones mid-decode with their partial tokens.

    Workload knobs: ``logit_mask`` is an optional ``(G, V)`` bool array
    (``G ≤ max_new_tokens``) constraining generated position ``g`` to
    its true entries (``workloads/infill.ScaffoldSpec`` builds these;
    positions past ``G`` are unconstrained); ``tenant`` selects a row of
    the engine's LoRA adapter bank (0 = base model; nonzero requires the
    engine to hold a bank).

    QoS knobs (docs/SERVING.md §10): ``priority`` picks the scheduling
    class (higher = more urgent; classes are served strictly in order
    and a high-priority arrival may PREEMPT a lower-priority in-flight
    request — the replay is bit-exact); within a class, tenants share
    by weight (``qos_weights``) and deadlines order EDF.
    """

    uid: Any
    tokens: Sequence[int]
    max_new_tokens: int = 128
    top_k: int | None = None
    temperature: float = 1.0
    seed: int = 0
    deadline: float | None = None
    ttl: float | None = None
    on_complete: Callable[["Completion"], None] | None = None
    submit_time: float = dataclasses.field(default_factory=time.perf_counter)
    logit_mask: Any = None
    tenant: int = 0
    # request class for routing ("generate" | "embed") — the cluster
    # frontend sets "embed" via submit_embed(); in-process callers use
    # the engine's submit()/submit_embed() methods directly
    workload: str = "generate"
    priority: int = 0


@dataclasses.dataclass
class Completion:
    """A finished request: ``tokens`` is the generated tail only (EOS
    included when the model emitted one).

    ``status`` is ``STATUS_OK`` for served requests (``finish_reason`` is
    ``"eos"``/``"length"``) or a shed type (``SHED_QUEUE_FULL`` /
    ``SHED_DEADLINE`` / ``FAILED_FAULT``, mirrored into
    ``finish_reason``) — load shedding produces a COMPLETION, never an
    exception, so every submitted request is answered exactly once.
    """

    uid: Any
    prime: np.ndarray
    tokens: np.ndarray
    finish_reason: str  # "eos" | "length" | "embed" | shed status
    submit_time: float
    finish_time: float
    status: str = STATUS_OK
    embedding: np.ndarray | None = None  # (D,) f32 for embed requests
    # weight generation that primed the request — the serving control
    # plane bumps this on swap_weights; 0 for a never-swapped engine
    generation: int = 0
    # instant the request's FIRST generated token existed (admission
    # dispatch returned) — None for sheds and embed completions.  The
    # cluster rewrites this onto the driver clock so ``ttft`` is
    # end-to-end (queue + prefill + transport + merge) fleet-wide.
    first_token_time: float | None = None
    # latency as measured on the WORKER's clock (submit→finish inside
    # the remote engine); 0.0 for local completions, where ``latency``
    # already is that number.  The difference vs ``latency`` is the
    # transport + merge overhead the fleet adds on top of the engine.
    worker_latency: float = 0.0

    @property
    def latency(self) -> float:
        return self.finish_time - self.submit_time

    @property
    def ttft(self) -> float | None:
        """Time to first token, or None when it was never produced."""
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time

    @property
    def ok(self) -> bool:
        return self.status == STATUS_OK


class ServingEngine:
    """Slot-based continuous-batching engine over a fixed device batch.

    ``num_slots`` is the max concurrent requests; ``chunk_size`` the
    decode steps per device program; ``max_len`` the sequence budget per
    slot (prime + generated, ≤ ``config.seq_len``).

    **Paged mode** (``paged=True``): the per-slot SGU gate cache — the
    one ``max_len``-sized buffer, i.e. this architecture's pageable "KV"
    — moves into a global page pool (``decode/paging.py``): pages are
    allocated on demand as positions advance, freed (refcounted) at
    completion, and shared across requests with a common prompt prefix.
    Admission is gated by free PAGES as well as free slots; when the pool
    runs dry mid-decode, starved slots are PAUSED (their rows freeze —
    position, key and sequence do not advance, so the trajectory is
    delayed, never altered) and, if every live slot is starved, the most
    recently admitted one is evicted back to the queue head (restart
    preemption: determinism means replaying it reproduces the identical
    prefix of tokens).  Greedy outputs are token-for-token identical to
    the fixed-slot engine — the XLA fallback contraction is bit-matched
    to the dense decode path (``ops/pallas_paged_attention.py``).

    ``num_pages`` counts pool pages incl. the 2 reserved ones (default:
    full budget — every slot can reach ``max_len``); ``paged_impl`` picks
    the ragged kernel (``"pallas"``) or the gather fallback (``"xla"``).

    Robustness knobs: ``max_queue`` bounds admission (``None`` =
    unbounded; overflow sheds the incoming request, or the OLDEST queued
    one under ``shed_policy="shed-oldest"``); ``fault_retries`` is the
    in-place retries per phase for transient faults (exhaustion escapes
    as :class:`RetryError` for the restart-and-replay loop);
    ``watchdog`` receives a heartbeat per ``step()`` and is paused around
    first-time compiles.  Counters live in ``self.robust``
    (:func:`robustness_counters` merges everything).

    QoS knobs (docs/SERVING.md §10): admission runs through a
    priority / weighted-fair / EDF scheduling queue
    (``decode/qos.py``) — ``qos_weights`` maps tenant -> relative share
    (missing tenants weigh 1.0; nonzero-weight tenants are
    starvation-free).  A high-priority arrival blocked on slots or
    pages PREEMPTS the lowest-priority in-flight request
    (:meth:`_maybe_preempt`): the victim replays from scratch
    bit-exactly, so preemption trades latency, never tokens.  Under
    ``shed_policy="shed-oldest"`` the victim is the lowest class's
    oldest request, never a strictly higher class than the newcomer.

    **Speculative decoding** (``spec=True``): a draft model
    (``draft_config``/``draft_params``; defaults to the IDENTITY draft —
    the target itself, 100% acceptance) proposes ``spec_k`` tokens per
    round, verified in one fused target scan (``decode/spec.py``).
    Output is token-identical to plain decoding for ANY draft — greedy
    and sampled alike — so per-request seed determinism and
    snapshot/replay survive unchanged.  ``draft_config`` without
    ``draft_params`` random-initializes the draft (testing convenience;
    a real deployment loads a trained draft).

    **Disaggregated serving** (``disagg=True``): prefill runs as its own
    worker program over FIFO-prefix batches of up to ``prefill_batch``
    requests sharing a bucket, producing cache handles into a bounded
    queue of ``handoff_depth`` (``decode/handoff.py``); the decode stage
    admits by merging handles into free slots with the handle DONATED
    (caches move, not copy).  ``step()`` dispatches the decode chunk
    before the round's prefill, so long prefills stop stalling in-flight
    decode.
    """

    def __init__(self, config: ProGenConfig, params, *,
                 policy: Policy | None = None, num_slots: int = 8,
                 chunk_size: int = 32, max_len: int | None = None,
                 mesh: Mesh | None = None,
                 strategies: Sequence[str] = ("dp",),
                 params_shardings=None,
                 paged: bool = False, page_size: int = 16,
                 num_pages: int | None = None, paged_impl: str = "xla",
                 prefix_cache: bool = True,
                 max_queue: int | None = None, shed_policy: str = "reject",
                 fault_retries: int = 3, watchdog: Watchdog | None = None,
                 spec: bool = False, draft_config: ProGenConfig | None = None,
                 draft_params=None, spec_k: int = 4,
                 disagg: bool = False, prefill_batch: int | None = None,
                 handoff_depth: int = 2, remote_prefill: bool = False,
                 lora_bank=None, qos_weights: dict | None = None,
                 quantize: str | None = None):
        self.config = config
        self.policy = policy or make_policy()
        self.num_slots = num_slots
        self.chunk_size = chunk_size
        self.max_len = min(max_len or config.seq_len, config.seq_len)
        self.mesh = mesh
        self.strategies = tuple(strategies)
        # priority / weighted-fair / EDF scheduling queue — with default
        # weights, a single tenant and no deadlines it is exact FIFO
        self._queue = QoSQueue(weights=qos_weights)
        self.qos_weights = dict(qos_weights or {})
        self._qos_gauge_keys: set = set()
        self._inflight: dict[int, Request] = {}  # slot -> request
        # shared-prefix forking (submit_fork): leader uid -> followers
        # held back until the leader's prefix pages are published, plus
        # first-token instants for the TTFT field on completions
        self._fork_wait: dict[Any, list[Request]] = {}
        self.fork_groups = 0
        self._ttft: dict[Any, float] = {}
        # admission recency (slot -> monotone seq) across ALL modes: the
        # preemption and pool-starvation paths evict youngest-first
        self._admit_seq = 0
        self._admit_order: dict[int, int] = {}
        self.completions: list[Completion] = []
        self.chunks_run = 0
        if shed_policy not in ("reject", "shed-oldest"):
            raise ValueError(f"shed_policy {shed_policy!r}: want 'reject' "
                             f"or 'shed-oldest'")
        self.max_queue = max_queue
        self.shed_policy = shed_policy
        self.fault_retries = fault_retries
        self._watchdog = watchdog
        self.robust = RobustnessCounters()
        self._pending: list[Completion] = []   # sheds awaiting step() return
        self._draining = False
        self._aot: dict[tuple, Any] = {}       # AOT-compiled executables
        self._compiled_keys: set[tuple] = set()
        self._defer_streak: dict[str, int] = {}
        # dispatch wall per stage (perf_counter deltas around the guarded
        # device calls) — multi-process bench records prove prefill wall
        # LEAVES the decode process (its prefill_s stays 0.0)
        self.stage_seconds = {"prefill_s": 0.0, "merge_s": 0.0,
                              "decode_chunk_s": 0.0, "embed_s": 0.0}
        # the same deltas feed the process tracer (no-op unless enabled)
        # and the shared metrics registry's per-stage latency histograms
        self._tracer = _obs_trace.get_tracer()
        registry = _metrics.get_registry()
        self._stage_hist = {
            "prefill_s": registry.histogram("engine.prefill_s"),
            "merge_s": registry.histogram("engine.merge_s"),
            "decode_chunk_s": registry.histogram("engine.decode_chunk_s"),
            "embed_s": registry.histogram("engine.embed_s"),
        }

        if params_shardings is not None:
            params = jax.device_put(params, {"params": params_shardings})

        # opt-in quantized serving: "weights" re-types every dense kernel
        # and SGU spatial weight to int8 (f32 scales in a parallel
        # "qscale" collection); "weights+pages" additionally stores the
        # paged SGU gate cache as 8-bit pages.  None (default) is the
        # unchanged bit-gated full-precision engine.
        if quantize not in (None, "weights", "weights+pages"):
            raise ValueError(f"quantize {quantize!r}: want None, "
                             f"'weights' or 'weights+pages'")
        if quantize == "weights+pages" and not paged:
            raise ValueError("quantize='weights+pages' requires paged=True "
                             "(the 8-bit gate format is a page format)")
        self.quantize = quantize
        self._weights_mode = "int8" if quantize else "bf16"
        self.gate_dtype = "int8" if quantize == "weights+pages" else "bf16"
        if quantize:
            params = self._quantize_variables(params)

        self.spec = spec
        self.disagg = disagg
        self.lora = lora_bank is not None
        if self.lora:
            # composition bounds: the adapter gather composes with dense,
            # paged, and disaggregated decode (the handle carries a
            # ``tenant`` leaf in its state tree); the spec draft/commit
            # scans do not carry tenant state (yet)
            if spec:
                raise ValueError("lora_bank does not compose with spec=True")
            from progen_tpu.workloads.lora import validate_lora_bank

            self.num_tenants = validate_lora_bank(config, lora_bank)
            lora_bank = jax.tree.map(jnp.asarray, lora_bank)
        else:
            self.num_tenants = 1
        if spec:
            if spec_k < 1:
                raise ValueError(f"spec_k must be >= 1, got {spec_k}")
            self.spec_k = int(spec_k)
            self.draft_config = draft_config or config
            check_draft_config(config, self.draft_config)
            # an identity draft shares the (possibly quantized) target
            # params, so its models must match the target's weight mode;
            # an explicit draft stays full precision
            identity_draft = draft_params is None and draft_config is None
            draft_weights = self._weights_mode if identity_draft else "bf16"
            if draft_params is None:
                if draft_config is None:
                    draft_params = params  # identity draft
                else:
                    from progen_tpu.parallel import unbox

                    toks = jnp.zeros((1, self.draft_config.seq_len),
                                     jnp.int32)
                    draft_params = unbox(jax.jit(ProGen(
                        config=self.draft_config,
                        policy=self.policy).init)(jax.random.key(0), toks))
            # rounds per dispatch: a fully-accepted round advances k+1
            # positions, so the chunk budget is kept in emitted tokens
            self._spec_rounds = max(1, chunk_size // (self.spec_k + 1))
            self._max_advance = self._spec_rounds * (self.spec_k + 1)
            self._draft_step_model = ProGenDecodeStep(
                config=self.draft_config, policy=self.policy,
                weights=draft_weights)
            self._draft_prefill_model = ProGen(config=self.draft_config,
                                               policy=self.policy,
                                               weights=draft_weights)
            self._spec_emitted = jnp.zeros((), jnp.int32)
            self._spec_verify_rounds = jnp.zeros((), jnp.int32)
            self._params = {"target": params, "draft": draft_params}
        elif self.lora:
            self._max_advance = chunk_size
            # the bank rides the params pytree so every AOT program takes
            # it as a real argument (hot-swappable without recompiles)
            self._params = {"base": params, "adapters": lora_bank}
        else:
            self._max_advance = chunk_size
            self._params = params
        # weight generation: bumped by reload_weights(); completions are
        # stamped with the generation current when they finish (in the
        # multi-process cluster the driver stamps from router bookkeeping
        # instead — a uid's generation is the one that PRIMED it)
        self.generation = 0

        if mesh is not None:
            from progen_tpu.parallel.sharding import logical_rules

            rules = logical_rules(self.strategies)

            def trace_ctx():
                stack = contextlib.ExitStack()
                stack.enter_context(mesh)
                stack.enter_context(nn.logical_axis_rules(rules))
                return stack
        else:
            trace_ctx = contextlib.ExitStack
        self._trace_ctx = trace_ctx

        self.paged = paged
        self.paged_impl = paged_impl if paged else None
        if paged:
            self.page_size = page_size
            self.pages_per_row = -(-self.max_len // page_size)
            if num_pages is None:
                num_pages = RESERVED_PAGES + num_slots * self.pages_per_row
            self._pool = PagePool(num_pages, page_size,
                                  prefix_caching=prefix_cache,
                                  gate_dtype=self.gate_dtype)
            self._slot_pages: dict[int, SlotPages] = {}
            self._page_table = np.zeros((num_slots, self.pages_per_row),
                                        np.int32)
            self._paused = np.zeros((num_slots,), bool)
            self._host_stop = np.zeros((num_slots,), np.int64)
            self.evictions = 0
            self.pause_events = 0
            self.prefix_hits = 0
            self.prefix_lookups = 0
            self._paged_step_model = ProGenPagedDecodeStep(
                config=config, n_rows=self.max_len, policy=self.policy,
                impl=paged_impl, weights=self._weights_mode,
                gate_dtype=self.gate_dtype)
            self._decode_chunk = jax.jit(
                self._decode_chunk_spec_paged_impl if spec
                else self._decode_chunk_paged_impl)
            self._admit = jax.jit(self._admit_paged_impl)
        else:
            self._step_model = ProGenDecodeStep(config=config,
                                                policy=self.policy,
                                                weights=self._weights_mode)
            self._decode_chunk = jax.jit(
                self._decode_chunk_spec_impl if spec
                else self._decode_chunk_impl)
            self._admit = jax.jit(self._admit_impl)
        self._prefill_model = ProGen(config=config, policy=self.policy,
                                     weights=self._weights_mode)
        if remote_prefill and not disagg:
            raise ValueError("remote_prefill requires disagg=True")
        self.remote_prefill = remote_prefill
        if disagg:
            self.prefill_batch = max(1, min(prefill_batch or num_slots,
                                            num_slots))
            self._handoff = HandoffQueue(handoff_depth)
            self._prefill_worker = jax.jit(self._prefill_worker_impl)
            # the handle is donated: its cache buffers are dead after the
            # merge, so XLA may move them into the slot state
            self._merge = jax.jit(self._merge_impl, donate_argnums=(1,))
        else:
            self._handoff = None
        # embeddings endpoint: a separate request class served by a
        # prefill-shaped program — consumes no decode slots, batches per
        # prime bucket, AOT-warmable like admission
        self._embed_queue: deque[Request] = deque()
        self.embed_batch = num_slots
        self._embedder = make_embedder(config, self.policy, mesh=mesh,
                                       strategies=self.strategies,
                                       weights=self._weights_mode)
        self.state = self._init_state()

    # ---------------------------------------------------------------- state

    def _init_state(self) -> dict:
        s, L = self.num_slots, self.max_len
        with self._trace_ctx():
            caches = init_caches(self.config, s, self.policy, decode_len=L,
                                 with_sgu=not self.paged)
            if self.paged:
                caches.pop("sgu_gate")
                caches["sgu_pool"] = init_gate_pool(
                    self.config, self._pool.num_pages, self.page_size,
                    self.policy, gate_dtype=self.gate_dtype)
                if self.gate_dtype == "int8":
                    caches["sgu_pool_scale"] = init_gate_scale(
                        self.config, self._pool.num_pages, self.page_size)
            if self.mesh is not None:
                caches = _constrain_caches(caches, self.mesh, self.strategies)
        keys = jax.vmap(jax.random.key)(jnp.zeros((s,), jnp.uint32))
        state = {
            "seq": jnp.zeros((s, L), jnp.int32),
            "caches": caches,
            "pos": jnp.zeros((s,), jnp.int32),     # index of newest token
            "start": jnp.zeros((s,), jnp.int32),   # prime length
            "stop": jnp.zeros((s,), jnp.int32),    # start + max_new (≤ L)
            "active": jnp.zeros((s,), bool),
            "done": jnp.zeros((s,), bool),
            "keys": jax.random.key_data(keys),     # raw uint32 key data
            "top_k": jnp.zeros((s,), jnp.int32),   # 0 = disabled
            "temp": jnp.ones((s,), jnp.float32),
            # per-slot per-position logit mask, indexed by WRITE position;
            # all-true rows are bit-identical to no masking at all, so the
            # plain generate path pays only the (S, L, V)-bool gather
            "lmask": jnp.ones((s, L, self.config.num_tokens), bool),
        }
        if self.lora:
            state["tenant"] = jnp.zeros((s,), jnp.int32)
        if self.spec:
            # the draft's caches stay DENSE per slot even in paged mode:
            # the draft is tiny, paging its rows would buy nothing
            state["draft_caches"] = init_caches(
                self.draft_config, s, self.policy, decode_len=L)
        return state

    # ------------------------------------------------------ fault containment

    def _note_stage(self, stage: str, span: str, t0: float, **args) -> None:
        """Fold one guarded device dispatch into every observability
        surface at once: ``stage_seconds`` (the legacy per-stage wall),
        the shared metrics histogram, and the trace ring (a no-op span
        unless tracing is enabled)."""
        dt = time.perf_counter() - t0
        self.stage_seconds[stage] += dt
        self._stage_hist[stage].observe(dt)
        self._tracer.add(span, t0, dt, **args)

    def _guard(self, point: str, fn: Callable | None = None, *args,
               key: tuple | None = None):
        """Run ``faults.inject(point)`` + ``fn(*args)`` with transient
        faults retried in place (no backoff — the retried work is an
        in-process dispatch of a pure function, so re-running it is both
        safe and deterministic).  Non-transient faults raise
        :class:`_ContainedFault` for the caller's shed rule; transient
        exhaustion raises :class:`RetryError`, the signal the
        restart-and-replay loop (:func:`run_with_restarts`) catches.

        ``key`` names the compiled program ``fn`` dispatches: its first
        run pauses the watchdog (cold compiles are legitimately slow).
        """
        last: BaseException | None = None
        for attempt in range(max(0, self.fault_retries) + 1):
            try:
                faults.inject(point)
                if fn is None:
                    out = None
                elif (self._watchdog is not None and key is not None
                        and key not in self._compiled_keys):
                    with self._watchdog.paused():
                        out = fn(*args)
                else:
                    out = fn(*args)
                if key is not None:
                    self._compiled_keys.add(key)
                if attempt:
                    self.robust.faults_contained += attempt
                return out
            except Exception as e:
                if not default_classifier(e):
                    raise _ContainedFault(point) from e
                last = e
        raise RetryError(
            f"{point}: transient fault persisted through "
            f"{max(0, self.fault_retries) + 1} attempt(s)",
            attempts=max(0, self.fault_retries) + 1, elapsed=0.0,
        ) from last

    def _defer(self, phase: str, cause: BaseException) -> None:
        """Record one deferred round of ``phase`` (fatal-fault
        containment: skip the phase this step, retry next step).  A
        streak past ``_MAX_DEFER_STREAK`` means the fault is permanent —
        give up loudly instead of spinning."""
        streak = self._defer_streak.get(phase, 0) + 1
        self._defer_streak[phase] = streak
        if streak > _MAX_DEFER_STREAK:
            raise RuntimeError(
                f"serve.{phase} failed {streak} consecutive rounds — "
                f"fault is not transient and not shedding") from cause

    def _admit_call(self, *args):
        """Dispatch the admission (prefill) program: the AOT executable
        for this prefill bucket when warmed, the jit wrapper otherwise."""
        fn = self._aot.get(("admit", args[0].shape[1]), self._admit)
        return fn(self._params, self.state, *args)

    def _chunk_call(self, *args):
        fn = self._aot.get(("chunk",), self._decode_chunk)
        return fn(self._params, self.state, *args)

    def _embed_call(self, tokens, lengths):
        """Dispatch the embedding program for this prefill bucket (AOT
        executable when warmed).  Embeddings always run the BASE model —
        no sampling, no adapters, no slot state."""
        fn = self._aot.get(("embed", tokens.shape[1]), self._embedder)
        return fn(self._target_params(self._params), tokens, lengths)

    def _target_params(self, params):
        """Under speculative decoding ``self._params`` bundles target and
        draft weights; under LoRA it bundles the base tree and the
        adapter bank; plain serving passes the target tree through."""
        if self.spec:
            return params["target"]
        if self.lora:
            return params["base"]
        return params

    def _adapters(self, params):
        """The stacked adapter bank when serving LoRA, else ``None`` (the
        model applies no delta and traces exactly as before)."""
        return params["adapters"] if self.lora else None

    @staticmethod
    def _quantize_variables(variables):
        """Re-type a full-precision variables dict for int8 serving:
        dense kernels and SGU spatial weights become int8 leaves (same
        tree structure, so shardings and AOT shapes carry over) and the
        per-channel f32 scales ride in a parallel ``qscale`` collection.
        LoRA adapter banks are NOT quantized — deltas stay full precision
        on top of the int8 base."""
        from progen_tpu.ops.quant import quantize_params

        qtree, scales = quantize_params(variables["params"])
        return {**variables, "params": qtree, "qscale": scales}

    def _activate_xla_fallback(self) -> None:
        """Degrade the paged decode step from the Pallas ragged kernel to
        its bit-identical XLA gather fallback (``ops/
        pallas_paged_attention.py``) — counted and logged, never fatal.
        Token streams are unaffected: the two impls are numerically
        matched, which is exactly why the fallback is safe mid-request.
        """
        self.robust.fallback_activations += 1
        self.paged_impl = "xla"
        self._paged_step_model = ProGenPagedDecodeStep(
            config=self.config, n_rows=self.max_len, policy=self.policy,
            impl="xla", weights=self._weights_mode,
            gate_dtype=self.gate_dtype)
        self._decode_chunk = jax.jit(
            self._decode_chunk_spec_paged_impl if self.spec
            else self._decode_chunk_paged_impl)
        self._aot.pop(("chunk",), None)
        self._compiled_keys.discard(("chunk",))
        print("serving: pallas paged kernel failed; degraded to the "
              "bit-identical XLA fallback", flush=True)

    # ------------------------------------------------------------- decoding

    def _decode_chunk_impl(self, params, state):
        cfg = self.config

        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def body(st, _):
                live = st["active"] & ~st["done"]
                pos = st["pos"]
                tok = jnp.take_along_axis(st["seq"], pos[:, None],
                                          axis=1)[:, 0]
                logits, caches = self._step_model.apply(
                    self._target_params(params), tok, pos, st["caches"],
                    self._adapters(params), st.get("tenant"))
                kd, sub = split_keys_batched(st["keys"])
                writepos = jnp.clip(pos + 1, 0, self.max_len - 1)
                # the infill mask row for the position this step WRITES;
                # all-pass rows leave sampling bit-identical
                mrow = jnp.take_along_axis(
                    st["lmask"], writepos[:, None, None], axis=1)[:, 0]
                nxt = gumbel_topk_sample_batched(
                    sub, logits, st["top_k"], st["temp"],
                    mask=mrow).astype(jnp.int32)
                cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                          axis=1)[:, 0]
                val = jnp.where(live, nxt, cur)
                seq = st["seq"].at[
                    jnp.arange(self.num_slots), writepos].set(val)
                new_pos = jnp.where(live, pos + 1, pos)
                done = st["done"] | (live & (
                    (val == EOS_ID) | (new_pos + 1 >= st["stop"])))
                # a slot's key advances only on its own live steps, so a
                # request's trajectory is independent of its neighbours
                new_keys = jnp.where(live[:, None], kd, st["keys"])
                return {**st, "seq": seq, "caches": caches, "pos": new_pos,
                        "done": done, "keys": new_keys}, None

            state, _ = jax.lax.scan(body, state, None,
                                    length=self.chunk_size)
        return state

    def _admit_impl(self, params, state, tokens, lengths, stops, seeds,
                    top_k, temp, mask, lmask, tenant=None):
        """Prefill ``tokens (S, P_pad)`` in one parallel forward and merge
        rows where ``mask`` into ``state`` (rows outside ``mask`` carry
        dummy primes and are discarded).  ``lmask (S, L, V)`` is each
        row's infill logit mask indexed by write position (all-true for
        unconstrained requests); ``tenant (S,)`` rides only under LoRA."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                self._target_params(params), tokens,
                self._adapters(params), tenant, mutable=["cache"])
            caches_new = harvest_caches(cfg, varz["cache"], lengths,
                                        self.policy, self.max_len)
            if self.mesh is not None:
                caches_new = _constrain_caches(caches_new, self.mesh,
                                               self.strategies)
            if self.spec:
                _, dvarz = self._draft_prefill_model.apply(
                    params["draft"], tokens, mutable=["cache"])
                draft_new = harvest_caches(
                    self.draft_config, dvarz["cache"], lengths,
                    self.policy, self.max_len)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        # the first generated token writes at position ``lengths`` — its
        # mask row applies here, not in the decode chunk
        first_mrow = jnp.take_along_axis(
            lmask, lengths[:, None, None], axis=1)[:, 0]
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp,
            mask=first_mrow).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        # p_pad is window-aligned and may overshoot L; real tokens never do
        # (submit enforces prime + 1 <= max_len), so truncation drops pad only
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        pos = lengths
        done = (first == EOS_ID) | (pos + 1 >= stops)

        def merge(new, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        merged_caches = jax.tree.map(merge, caches_new, state["caches"])
        out = {
            "seq": merge(seq, state["seq"]),
            "caches": merged_caches,
            "pos": merge(pos, state["pos"]),
            "start": merge(lengths, state["start"]),
            "stop": merge(stops, state["stop"]),
            "active": merge(jnp.ones((s,), bool), state["active"]),
            "done": merge(done, state["done"]),
            "keys": merge(jax.random.key_data(split[:, 0]), state["keys"]),
            "top_k": merge(top_k, state["top_k"]),
            "temp": merge(temp, state["temp"]),
            "lmask": merge(lmask, state["lmask"]),
        }
        if self.lora:
            out["tenant"] = merge(tenant, state["tenant"])
        if self.spec:
            out["draft_caches"] = jax.tree.map(
                merge, draft_new, state["draft_caches"])
        return out

    # -------------------------------------------------------- paged decoding

    _RING_KEYS = ("attn_prev", "ff_prev", "k", "v")

    def _decode_chunk_paged_impl(self, params, state, table, paused):
        """Paged twin of ``_decode_chunk_impl``: the page ``table`` and
        ``paused`` mask ride in as data (host-side allocation decisions
        never retrace the program).  Paused rows run the step but are
        fully masked — sequence/position/key freeze AND their ring/carry
        writes are dropped (a paused row's carries still hold position
        ``pos-1``'s activations; letting the discarded speculative step
        overwrite them would corrupt the real step after unpausing).
        Pool writes are masked inside the step via ``write_ok``."""
        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def body(st, _):
                live = st["active"] & ~st["done"] & ~paused
                pos = st["pos"]
                tok = jnp.take_along_axis(st["seq"], pos[:, None],
                                          axis=1)[:, 0]
                logits, caches = self._paged_step_model.apply(
                    self._target_params(params), tok, pos, st["caches"],
                    table, live, self._adapters(params), st.get("tenant"))

                def mrg(new, old):
                    m = live.reshape((-1,) + (1,) * (old.ndim - 1))
                    return jnp.where(m, new, old)

                caches = {
                    **{k: jax.tree.map(mrg, caches[k], st["caches"][k])
                       for k in self._RING_KEYS},
                    "sgu_pool": caches["sgu_pool"],
                    # 8-bit gate pages carry a per-row scale pool whose
                    # writes are masked inside the step, like the pool's
                    **({"sgu_pool_scale": caches["sgu_pool_scale"]}
                       if "sgu_pool_scale" in caches else {}),
                }
                kd, sub = split_keys_batched(st["keys"])
                writepos = jnp.clip(pos + 1, 0, self.max_len - 1)
                mrow = jnp.take_along_axis(
                    st["lmask"], writepos[:, None, None], axis=1)[:, 0]
                nxt = gumbel_topk_sample_batched(
                    sub, logits, st["top_k"], st["temp"],
                    mask=mrow).astype(jnp.int32)
                cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                          axis=1)[:, 0]
                val = jnp.where(live, nxt, cur)
                seq = st["seq"].at[
                    jnp.arange(self.num_slots), writepos].set(val)
                new_pos = jnp.where(live, pos + 1, pos)
                done = st["done"] | (live & (
                    (val == EOS_ID) | (new_pos + 1 >= st["stop"])))
                # key advances only on the slot's own live steps (see the
                # dense body) — pausing therefore delays, never alters
                new_keys = jnp.where(live[:, None], kd, st["keys"])
                return {**st, "seq": seq, "caches": caches, "pos": new_pos,
                        "done": done, "keys": new_keys}, None

            state, _ = jax.lax.scan(body, state, None,
                                    length=self.chunk_size)
        return state

    def _admit_paged_impl(self, params, state, tokens, lengths, stops,
                          seeds, top_k, temp, mask, lmask, table, wtable,
                          tenant=None):
        """Paged twin of ``_admit_impl``: rings/carries harvest and merge
        as in the dense path, but gate rows scatter straight into the
        page pool through the WRITE table (``wtable`` — private pages
        only; prefix-shared and dummy rows dump)."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                self._target_params(params), tokens,
                self._adapters(params), tenant, mutable=["cache"])
            caches_new = harvest_caches(cfg, varz["cache"], lengths,
                                        self.policy, self.max_len,
                                        with_sgu=False)
            if self.gate_dtype == "int8":
                pool_new, pscale_new = harvest_gate_pages(
                    cfg, varz["cache"], lengths,
                    state["caches"]["sgu_pool"], wtable, self.policy,
                    pool_scale=state["caches"]["sgu_pool_scale"])
            else:
                pool_new = harvest_gate_pages(
                    cfg, varz["cache"], lengths,
                    state["caches"]["sgu_pool"], wtable, self.policy)
            if self.mesh is not None:
                caches_new = _constrain_caches(caches_new, self.mesh,
                                               self.strategies)
            if self.spec:
                # draft caches stay dense even in paged mode — the draft
                # is small enough that paging it would buy nothing
                _, dvarz = self._draft_prefill_model.apply(
                    params["draft"], tokens, mutable=["cache"])
                draft_new = harvest_caches(
                    self.draft_config, dvarz["cache"], lengths,
                    self.policy, self.max_len)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        first_mrow = jnp.take_along_axis(
            lmask, lengths[:, None, None], axis=1)[:, 0]
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp,
            mask=first_mrow).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        pos = lengths
        done = (first == EOS_ID) | (pos + 1 >= stops)

        def merge(new, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        merged_caches = {
            **{k: jax.tree.map(merge, caches_new[k], state["caches"][k])
               for k in self._RING_KEYS},
            "sgu_pool": pool_new,
            **({"sgu_pool_scale": pscale_new}
               if self.gate_dtype == "int8" else {}),
        }
        out = {
            "seq": merge(seq, state["seq"]),
            "caches": merged_caches,
            "pos": merge(pos, state["pos"]),
            "start": merge(lengths, state["start"]),
            "stop": merge(stops, state["stop"]),
            "active": merge(jnp.ones((s,), bool), state["active"]),
            "done": merge(done, state["done"]),
            "keys": merge(jax.random.key_data(split[:, 0]), state["keys"]),
            "top_k": merge(top_k, state["top_k"]),
            "temp": merge(temp, state["temp"]),
            "lmask": merge(lmask, state["lmask"]),
        }
        if self.lora:
            out["tenant"] = merge(tenant, state["tenant"])
        if self.spec:
            out["draft_caches"] = jax.tree.map(
                merge, draft_new, state["draft_caches"])
        return out

    # --------------------------------------------------- speculative decoding

    def _decode_chunk_spec_impl(self, params, state):
        """Speculative twin of ``_decode_chunk_impl``: the chunk becomes
        ``_spec_rounds`` propose/verify/commit rounds (``decode/spec.py``)
        instead of ``chunk_size`` single-token target steps.  Returns
        ``(state, stats)``; emitted-token and verify-round counts stay on
        device (``spec_counters`` reads them off the hot path)."""
        tgt, drf = params["target"], params["draft"]
        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def target_step(tok, pos, caches, live):
                del live  # dense writes roll back via merge_caches
                return self._step_model.apply(tgt, tok, pos, caches)

            def draft_step(tok, pos, dc):
                return self._draft_step_model.apply(drf, tok, pos, dc)

            def merge_caches(live, new, old):
                def mrg(n, o):
                    m = live.reshape((-1,) + (1,) * (o.ndim - 1))
                    return jnp.where(m, n, o)
                return jax.tree.map(mrg, new, old)

            emitted = jnp.zeros((), jnp.int32)
            rounds = jnp.zeros((), jnp.int32)
            for _ in range(self._spec_rounds):
                live0 = state["active"] & ~state["done"]
                state, em = spec_round(
                    state, spec_k=self.spec_k, max_len=self.max_len,
                    eos_id=EOS_ID, target_step=target_step,
                    draft_step=draft_step, merge_caches=merge_caches,
                    live0=live0)
                emitted = emitted + jnp.sum(em)
                rounds = rounds + jnp.any(live0).astype(jnp.int32)
        return state, {"emitted": emitted, "rounds": rounds}

    def _decode_chunk_spec_paged_impl(self, params, state, table, paused):
        """Speculative + paged.  Pool writes are masked inside the step
        via ``write_ok=live`` (a live verify step consumes a token the
        round has already committed, so its pool write is final); only
        ring/carry keys need the live-mask rollback, exactly as in the
        plain paged chunk body."""
        tgt, drf = params["target"], params["draft"]
        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def target_step(tok, pos, caches, live):
                return self._paged_step_model.apply(
                    tgt, tok, pos, caches, table, live)

            def draft_step(tok, pos, dc):
                return self._draft_step_model.apply(drf, tok, pos, dc)

            def merge_caches(live, new, old):
                def mrg(n, o):
                    m = live.reshape((-1,) + (1,) * (o.ndim - 1))
                    return jnp.where(m, n, o)
                return {
                    **{k: jax.tree.map(mrg, new[k], old[k])
                       for k in self._RING_KEYS},
                    "sgu_pool": new["sgu_pool"],
                    **({"sgu_pool_scale": new["sgu_pool_scale"]}
                       if "sgu_pool_scale" in new else {}),
                }

            emitted = jnp.zeros((), jnp.int32)
            rounds = jnp.zeros((), jnp.int32)
            for _ in range(self._spec_rounds):
                live0 = state["active"] & ~state["done"] & ~paused
                state, em = spec_round(
                    state, spec_k=self.spec_k, max_len=self.max_len,
                    eos_id=EOS_ID, target_step=target_step,
                    draft_step=draft_step, merge_caches=merge_caches,
                    live0=live0)
                emitted = emitted + jnp.sum(em)
                rounds = rounds + jnp.any(live0).astype(jnp.int32)
        return state, {"emitted": emitted, "rounds": rounds}

    # ------------------------------------------------- disaggregated serving

    def _prefill_worker_impl(self, params, tokens, lengths, stops, seeds,
                             top_k, temp, lmask, tenant=None):
        """Prefill stage of disaggregated serving: same math as the admit
        impls but with NO slot state in scope — the product is a handle
        of ``(num_slots, ...)`` slabs the merge program later gathers
        into slots.  Gate rows stay dense here even in paged mode (the
        worker cannot know which pool pages the rows will land in; the
        merge scatters them through a row-indexed write table).
        ``tenant (S,)`` rides only under LoRA and travels in the handle
        state so the decode side keeps gathering the right adapter."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                self._target_params(params), tokens,
                self._adapters(params), tenant, mutable=["cache"])
            caches = harvest_caches(cfg, varz["cache"], lengths,
                                    self.policy, self.max_len)
            if self.mesh is not None:
                caches = _constrain_caches(caches, self.mesh,
                                           self.strategies)
            if self.spec:
                _, dvarz = self._draft_prefill_model.apply(
                    params["draft"], tokens, mutable=["cache"])
                draft_caches = harvest_caches(
                    self.draft_config, dvarz["cache"], lengths,
                    self.policy, self.max_len)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        first_mrow = jnp.take_along_axis(
            lmask, lengths[:, None, None], axis=1)[:, 0]
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp,
            mask=first_mrow).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        out = {
            "seq": seq,
            "caches": caches,
            "pos": lengths,
            "start": lengths,
            "stop": stops,
            "done": (first == EOS_ID) | (lengths + 1 >= stops),
            "keys": jax.random.key_data(split[:, 0]),
            "top_k": top_k,
            "temp": temp,
            "lmask": lmask,
        }
        if self.lora:
            out["tenant"] = tenant
        if self.spec:
            out["draft_caches"] = draft_caches
        return out

    def _merge_impl(self, state, hstate, gate_rows, src, mask, *extra):
        """Decode-side half of the handoff: gather handle rows into slot
        state.  ``src (S,)`` gives each slot its handle row (any value
        where ``mask`` is False), ``mask (S,)`` the slots being admitted.
        The handle is DONATED (``donate_argnums=(1,)``) — its buffers
        alias the merged state outputs, so the caches move rather than
        copy.  A gather (host-inverted mapping) rather than a scatter of
        handle rows: no duplicate-index hazard, and dead rows vanish for
        free.  In paged mode the handle's dense gate slabs ride in as
        ``gate_rows`` (NOT donated — they scatter into the pool, so they
        cannot alias anything) and ``extra[0]`` is ``row_wtable (S,
        ppr)``: a handle-ROW-indexed write table (DUMP for unused rows)
        feeding ``scatter_gate_rows``."""
        s = self.num_slots
        csrc = jnp.clip(src, 0, s - 1)

        def take(h, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, jnp.take(h, csrc, axis=0), old)

        if self.paged:
            (row_wtable,) = extra
            h_caches = hstate["caches"]
            if self.gate_dtype == "int8":
                # handle slabs arrive in compute dtype; they quantize
                # here, at the page-pool boundary
                pool, pscale = scatter_gate_rows(
                    self.config, gate_rows, hstate["start"],
                    state["caches"]["sgu_pool"], row_wtable,
                    pool_scale=state["caches"]["sgu_pool_scale"])
            else:
                pool = scatter_gate_rows(
                    self.config, gate_rows, hstate["start"],
                    state["caches"]["sgu_pool"], row_wtable)
            caches = {
                **{k: jax.tree.map(take, h_caches[k], state["caches"][k])
                   for k in self._RING_KEYS},
                "sgu_pool": pool,
                **({"sgu_pool_scale": pscale}
                   if self.gate_dtype == "int8" else {}),
            }
        else:
            caches = jax.tree.map(take, hstate["caches"],
                                  state["caches"])
        out = {
            "seq": take(hstate["seq"], state["seq"]),
            "caches": caches,
            "pos": take(hstate["pos"], state["pos"]),
            "start": take(hstate["start"], state["start"]),
            "stop": take(hstate["stop"], state["stop"]),
            "active": state["active"] | mask,
            "done": take(hstate["done"], state["done"]),
            "keys": take(hstate["keys"], state["keys"]),
            "top_k": take(hstate["top_k"], state["top_k"]),
            "temp": take(hstate["temp"], state["temp"]),
            "lmask": take(hstate["lmask"], state["lmask"]),
        }
        if self.lora:
            out["tenant"] = take(hstate["tenant"], state["tenant"])
        if self.spec:
            out["draft_caches"] = jax.tree.map(
                take, hstate["draft_caches"], state["draft_caches"])
        return out

    def _prefill_worker_call(self, *args):
        fn = self._aot.get(("prefill", args[0].shape[1]),
                           self._prefill_worker)
        return fn(self._params, *args)

    def _merge_call(self, hstate, *args):
        fn = self._aot.get(("merge",), self._merge)
        if self.paged:
            # split the gate slabs out of the donated handle (they
            # scatter, never alias; donating them only warns)
            gate = hstate["caches"]["sgu_gate"]
            hstate = {**hstate, "caches": {
                k: v for k, v in hstate["caches"].items()
                if k != "sgu_gate"}}
            return fn(self.state, hstate, gate, *args)
        return fn(self.state, hstate, {}, *args)

    # ----------------------------------------------------------------- API

    def submit(self, request: Request) -> None:
        """Queue a request.  Structural errors (empty prime, no room to
        generate) still raise — they are caller bugs; OPERATIONAL
        conditions (injected faults, expired deadline, full queue) shed
        the request as a typed completion instead, so a loaded or faulty
        server answers every request rather than crashing on admission.
        """
        n = len(request.tokens)
        if n < 1:
            raise ValueError(f"request {request.uid!r}: empty prime")
        if n + 1 > self.max_len:
            raise ValueError(
                f"request {request.uid!r}: prime length {n} leaves no room "
                f"for generation (max_len {self.max_len})"
            )
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1")
        if request.logit_mask is not None:
            m = np.asarray(request.logit_mask, bool)
            if m.ndim != 2 or m.shape[1] != self.config.num_tokens:
                raise ValueError(
                    f"request {request.uid!r}: logit_mask must be "
                    f"(G, {self.config.num_tokens}), got {m.shape}")
            if m.shape[0] > request.max_new_tokens:
                raise ValueError(
                    f"request {request.uid!r}: logit_mask has {m.shape[0]} "
                    f"rows but max_new_tokens={request.max_new_tokens}")
            if n + m.shape[0] > self.max_len:
                raise ValueError(
                    f"request {request.uid!r}: mask rows run past max_len "
                    f"{self.max_len} (prime {n} + {m.shape[0]} rows)")
            if not m.any(axis=1).all():
                raise ValueError(
                    f"request {request.uid!r}: logit_mask has an all-False "
                    f"row — every constrained position needs >= 1 allowed "
                    f"token")
            request.logit_mask = m
        tenant = int(request.tenant)
        if tenant != 0 and not self.lora:
            raise ValueError(
                f"request {request.uid!r}: tenant={tenant} but the engine "
                f"was built without a lora_bank")
        if not (0 <= tenant < self.num_tenants):
            raise ValueError(
                f"request {request.uid!r}: tenant {tenant} outside the "
                f"bank's [0, {self.num_tenants})")
        if self.paged:
            stop = min(n + request.max_new_tokens, self.max_len)
            worst = pages_for_span(stop - 1, self.page_size)
            if worst > self._pool.capacity:
                raise ValueError(
                    f"request {request.uid!r}: needs up to {worst} pages "
                    f"but the pool only has {self._pool.capacity} — "
                    f"raise num_pages or lower max_new_tokens")
        try:
            self._guard("serve.submit")
        except (_ContainedFault, RetryError):
            self._shed(request, FAILED_FAULT)
            return
        deadline = self._deadline_of(request)
        if deadline is not None and time.perf_counter() > deadline:
            self._shed(request, SHED_DEADLINE)
            return
        if self.max_queue is not None and len(self._queue) >= self.max_queue:
            if self.shed_policy == "shed-oldest":
                # priority-aware: drop the LOWEST class (oldest within
                # it); when the newcomer ranks below everything queued,
                # the newcomer is the victim — a strictly higher-priority
                # request is never shed while a lower one sits queued
                victim = self._queue.shed_victim()
                if (victim is not None
                        and victim.priority <= request.priority):
                    self._queue.remove(victim)
                    self._shed(victim, SHED_QUEUE_FULL)
                else:
                    self._shed(request, SHED_QUEUE_FULL)
                    return
            else:
                self._shed(request, SHED_QUEUE_FULL)
                return
        self._queue.append(request)
        self._tracer.event("serve.submit", trace=request.uid,
                           queue=len(self._queue))

    def submit_embed(self, request: Request) -> None:
        """Queue an EMBEDDING request: one prefill-shaped forward, mean-
        pooled final hidden state, no decode slot consumed.  Same shed
        rules as :meth:`submit`; ``max_new_tokens``/``top_k``/``temp``/
        ``seed`` are ignored (nothing is sampled)."""
        n = len(request.tokens)
        if n < 1:
            raise ValueError(f"request {request.uid!r}: empty prime")
        if n > self.config.seq_len:
            raise ValueError(
                f"request {request.uid!r}: prime length {n} exceeds "
                f"seq_len {self.config.seq_len}")
        if request.logit_mask is not None:
            raise ValueError(
                f"request {request.uid!r}: embed requests take no "
                f"logit_mask (nothing is sampled)")
        if int(request.tenant) != 0:
            raise ValueError(
                f"request {request.uid!r}: embed requests run the base "
                f"model (tenant must be 0)")
        try:
            self._guard("serve.submit")
        except (_ContainedFault, RetryError):
            self._shed(request, FAILED_FAULT)
            return
        deadline = self._deadline_of(request)
        if deadline is not None and time.perf_counter() > deadline:
            self._shed(request, SHED_DEADLINE)
            return
        if (self.max_queue is not None
                and len(self._embed_queue) >= self.max_queue):
            if self.shed_policy == "shed-oldest":
                self._shed(self._embed_queue.popleft(), SHED_QUEUE_FULL)
            else:
                self._shed(request, SHED_QUEUE_FULL)
                return
        self._embed_queue.append(request)
        self._tracer.event("serve.submit_embed", trace=request.uid,
                           queue=len(self._embed_queue))

    def submit_fork(self, request: Request, n_samples: int) -> list:
        """Best-of-N: fork ``n_samples`` trajectories off one shared
        prime.  Fork ``k`` is ``request`` with ``uid + k`` and ``seed +
        k`` — each completion is token-identical to submitting that
        request independently (a trajectory depends only on (params,
        prime, seed, knobs)), so callers may rank or dedup the samples
        freely.  The caller owns uid-space: ``uid .. uid+n-1`` must be
        unused.

        On a paged engine with the prefix cache enabled the forks share
        the prime's full prefix pages through the pool's refcounts — the
        leader (fork 0) is submitted immediately and primes the pages;
        the followers are held until the leader's registrations publish
        (or the leader sheds), then admitted as cache hits, so N samples
        cost one set of prime pages instead of N.  Dense engines and
        ``prefix_cache=False`` pools submit all forks immediately (same
        tokens, no sharing to exploit).  Returns the fork uids in order;
        sheds still answer per-fork as typed completions."""
        if n_samples < 1:
            raise ValueError(f"request {request.uid!r}: n_samples must "
                             f"be >= 1, got {n_samples}")
        if not isinstance(request.uid, int):
            raise ValueError(f"request {request.uid!r}: submit_fork "
                             f"derives fork uids by offset — uid must "
                             f"be an int")
        forks = [dataclasses.replace(request, uid=request.uid + k,
                                     seed=request.seed + k)
                 for k in range(n_samples)]
        self.fork_groups += 1
        share = (self.paged and self._pool.prefix_caching
                 and n_samples > 1
                 and len(request.tokens) >= self.page_size)
        self.submit(forks[0])
        if not share:
            for f in forks[1:]:
                self.submit(f)
        else:
            # hold the followers until the leader's prefix pages are
            # published — released by _release_forks on the step after
            # the leader leaves the queue (admitted OR shed), so a shed
            # leader never strands its followers
            self._fork_wait[forks[0].uid] = forks[1:]
        self._tracer.event("serve.submit_fork", trace=request.uid,
                           n_samples=n_samples)
        return [f.uid for f in forks]

    def forget_ttft(self, uids) -> None:
        """Drop first-token stamps for requests that leave this engine
        for another process (prefill workers hand off and never harvest
        locally), so the stamp map cannot grow without bound."""
        for u in uids:
            self._ttft.pop(u, None)

    def _release_forks(self) -> None:
        """Submit fork followers whose leader has left the queue (its
        admission committed the shared prefix registrations — or it shed,
        in which case the followers proceed unshared).  Runs at the top
        of :meth:`step` so followers land one admission round behind
        their leader."""
        if not self._fork_wait:
            return
        queued = {r.uid for r in self._queue}
        ready = [uid for uid in self._fork_wait if uid not in queued]
        for uid in ready:
            for f in self._fork_wait.pop(uid):
                self.submit(f)

    @property
    def pending(self) -> int:
        return (len(self._queue) + len(self._embed_queue)
                + sum(len(v) for v in self._fork_wait.values()))

    @property
    def num_active(self) -> int:
        return len(self._inflight)

    @property
    def has_work(self) -> bool:
        """True while anything remains for ``step()`` to do or report —
        queued requests, held fork followers, in-flight slots, or shed
        completions not yet returned by a ``step()`` call."""
        n = (len(self._queue) + len(self._embed_queue)
             + len(self._inflight) + len(self._pending)
             + sum(len(v) for v in self._fork_wait.values()))
        if self.disagg:
            n += len(self._handoff)
        return n > 0

    # ---------------------------------------------------------- shedding

    @staticmethod
    def _deadline_of(r: Request) -> float | None:
        if r.deadline is not None:
            return r.deadline
        if r.ttl is not None:
            return r.submit_time + r.ttl
        return None

    def _shed(self, r: Request, status: str, tokens=None) -> Completion:
        """Answer ``r`` with a typed shed completion (callback fires,
        counters bump); ``tokens`` carries any partial generation an
        in-flight deadline cancellation salvaged."""
        if status == SHED_QUEUE_FULL:
            self.robust.sheds_queue_full += 1
        elif status == SHED_DEADLINE:
            self.robust.sheds_deadline += 1
        else:
            self.robust.failed_faults += 1
        comp = Completion(
            uid=r.uid,
            prime=np.asarray(  # graftcheck: disable=host-sync
                r.tokens, np.int32),
            tokens=np.asarray(  # graftcheck: disable=host-sync
                [] if tokens is None else tokens, np.int32),
            finish_reason=status, status=status,
            submit_time=r.submit_time, finish_time=time.perf_counter(),
            generation=self.generation,
            first_token_time=self._ttft.pop(r.uid, None))
        self.completions.append(comp)
        self._pending.append(comp)
        self._tracer.event("serve.shed", trace=r.uid, status=status)
        if r.on_complete is not None:
            r.on_complete(comp)
        return comp

    def _drain_pending(self) -> list[Completion]:
        out, self._pending = self._pending, []
        return out

    def _shed_expired(self) -> None:
        """Shed every queued request past its deadline (before it costs a
        prefill) and cancel expired in-flight slots (their partial tokens
        ride along in the shed completion)."""
        now = time.perf_counter()
        for q in (self._queue, self._embed_queue):
            expired_q = [r for r in q
                         if self._deadline_of(r) is not None
                         and now > self._deadline_of(r)]
            for r in expired_q:
                q.remove(r)
                self._shed(r, SHED_DEADLINE)
        slots = [s for s, r in self._inflight.items()
                 if self._deadline_of(r) is not None
                 and now > self._deadline_of(r)]
        if not slots:
            return
        active, seq, pos, start = _host_fetch(
            (self.state["active"], self.state["seq"], self.state["pos"],
             self.state["start"]))
        act = self.state["active"]
        for slot in slots:
            r = self._inflight.pop(slot)
            toks = (seq[slot, start[slot]: pos[slot] + 1].copy()
                    if active[slot] else None)
            if self.paged:
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
            self._shed(r, SHED_DEADLINE, tokens=toks)
            act = act.at[slot].set(False)
        self.state = {**self.state, "active": act}

    # ----------------------------------------------------------- admission

    def _maybe_preempt(self) -> None:
        """Priority preemption: while the scheduler's head is blocked
        (no free slot, or — paged — no pages for its prime) and some
        in-flight request ranks STRICTLY below it, cancel the victim and
        re-enqueue it through the scheduler.  Victim choice: lowest
        priority class first, then most recently admitted (least decode
        work thrown away).  Replay-from-scratch is bit-exact — a
        trajectory depends only on (params, prime, seed, knobs) — so
        preemption trades only latency, never correctness.  Disabled
        under disagg: a remote-prefill replica cannot replay locally, so
        cluster QoS is enforced at each prefill worker's queue instead.
        """
        if self.disagg:
            return
        while self._queue and self._inflight:
            head = self._queue[0]
            blocked = len(self._inflight) >= self.num_slots
            if not blocked and self.paged:
                need = pages_for_span(len(head.tokens), self.page_size)
                blocked = not self._pool.can_allocate(need)
            if not blocked:
                return
            victim = min(
                self._inflight,
                key=lambda s: (self._inflight[s].priority,
                               -self._admit_order.get(s, 0)))
            if self._inflight[victim].priority >= head.priority:
                return
            self._preempt_slot(victim)

    def _preempt_slot(self, slot: int) -> None:
        """Cancel ``slot``'s in-flight request for a higher class and
        re-enqueue it THROUGH the scheduler: it keeps its original queue
        seniority among same-class peers but waits behind the class that
        displaced it (contrast :meth:`_evict_slot`, whose front-of-queue
        requeue is the pool-starvation replay path)."""
        r = self._inflight.pop(slot)
        if self.paged:
            self._host_stop[slot] = 0
            self._free_slot_pages(slot)
        else:
            self._admit_order.pop(slot, None)
        self.state = {**self.state, "active":
                      self.state["active"].at[slot].set(False)}
        self._queue.append(r)
        self.robust.preemptions += 1
        self._tracer.event("serve.preempt", trace=r.uid, slot=slot)

    def _admit_pending(self) -> None:
        if not self._queue:
            return
        self._maybe_preempt()
        if len(self._inflight) >= self.num_slots:
            return
        try:
            self._guard("serve.admit")
        except _ContainedFault:
            # the admission machinery is poisoned for this round: shed the
            # queue head (livelock breaker — a permanently faulting point
            # must not starve the whole queue) and defer the rest
            self._shed(self._queue.popleft(), FAILED_FAULT)
            return
        if self.paged:
            self._admit_pending_paged()
        else:
            self._admit_pending_dense()

    def _build_lmask(self, rows: list) -> np.ndarray:
        """``(S, max_len, V)`` write-position-indexed logit masks for the
        rows being admitted (``rows`` pairs a slot/handle-row index with
        its request).  Unconstrained rows stay all-True — bit-identical
        to serving without masks at all.  Request row ``g`` constrains
        the token written at absolute position ``len(prime) + g``."""
        lmask = np.ones((self.num_slots, self.max_len,
                         self.config.num_tokens), bool)
        for idx, r in rows:
            if r.logit_mask is not None:
                m = np.asarray(r.logit_mask, bool)
                p = len(r.tokens)
                lmask[idx, p: p + m.shape[0]] = m
        return lmask

    def _admit_pending_dense(self) -> None:
        free = [i for i in range(self.num_slots) if i not in self._inflight]
        if not free or not self._queue:
            return
        batch: list[tuple[int, Request]] = []
        while free and self._queue:
            batch.append((free.pop(0), self._queue.popleft()))

        s = self.num_slots
        longest = max(len(r.tokens) for _, r in batch)
        p_pad = pad_prime_length(longest, self.config.window_size,
                                 self.config.seq_len, bucket=True)
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        mask = np.zeros((s,), bool)
        tenant = np.zeros((s,), np.int32)
        for slot, r in batch:
            t = np.asarray(r.tokens, np.int32)
            tokens[slot, : len(t)] = t
            lengths[slot] = len(t)
            stops[slot] = min(len(t) + r.max_new_tokens, self.max_len)
            seeds[slot] = np.uint32(int(r.seed) & 0xFFFFFFFF)
            top_k[slot] = 0 if r.top_k is None else int(r.top_k)
            temp[slot] = float(r.temperature)
            mask[slot] = True
            tenant[slot] = int(r.tenant)
            self._inflight[slot] = r
            self._admit_order[slot] = self._admit_seq
            self._admit_seq += 1
        lmask = self._build_lmask(batch)
        extra = (tenant,) if self.lora else ()

        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation("serve.admit_prefill"):
                self.state = self._guard(
                    "serve.prefill", self._admit_call, tokens, lengths,
                    stops, seeds, top_k, temp, mask, lmask, *extra,
                    key=("admit", p_pad))
            self._note_stage("prefill_s", "serve.admit_prefill", t0,
                             uids=[r.uid for _, r in batch], p_pad=p_pad)
        except _ContainedFault:
            # the batch's prefill never merged: undo the bookkeeping and
            # shed exactly the requests whose work was lost
            for slot, r in batch:
                self._inflight.pop(slot, None)
                self._shed(r, FAILED_FAULT)
        except RetryError:
            # escape for restart-and-replay, but leave the engine
            # consistent: the un-prefilled batch goes back to the queue
            # front in its original order
            for slot, r in reversed(batch):
                self._inflight.pop(slot, None)
                self._queue.appendleft(r)
            raise
        else:
            # the admit program samples each request's first token, so
            # admission success IS first-token time; setdefault keeps the
            # earliest stamp across evict/replay round-trips
            now = time.perf_counter()
            for _, r in batch:
                self._ttft.setdefault(r.uid, now)

    def _admit_pending_paged(self) -> None:
        """FIFO admission gated by free slots AND free pages.

        The head of the queue is admitted only if the pool can cover its
        whole prime plus the first sampled token WITHOUT prefix sharing
        (a conservative bound — actual planning below shares whatever it
        can, so the allocation never exceeds the reservation); a blocked
        head DEFERS everything behind it.  "Head" is whatever the QoS
        scheduler ranks first RIGHT NOW (priority, then weighted-fair
        tenant share, then EDF) — within one admission round the order
        is fixed, across rounds a higher-priority arrival may overtake
        a deferred head (that, plus :meth:`_maybe_preempt`, is the QoS
        contract; pre-QoS FIFO deferral is the degenerate single-class
        case).
        """
        free = [i for i in range(self.num_slots) if i not in self._inflight]
        batch: list[tuple[int, Request]] = []
        reserved = 0
        while free and self._queue:
            r = self._queue[0]
            need = pages_for_span(len(r.tokens), self.page_size)
            if not self._pool.can_allocate(reserved + need):
                break  # head-of-line blocks: deferral, not reordering
            reserved += need
            batch.append((free.pop(0), self._queue.popleft()))
        if not batch:
            return

        s = self.num_slots
        longest = max(len(r.tokens) for _, r in batch)
        p_pad = pad_prime_length(longest, self.config.window_size,
                                 self.config.seq_len, bucket=True)
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        mask = np.zeros((s,), bool)
        tenant = np.zeros((s,), np.int32)
        wtable = np.full((s, self.pages_per_row), DUMP_PAGE, np.int32)
        pending_prefix: list[tuple[tuple, int]] = []
        planned: list[tuple[int, Request]] = []
        try:
            for slot, r in batch:
                t = np.asarray(r.tokens, np.int32)
                tokens[slot, : len(t)] = t
                lengths[slot] = len(t)
                stops[slot] = min(len(t) + r.max_new_tokens, self.max_len)
                seeds[slot] = np.uint32(int(r.seed) & 0xFFFFFFFF)
                top_k[slot] = 0 if r.top_k is None else int(r.top_k)
                temp[slot] = float(r.temperature)
                mask[slot] = True
                tenant[slot] = int(r.tenant)
                self._inflight[slot] = r
                self._host_stop[slot] = stops[slot]
                self._admit_order[slot] = self._admit_seq
                self._admit_seq += 1
                self._paused[slot] = False
                # planning allocates (and retains shared) pages — a
                # faultable operation, guarded at the SAME point as the
                # chunk-growth allocator.  A contained fault mid-batch
                # rolls back every page planned so far AND the deferred
                # registrations (pending_prefix dies with this frame) —
                # the fork path leans on exactly this discipline
                self._guard("serve.page_alloc", self._plan_slot_pages,
                            slot, r, p_pad, wtable, pending_prefix)
                planned.append((slot, r))
        except _ContainedFault:
            j = len(planned)
            for slot, r in reversed(batch[: j + 1]):
                self._inflight.pop(slot, None)
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
            # innocents (planned before the fault or never reached) go
            # back to the queue front in order; only the request whose
            # planning faulted is shed
            innocents = [r for _, r in batch[:j] + batch[j + 1:]]
            for r in reversed(innocents):
                self._queue.appendleft(r)
            self._shed(batch[j][1], FAILED_FAULT)
            return
        except RetryError:
            j = len(planned)
            for slot, r in reversed(batch[: j + 1]):
                self._inflight.pop(slot, None)
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
            for _, r in reversed(batch):
                self._queue.appendleft(r)
            raise
        lmask = self._build_lmask(batch)
        extra = (tenant,) if self.lora else ()

        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation("serve.admit_prefill"):
                self.state = self._guard(
                    "serve.prefill", self._admit_call, tokens, lengths,
                    stops, seeds, top_k, temp, mask, lmask,
                    self._page_table.copy(), wtable, *extra,
                    key=("admit", p_pad))
            self._note_stage("prefill_s", "serve.admit_prefill", t0,
                             uids=[r.uid for _, r in batch], p_pad=p_pad)
        except _ContainedFault:
            # prefill never merged: the planned pages hold nothing — free
            # them (no prefix registration was committed, so the index
            # cannot serve a garbage page) and shed the batch
            for slot, r in batch:
                self._inflight.pop(slot, None)
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
                self._shed(r, FAILED_FAULT)
            return
        except RetryError:
            for slot, r in reversed(batch):
                self._inflight.pop(slot, None)
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
                self._queue.appendleft(r)
            raise
        # prefill landed: NOW the freshly-filled full-prefix pages may be
        # published for sharing
        for key, pid in pending_prefix:
            self._pool.register_prefix(key, pid)
        now = time.perf_counter()
        for _, r in batch:
            self._ttft.setdefault(r.uid, now)

    # ---------------------------------------------------------- embeddings

    def _embed_round(self) -> None:
        """Serve one batch of embedding requests: a FIFO prefix of the
        embed queue sharing the head's prefill bucket, padded to
        ``embed_batch`` rows, one pooled forward, completions with the
        ``(D,)`` vector attached.  No slot state is touched — embedding
        traffic composes with any decode configuration."""
        if not self._embed_queue:
            return
        try:
            self._guard("serve.admit")
        except _ContainedFault:
            self._shed(self._embed_queue.popleft(), FAILED_FAULT)
            return
        cfg = self.config
        p_pad = pad_prime_length(len(self._embed_queue[0].tokens),
                                 cfg.window_size, cfg.seq_len, bucket=True)
        batch: list[Request] = []
        while (self._embed_queue and len(batch) < self.embed_batch
               and pad_prime_length(len(self._embed_queue[0].tokens),
                                    cfg.window_size, cfg.seq_len,
                                    bucket=True) == p_pad):
            batch.append(self._embed_queue.popleft())

        b = self.embed_batch
        tokens = np.zeros((b, p_pad), np.int32)
        lengths = np.ones((b,), np.int32)  # dummy rows: 1-token prime
        for row, r in enumerate(batch):
            t = np.asarray(r.tokens, np.int32)
            tokens[row, : len(t)] = t
            lengths[row] = len(t)
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation("serve.embed"):
                vecs = self._guard(
                    "serve.embed", self._embed_call, tokens, lengths,
                    key=("embed", p_pad))
            self._note_stage("embed_s", "serve.embed", t0,
                             uids=[r.uid for r in batch], p_pad=p_pad)
        except _ContainedFault:
            for r in batch:
                self._shed(r, FAILED_FAULT)
            return
        except RetryError:
            for r in reversed(batch):
                self._embed_queue.appendleft(r)
            raise
        vecs = np.asarray(jax.device_get(
            vecs))
        now = time.perf_counter()
        for row, r in enumerate(batch):
            comp = Completion(
                uid=r.uid, prime=np.asarray(r.tokens, np.int32),
                tokens=np.zeros((0,), np.int32), finish_reason="embed",
                submit_time=r.submit_time, finish_time=now,
                embedding=vecs[row], generation=self.generation)
            self.completions.append(comp)
            self._pending.append(comp)
            if r.on_complete is not None:
                r.on_complete(comp)

    # ------------------------------------------- disaggregated admission

    def _prefill_round(self) -> None:
        """Prefill stage of a disaggregated step: run the worker over a
        FIFO prefix of the queue sharing the head's bucket and push the
        handle.  A full handoff queue skips the round entirely —
        backpressure: prefilled caches are the expensive thing to hold,
        so the wait is absorbed by the cheap token queue instead."""
        if not self._queue or self._handoff.full():
            return
        try:
            self._guard("serve.admit")
        except _ContainedFault:
            # same livelock breaker as inline admission: shed the head
            self._shed(self._queue.popleft(), FAILED_FAULT)
            return
        cfg = self.config
        p_pad = pad_prime_length(len(self._queue[0].tokens),
                                 cfg.window_size, cfg.seq_len, bucket=True)
        batch: list[Request] = []
        while (self._queue and len(batch) < self.prefill_batch
               and pad_prime_length(len(self._queue[0].tokens),
                                    cfg.window_size, cfg.seq_len,
                                    bucket=True) == p_pad):
            batch.append(self._queue.popleft())

        s = self.num_slots
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        tenant = np.zeros((s,), np.int32)
        for row, r in enumerate(batch):
            t = np.asarray(r.tokens, np.int32)
            tokens[row, : len(t)] = t
            lengths[row] = len(t)
            stops[row] = min(len(t) + r.max_new_tokens, self.max_len)
            seeds[row] = np.uint32(int(r.seed) & 0xFFFFFFFF)
            top_k[row] = 0 if r.top_k is None else int(r.top_k)
            temp[row] = float(r.temperature)
            tenant[row] = int(r.tenant)
        # handle-ROW-indexed, like every other slab the worker produces
        lmask = self._build_lmask(list(enumerate(batch)))
        extra = (tenant,) if self.lora else ()
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation("serve.prefill"):
                h = self._guard(
                    "serve.prefill", self._prefill_worker_call, tokens,
                    lengths, stops, seeds, top_k, temp, lmask, *extra,
                    key=("prefill", p_pad))
            self._note_stage("prefill_s", "serve.prefill", t0,
                             uids=[r.uid for r in batch], p_pad=p_pad)
        except _ContainedFault:
            for r in batch:
                self._shed(r, FAILED_FAULT)
            return
        except RetryError:
            for r in reversed(batch):
                self._queue.appendleft(r)
            raise
        # the prefill worker samples each request's first token, so the
        # handle landing IS first-token time (the decode-side merge only
        # moves already-sampled state into slots)
        now = time.perf_counter()
        for r in batch:
            self._ttft.setdefault(r.uid, now)
        self._handoff.put(Handle(requests=batch, state=h, p_pad=p_pad))

    def _admit_from_handoff(self) -> None:
        """Decode-side admission: move queued handles into free slots via
        the donating merge program.  The head handle DEFERS (never
        reorders) while slots or pages are short, exactly like inline
        paged admission."""
        while self._handoff:
            h = self._handoff.peek()
            now = time.perf_counter()
            expired: list[Request] = []
            live_rows: list[tuple[int, Request]] = []
            for row, r in enumerate(h.requests):
                d = self._deadline_of(r)
                if d is not None and now > d:
                    expired.append(r)
                else:
                    live_rows.append((row, r))
            free = [i for i in range(self.num_slots)
                    if i not in self._inflight]
            if len(free) < len(live_rows):
                return
            if self.paged and live_rows:
                need = sum(pages_for_span(len(r.tokens), self.page_size)
                           for _, r in live_rows)
                if not self._pool.can_allocate(need):
                    return
            # peek-then-pop: ``h`` above came from front() without
            # consuming; this get() pops that same handle now that
            # admission is committed — ownership continues in ``h``
            # graftcheck: disable=resource-leak
            self._handoff.get()
            if live_rows:
                src = np.zeros((self.num_slots,), np.int32)
                mask = np.zeros((self.num_slots,), bool)
                extra: tuple = ()
                pending_prefix: list[tuple[tuple, int]] = []
                placed: list[tuple[int, Request]] = []
                if self.paged:
                    # the merge scatters the handle's dense gate slabs
                    # through a handle-ROW-indexed write table; the page
                    # plan is slot-indexed, so plan into a slot scratch
                    # row and copy it across
                    row_wtable = np.full(
                        (self.num_slots, self.pages_per_row), DUMP_PAGE,
                        np.int32)
                    scratch = np.full(
                        (self.num_slots, self.pages_per_row), DUMP_PAGE,
                        np.int32)
                for slot, (row, r) in zip(free, live_rows):
                    src[slot] = row
                    mask[slot] = True
                    self._inflight[slot] = r
                    placed.append((slot, r))
                    if self.paged:
                        self._host_stop[slot] = min(
                            len(r.tokens) + r.max_new_tokens, self.max_len)
                        self._admit_order[slot] = self._admit_seq
                        self._admit_seq += 1
                        self._paused[slot] = False
                        self._plan_slot_pages(slot, r, h.p_pad, scratch,
                                              pending_prefix)
                        row_wtable[row] = scratch[slot]
                if self.paged:
                    extra = (row_wtable,)
                t0 = time.perf_counter()
                try:
                    # the merge DONATES the handle's buffers; this stays
                    # retry/requeue-safe because faults.inject raises
                    # BEFORE the jitted program dispatches — a contained
                    # or transient failure here has not consumed them
                    with jax.profiler.TraceAnnotation("serve.merge"):
                        self.state = self._guard(
                            "serve.handoff", self._merge_call, h.state,
                            src, mask, *extra, key=("merge",))
                    self._note_stage(
                        "merge_s", "serve.merge", t0,
                        uids=[r.uid for _, r in live_rows])
                except _ContainedFault:
                    for slot, r in placed:
                        self._inflight.pop(slot, None)
                        if self.paged:
                            self._host_stop[slot] = 0
                            self._free_slot_pages(slot)
                        self._shed(r, FAILED_FAULT)
                except RetryError:
                    for slot, r in placed:
                        self._inflight.pop(slot, None)
                        if self.paged:
                            self._host_stop[slot] = 0
                            self._free_slot_pages(slot)
                    # expired rows were NOT shed yet, so the requeued
                    # handle replays them all exactly once after restart
                    self._handoff.requeue(h)
                    raise
                else:
                    for key, pid in pending_prefix:
                        self._pool.register_prefix(key, pid)
                    # remote-prefill handles never passed through this
                    # engine's _prefill_round; their first token lands
                    # here (setdefault keeps the local prefill stamp on
                    # the inline disagg path)
                    merged = time.perf_counter()
                    for _, r in live_rows:
                        self._ttft.setdefault(r.uid, merged)
            for r in expired:
                self._shed(r, SHED_DEADLINE)

    def _plan_slot_pages(self, slot: int, r: Request, p_pad: int,
                         wtable: np.ndarray,
                         pending_prefix: list[tuple[tuple, int]]) -> None:
        """Build the slot's page list for rows ``[0, P]`` (prime + first
        sampled token): longest run of prefix-cache hits first, fresh
        private pages for the rest.  Fills the slot's ``_page_table`` row
        and its ``wtable`` row (private pages only — shared pages were
        filled by the request that first computed them and MUST stay
        read-only: rewriting them from a different prefill batch shape
        could perturb the sharer's bits).

        Fresh full-prefix pages are NOT registered here: registrations
        collect in ``pending_prefix`` and commit only after the guarded
        prefill dispatch succeeds — a failed prefill must never leave the
        index pointing at pages that were never filled."""
        ps = self.page_size
        p = len(r.tokens)
        n_pages = p // ps + 1  # decode writes row P before any page grows
        n_full = p // ps       # full pages strictly inside the prime
        shared: list[int] = []
        for j in range(n_full):
            pid = self._pool.lookup_prefix(prefix_key(p_pad, r.tokens,
                                                      (j + 1) * ps))
            if pid is None:
                break
            shared.append(pid)
        fresh = self._pool.allocate(n_pages - len(shared))
        assert fresh is not None, "admission reserved pages conservatively"
        for pid in shared:
            self._pool.retain(pid)
        self.prefix_hits += len(shared)
        self.prefix_lookups += n_full
        pages = shared + fresh
        for j in range(len(shared), n_full):
            pending_prefix.append(
                (prefix_key(p_pad, r.tokens, (j + 1) * ps), pages[j]))
        self._slot_pages[slot] = SlotPages(pages=pages, shared=len(shared))
        self._page_table[slot, :] = NULL_PAGE
        self._page_table[slot, : n_pages] = pages
        wtable[slot, : n_pages] = [DUMP_PAGE] * len(shared) + fresh

    def _free_slot_pages(self, slot: int) -> None:
        sp = self._slot_pages.pop(slot, None)
        if sp is None:
            return
        for pid in sp.pages:
            self._pool.release(pid)
        self._page_table[slot, :] = NULL_PAGE
        self._paused[slot] = False
        self._admit_order.pop(slot, None)

    def _evict_slot(self, slot: int) -> None:
        """Restart preemption: free the slot's pages and push its request
        back to the FRONT of the queue.  Replaying from scratch is safe —
        a trajectory depends only on (params, prime, seed, knobs), so the
        re-decode reproduces the identical token prefix."""
        r = self._inflight.pop(slot)
        self._free_slot_pages(slot)
        self.state = {**self.state, "active":
                      self.state["active"].at[slot].set(False)}
        self._queue.appendleft(r)
        self.evictions += 1

    def _ensure_chunk_pages(self) -> None:
        """Before each chunk, grow every live slot's page list to cover
        all positions the chunk can write (``[pos, min(pos+chunk,
        stop)-1]``).  Slots the pool cannot cover are PAUSED for this
        chunk (their rows freeze entirely); if the pool starves every
        live slot, the youngest is evicted until someone can run."""
        if not self._inflight:
            return
        try:
            self._guard("serve.page_alloc")
        except _ContainedFault as e:
            # contain an allocator fault like pool starvation: pause every
            # live slot for this chunk (their rows freeze — trajectories
            # are delayed, never altered) and retry next round
            self._defer("page_alloc", e)
            for slot in self._inflight:
                if not self._paused[slot]:
                    self.pause_events += 1
                self._paused[slot] = True
            return
        self._defer_streak.pop("page_alloc", None)
        pos = _host_fetch(
            self.state["pos"])
        for _ in range(len(self._inflight) + 1):
            slots = sorted(self._inflight, key=self._admit_order.__getitem__)
            for slot in slots:
                # last position the chunk can consume: done fires when
                # new_pos + 1 >= stop, so a live slot never consumes past
                # stop - 2; gate rows are written at consumed positions.
                # _max_advance == chunk_size except under speculation,
                # where a chunk of fully-accepted rounds can advance
                # rounds * (k + 1) positions
                last = min(int(pos[slot]) + self._max_advance - 1,
                           int(self._host_stop[slot]) - 2)
                need = pages_for_span(last, self.page_size)
                sp = self._slot_pages[slot]
                delta = need - len(sp.pages)
                if delta <= 0:
                    self._paused[slot] = False
                    continue
                fresh = self._pool.allocate(delta)
                if fresh is None:
                    if not self._paused[slot]:
                        self.pause_events += 1
                    self._paused[slot] = True
                    continue
                base = len(sp.pages)
                sp.pages.extend(fresh)
                self._page_table[slot, base: base + delta] = fresh
                self._paused[slot] = False
            if any(not self._paused[s] for s in self._inflight):
                return
            # every live slot starved: evict the most recently admitted
            victim = max(self._inflight, key=self._admit_order.__getitem__)
            if len(self._inflight) == 1:
                raise RuntimeError(
                    f"page pool too small for any progress: slot {victim} "
                    f"needs pages beyond capacity {self._pool.capacity} "
                    f"with nothing left to evict")
            self._evict_slot(victim)

    def _harvest_done(self) -> list[Completion]:
        try:
            self._guard("serve.harvest")
        except _ContainedFault as e:
            # finished slots stay done-but-active; the next step's harvest
            # picks them up (their state is inert — done rows are masked
            # no-ops in the chunk body)
            self._defer("harvest", e)
            return []
        self._defer_streak.pop("harvest", None)
        t0 = time.perf_counter()
        # two-phase fetch: one small transfer of the per-slot flags gates
        # the call (the common case is "nothing finished"); the big seq
        # buffer only crosses the wire when some slot actually completed
        done, active = _host_fetch(
            (self.state["done"], self.state["active"]))
        ready = [i for i in range(self.num_slots)
                 if done[i] and active[i] and i in self._inflight]
        if not ready:
            return []
        seq, pos, start = _host_fetch(
            (self.state["seq"], self.state["pos"], self.state["start"]))
        out = []
        now = time.perf_counter()
        act = self.state["active"]
        for i in ready:
            r = self._inflight.pop(i)
            if self.paged:
                self._free_slot_pages(i)
            toks = seq[i, start[i]: pos[i] + 1].copy()
            reason = "eos" if (toks.size and toks[-1] == EOS_ID) else "length"
            comp = Completion(
                uid=r.uid, prime=np.asarray(r.tokens, np.int32),
                tokens=toks, finish_reason=reason,
                submit_time=r.submit_time, finish_time=now,
                generation=self.generation,
                first_token_time=self._ttft.pop(r.uid, None))
            out.append(comp)
            if r.on_complete is not None:
                r.on_complete(comp)
            act = act.at[i].set(False)
        self.state = {**self.state, "active": act}
        self.completions.extend(out)
        self._tracer.add("serve.harvest", t0, time.perf_counter() - t0,
                         uids=[c.uid for c in out])
        return out

    def _dispatch_chunk(self) -> None:
        """Run one guarded decode chunk.  A fatal fault on the paged
        Pallas kernel degrades to the bit-identical XLA fallback and
        retries; a fatal fault anywhere else sheds the in-flight batch
        (``_fail_inflight``) and the engine keeps serving; transient
        exhaustion escapes as :class:`RetryError` (restart-and-replay).
        """
        if self.paged:
            self._ensure_chunk_pages()
            if not self._inflight:
                return  # everything got evicted back to the queue
            args = (self._page_table.copy(), self._paused.copy())
        else:
            args = ()
        point = "serve.verify" if self.spec else "serve.decode_chunk"
        while True:
            t0 = time.perf_counter()
            try:
                with jax.profiler.TraceAnnotation("serve.decode_chunk"):
                    out = self._guard(point, self._chunk_call, *args,
                                      key=("chunk",))
                self._note_stage(
                    "decode_chunk_s", "serve.decode_chunk", t0,
                    uids=[r.uid for r in self._inflight.values()])
                if self.spec:
                    out, stats = out
                    # lazy device-side accumulation — spec_counters()
                    # fetches these once, off the hot path
                    self._spec_emitted = self._spec_emitted + \
                        stats["emitted"]
                    self._spec_verify_rounds = self._spec_verify_rounds + \
                        stats["rounds"]
                self.state = out
                self.chunks_run += 1
                return
            except (_ContainedFault, RetryError) as e:
                if self.paged and self.paged_impl == "pallas":
                    self._activate_xla_fallback()
                    continue  # bit-identical retry on the degraded path
                if isinstance(e, RetryError):
                    raise
                self._fail_inflight()
                return

    def _fail_inflight(self) -> None:
        """Shed every in-flight request (``FAILED_FAULT``) after a fatal
        decode fault: the batch's device state can no longer be trusted
        to advance, but queued requests are untouched — the engine keeps
        serving."""
        act = self.state["active"]
        for slot in sorted(self._inflight):
            r = self._inflight.pop(slot)
            if self.paged:
                self._host_stop[slot] = 0
                self._free_slot_pages(slot)
            self._shed(r, FAILED_FAULT)
            act = act.at[slot].set(False)
        self.state = {**self.state, "active": act}

    def step(self) -> list[Completion]:
        """One engine iteration: shed expired requests, admit queued ones
        into free slots, decode one chunk, harvest newly finished slots.
        The return includes typed SHED completions recorded since the
        last step (e.g. queue-full sheds from ``submit()``)."""
        completed = self._drain_pending()
        if self._watchdog is not None:
            self._watchdog.beat("serve.step")
        self._shed_expired()
        self._release_forks()
        if not self._draining:
            if self.disagg:
                self._admit_from_handoff()
            else:
                self._admit_pending()
        completed += self._drain_pending()
        completed += self._harvest_done()  # instant EOS/length at admission
        if self._inflight:
            self._dispatch_chunk()
            completed += self._drain_pending()
            completed += self._harvest_done()
        if self._embed_queue and not self._draining:
            # embed AFTER the decode chunk for the same reason the disagg
            # prefill round runs there: in-flight decode never stalls
            # behind prefill-shaped work
            self._embed_round()
            completed += self._drain_pending()
        if self.disagg and not self._draining:
            # prefill AFTER the decode chunk: in-flight decode never
            # stalls behind a long prefill (the disaggregation p95 win);
            # when the decode pool is idle there is nothing to protect,
            # so admit eagerly rather than pay a step of TTFT latency.
            # A remote-prefill replica never runs the prefill stage at
            # all — handles arrive via admit_handle() from the transport
            if not self.remote_prefill:
                self._prefill_round()
            if not self._inflight and self._handoff:
                self._admit_from_handoff()
                completed += self._drain_pending()
                completed += self._harvest_done()
        # refresh the per-class/per-tenant gauges once per step so
        # heartbeat-ridden registry snapshots carry current depths
        self.qos_status()
        if self.paged:
            self._publish_cache_gauges()
        return completed

    # ----------------------------------------- multi-process handoff API

    def admit_handle(self, handle: Handle) -> bool:
        """Remote-handoff admission source (docs/SERVING.md §7): push a
        deserialized prefill product into the bounded handoff queue
        beside the in-process path.  False when the queue is at depth —
        the transport keeps the frame buffered and retries after a
        ``step()`` frees a slot (cross-process backpressure)."""
        if not self.disagg:
            raise RuntimeError("admit_handle() requires disagg=True")
        if self._handoff.full():
            return False
        return self._handoff.put(handle)

    def run_prefill_round(self) -> Handle | None:
        """Run one prefill round and POP the produced handle instead of
        leaving it queued — the prefill-worker process serializes it onto
        the wire, so the local queue must not absorb the backpressure
        that belongs to the remote replicas (the worker's credit window
        does that).  None when the queue was empty or the round shed."""
        if not self.disagg:
            raise RuntimeError("run_prefill_round() requires disagg=True")
        before = len(self._handoff)
        self._prefill_round()
        if len(self._handoff) > before:
            return self._handoff.get()
        return None

    @property
    def embed_pending(self) -> int:
        return len(self._embed_queue)

    def run_embed_round(self) -> None:
        """Serve one embedding batch (if queued).  The prefill-worker
        process never calls ``step()``, so this is its path for running
        embed traffic; completions land in the pending list and ship
        home via :meth:`drain_sheds`."""
        self._embed_round()

    def drain_sheds(self) -> list[Completion]:
        """Collect typed shed completions recorded since the last call
        (submit-time sheds, failed prefill rounds).  The prefill-worker
        process never calls ``step()``, so this is its path for shipping
        sheds home as completion messages."""
        return self._drain_pending()

    def run_until_idle(self, max_chunks: int | None = None) -> list[Completion]:
        """Drain the queue and all in-flight slots; returns completions
        (served and shed) in finish order."""
        out: list[Completion] = []
        chunks0 = self.chunks_run
        while self.has_work:
            out.extend(self.step())
            if (max_chunks is not None
                    and self.chunks_run - chunks0 >= max_chunks):
                raise RuntimeError(
                    f"engine exceeded {max_chunks} chunks without draining "
                    f"({self.num_active} active, {self.pending} pending)"
                )
        return out

    # ----------------------------------------------------------- lifecycle

    def drain(self, max_chunks: int | None = None) -> list[Completion]:
        """Stop admission and finish all IN-FLIGHT requests.  The queue
        is left intact (snapshot it, or resume stepping); returns the
        completions finished during the drain."""
        self._draining = True
        try:
            out = self._drain_pending()
            chunks0 = self.chunks_run
            while self._inflight or self._pending:
                out.extend(self.step())
                if (max_chunks is not None
                        and self.chunks_run - chunks0 >= max_chunks):
                    raise RuntimeError(
                        f"drain exceeded {max_chunks} chunks with "
                        f"{self.num_active} slot(s) still active")
        finally:
            self._draining = False
        return out

    def snapshot(self, path: str | None = None) -> dict:
        """Host-side request state, enough to REPLAY every unfinished
        request on a fresh engine: prompt, sampling params, seed, and the
        remaining deadline budget.  Device caches are deliberately
        absent — trajectories depend only on (params, prime, seed,
        knobs), so replay-from-scratch is token-identical and the
        snapshot stays tiny and restore-compatible across engine shapes
        (slots, chunk size, paged or dense).  The generated-so-far prefix
        is stored for observability, not for resumption.

        In-flight slots are ordered before the queue so a restore serves
        older work first.  With ``path`` the snapshot is also written as
        JSON (atomic rename).
        """
        entries = []
        if self._inflight:
            active, seq, pos, start = _host_fetch(
                (self.state["active"], self.state["seq"],
                 self.state["pos"], self.state["start"]))
            for slot in sorted(self._inflight):
                r = self._inflight[slot]
                gen = (seq[slot, start[slot]: pos[slot] + 1].tolist()
                       if active[slot] else [])
                entries.append(self._snap_request(r, gen))
        if self.disagg:
            # handed-off-but-unmerged requests replay from scratch like
            # queued ones (their caches are rebuilt; token-identical)
            for h in self._handoff:
                for r in h.requests:
                    entries.append(self._snap_request(r, []))
        for r in self._queue:
            entries.append(self._snap_request(r, []))
        for followers in self._fork_wait.values():
            # held fork followers are queue-like: replay from scratch
            for r in followers:
                entries.append(self._snap_request(r, []))
        for r in self._embed_queue:
            e = self._snap_request(r, [])
            e["workload"] = "embed"
            entries.append(e)
        snap = {"version": 1, "kind": "serving_snapshot",
                "requests": entries}
        if path is not None:
            tmp = f"{path}.tmp"
            with open(tmp, "w") as fh:
                json.dump(snap, fh)
            os.replace(tmp, path)
        return snap

    def _snap_request(self, r: Request, generated) -> dict:
        entry = {
            "uid": r.uid,
            "tokens": [int(t) for t in r.tokens],
            "max_new_tokens": int(r.max_new_tokens),
            "top_k": None if r.top_k is None else int(r.top_k),
            "temperature": float(r.temperature),
            "seed": int(r.seed),
            "generated": [int(t) for t in generated],
        }
        if r.logit_mask is not None:
            from progen_tpu.workloads.infill import mask_to_wire
            entry["logit_mask"] = mask_to_wire(r.logit_mask)
        if int(r.tenant) != 0:
            entry["tenant"] = int(r.tenant)
        if int(r.priority) != 0:
            entry["priority"] = int(r.priority)
        deadline = self._deadline_of(r)
        if deadline is not None:
            # perf_counter instants do not survive a process restart;
            # the REMAINING budget does
            entry["deadline_remaining"] = max(
                0.0, deadline - time.perf_counter())
        return entry

    def restore(self, snap, *, on_complete=None) -> int:
        """Resubmit every request from a :meth:`snapshot` (dict or JSON
        path) onto this (idle) engine; returns the number accepted.
        Deadlines resume with their remaining budget.  Restored requests
        pass through the normal ``submit()`` path, so queue bounds and
        expired budgets shed exactly as live traffic would."""
        if isinstance(snap, (str, os.PathLike)):
            with open(snap) as fh:
                snap = json.load(fh)
        if snap.get("kind") != "serving_snapshot":
            raise ValueError("not a serving snapshot")
        if self._inflight or self._queue or self._embed_queue or \
                self._fork_wait or (self.disagg and self._handoff):
            raise RuntimeError("restore() requires an idle engine")
        now = time.perf_counter()
        accepted = 0
        for e in snap["requests"]:
            lmask = None
            if e.get("logit_mask") is not None:
                from progen_tpu.workloads.infill import mask_from_wire
                lmask = mask_from_wire(e["logit_mask"],
                                       self.config.num_tokens)
            r = Request(
                uid=e["uid"], tokens=e["tokens"],
                max_new_tokens=e["max_new_tokens"], top_k=e["top_k"],
                temperature=e["temperature"], seed=e["seed"],
                on_complete=on_complete, submit_time=now,
                logit_mask=lmask, tenant=int(e.get("tenant", 0)),
                priority=int(e.get("priority", 0)))
            if "deadline_remaining" in e:
                r.deadline = now + e["deadline_remaining"]
            if e.get("workload") == "embed":
                self.submit_embed(r)
            else:
                self.submit(r)
            accepted += 1
        return accepted

    # ----------------------------------------------------- warmup + counters

    def reload_weights(self, params=None, lora_bank=None, *,
                       generation: int | None = None) -> int:
        """Swap the served weights in place — no recompiles, no dropped
        slots.  Params (and the LoRA adapter bank) are real ARGUMENTS of
        every compiled program, so replacing the pytree with an
        identically-shaped one is just a different argument on the next
        dispatch; in-flight slots continue on the new weights from their
        next step, which is why the serving control plane instead swaps
        at WORKER granularity (drain old, route new) to keep
        per-generation determinism.  Returns the new generation tag
        (``generation`` when given, else the old tag + 1); completions
        finishing after the swap carry it.
        """
        if params is None and lora_bank is None:
            raise ValueError("reload_weights needs params and/or lora_bank")
        if lora_bank is not None and not self.lora:
            raise ValueError("engine was built without a LoRA bank; the "
                             "bank's shape is baked into its programs")
        if params is not None and self.quantize:
            # the serving tree is int8 + qscale; incoming checkpoints
            # arrive full precision and re-quantize at the door
            params = self._quantize_variables(
                jax.tree.map(jnp.asarray, params))

        def _swap(new, old, what):
            new = jax.tree.map(jnp.asarray, new)
            if jax.tree.structure(new) != jax.tree.structure(old):
                raise ValueError(f"reload_weights: {what} tree structure "
                                 "does not match the serving tree")
            for a, b in zip(jax.tree.leaves(new), jax.tree.leaves(old)):
                if a.shape != b.shape or a.dtype != b.dtype:
                    raise ValueError(
                        f"reload_weights: {what} leaf mismatch "
                        f"{a.shape}/{a.dtype} vs {b.shape}/{b.dtype}")
            return new

        if self.spec:
            if params is not None:
                self._params = {**self._params, "target": _swap(
                    params, self._params["target"], "params")}
        elif self.lora:
            bundle = dict(self._params)
            if params is not None:
                bundle["base"] = _swap(params, self._params["base"],
                                       "params")
            if lora_bank is not None:
                from progen_tpu.workloads.lora import validate_lora_bank

                validate_lora_bank(self.config, lora_bank)
                bundle["adapters"] = _swap(
                    lora_bank, self._params["adapters"], "lora_bank")
            self._params = bundle
        else:
            self._params = _swap(params, self._params, "params")
        self.generation = (int(generation) if generation is not None
                           else self.generation + 1)
        return self.generation

    def aot_warmup(self, max_prime: int | None = None, *,
                   embed: bool = False) -> dict:
        """Explicitly compile the engine's whole program grid ahead of
        serving: one admission program per prefill bucket (``window *
        2^k`` up to ``max_prime``, default ``max_len - 1``) plus the
        decode-chunk program, via ``jit(...).lower().compile()``.  With
        ``embed=True`` the per-bucket embedding programs compile too
        (opt-in — engines that never see embed traffic skip the cost).  The
        compiled executables are dispatched directly afterwards, so a
        fresh (or restarted) process pays zero first-request compiles —
        the cold-start TTFT story (``benchmarks/bench_coldstart.py``).
        Composes with the persistent compilation cache
        (``--compile_cache``), which turns these compiles into disk hits.
        """
        t0 = time.perf_counter()
        as_shape = partial(jax.tree.map,
                           lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype))
        s = self.num_slots

        def i32(*shape):
            return jax.ShapeDtypeStruct(shape, jnp.int32)

        # lower with the CONCRETE params/state so their shardings (mesh
        # mode) are captured; per-call host arrays lower as abstract
        params_sd, state_sd = as_shape(self._params), as_shape(self.state)
        programs = 0
        cap = min(max_prime or self.max_len - 1, self.max_len - 1)
        buckets = prime_buckets(self.config.window_size,
                                self.config.seq_len, cap)
        u32 = partial(jax.ShapeDtypeStruct, dtype=jnp.uint32)
        f32 = partial(jax.ShapeDtypeStruct, dtype=jnp.float32)
        b8 = partial(jax.ShapeDtypeStruct, dtype=jnp.bool_)
        L, V = self.max_len, self.config.num_tokens
        for p_pad in buckets:
            if embed and ("embed", p_pad) not in self._aot:
                tgt_sd = as_shape(self._target_params(self._params))
                self._aot[("embed", p_pad)] = self._embedder.lower(
                    tgt_sd, i32(s, p_pad), i32(s)).compile()
                self._compiled_keys.add(("embed", p_pad))
                programs += 1
            if self.disagg:
                key = ("prefill", p_pad)
                if key in self._aot:
                    continue
                pre_args = [params_sd, i32(s, p_pad), i32(s), i32(s),
                            u32((s,)), i32(s), f32((s,)), b8((s, L, V))]
                if self.lora:
                    pre_args += [i32(s)]
                self._aot[key] = (
                    self._prefill_worker.lower(*pre_args).compile())
                self._compiled_keys.add(key)
                programs += 1
                continue
            key = ("admit", p_pad)
            if key in self._aot:
                continue
            admit_args = [params_sd, state_sd, i32(s, p_pad), i32(s),
                          i32(s), u32((s,)), i32(s), f32((s,)), b8((s,)),
                          b8((s, L, V))]
            if self.paged:
                admit_args += [i32(s, self.pages_per_row),
                               i32(s, self.pages_per_row)]
            if self.lora:
                admit_args += [i32(s)]
            self._aot[key] = self._admit.lower(*admit_args).compile()
            self._compiled_keys.add(key)
            programs += 1
        if self.disagg and ("merge",) not in self._aot:
            # the handle's shape is bucket-independent (everything is
            # harvested to max_len), so any bucket's worker sizes it
            h_args = [params_sd, i32(s, buckets[0]), i32(s), i32(s),
                      u32((s,)), i32(s), f32((s,)), b8((s, L, V))]
            if self.lora:
                h_args += [i32(s)]
            h_sd = jax.eval_shape(self._prefill_worker_impl, *h_args)
            gate_sd: dict = {}
            if self.paged:
                gate_sd = h_sd["caches"]["sgu_gate"]
                h_sd = {**h_sd, "caches": {
                    k: v for k, v in h_sd["caches"].items()
                    if k != "sgu_gate"}}
            merge_args = [state_sd, h_sd, gate_sd, i32(s), b8((s,))]
            if self.paged:
                merge_args += [i32(s, self.pages_per_row)]
            self._aot[("merge",)] = (
                self._merge.lower(*merge_args).compile())
            self._compiled_keys.add(("merge",))
            programs += 1
        if ("chunk",) not in self._aot:
            chunk_args = [params_sd, state_sd]
            if self.paged:
                chunk_args += [i32(s, self.pages_per_row),
                               jax.ShapeDtypeStruct((s,), bool)]
            self._aot[("chunk",)] = (
                self._decode_chunk.lower(*chunk_args).compile())
            self._compiled_keys.add(("chunk",))
            programs += 1
        return {"programs": programs,
                "seconds": time.perf_counter() - t0}

    def spec_counters(self) -> dict:
        """Speculation throughput counters — ONE device fetch, so call
        this off the hot path (the chunk impls accumulate the counts
        lazily on device).  ``accepted_tokens_per_round`` above 1.0 means
        each fused verify round emitted more than one token on average:
        the dispatch-count win speculative decoding buys."""
        if not self.spec:
            return {}
        emitted, rounds = _host_fetch(
            (self._spec_emitted, self._spec_verify_rounds))
        emitted, rounds = int(emitted), int(rounds)
        return {
            "spec_k": self.spec_k,
            "spec_emitted_tokens": emitted,
            "spec_verify_rounds": rounds,
            "accepted_tokens_per_round":
                (emitted / rounds) if rounds else 0.0,
        }

    def status(self) -> dict:
        """Live engine state for the /statusz endpoint — HOST bookkeeping
        only (queues, slot maps, counters, stage walls).  This is served
        from the statusz HTTP thread concurrently with the stage loop, so
        it must never sync the device: ``spec_counters`` is deliberately
        absent (it costs a ``jax.device_get``), and everything read here
        is a plain host dict/int the GIL keeps coherent."""
        active = len(self._inflight)
        return {
            "slots": {"total": self.num_slots, "active": active,
                      "free": self.num_slots - active},
            "queue_depth": len(self._queue),
            "embed_queue_depth": len(self._embed_queue),
            "pending_completions": len(self._pending),
            "inflight_uids": sorted(r.uid for r in
                                    list(self._inflight.values())),
            "chunks_run": self.chunks_run,
            "paged": self.paged,
            "disagg": self.disagg,
            "spec": self.spec,
            "stage_seconds": {k: round(v, 6) for k, v in
                              list(self.stage_seconds.items())},
            "qos": self.qos_status(),
            "cache": self.cache_status(),
            "robust": self.robustness_counters(),
        }

    def cache_status(self) -> dict | None:
        """Prefix-cache occupancy and sharing for /statusz — host dicts
        only, safe from the statusz thread.  None on dense engines."""
        if not self.paged:
            return None
        pool = self._pool.stats()
        hits, lookups = self.prefix_hits, self.prefix_lookups
        return {
            "prefix_hits": hits,
            "prefix_lookups": lookups,
            "hit_rate": (hits / lookups) if lookups else 0.0,
            "pages_shared": pool["shared_pages"],
            "cached_pages": pool["cached_pages"],
            "free_pages": pool["free_pages"],
            "capacity": pool["capacity"],
            "fork_groups": self.fork_groups,
        }

    def prefix_digest(self) -> dict | None:
        """Compact advertisement of this engine's cached prefixes for
        fleet-scope routing (rides worker heartbeat/stats frames); None
        on dense engines, which cache nothing."""
        if not self.paged:
            return None
        return self._pool.prefix_digest()

    def _publish_cache_gauges(self) -> None:
        """Mirror cache counters into registry gauges so heartbeats and
        /metricsz carry per-worker hit-rate inputs without a bench run."""
        registry = _metrics.get_registry()
        registry.gauge("engine.prefix_hits").set(self.prefix_hits)
        registry.gauge("engine.prefix_lookups").set(self.prefix_lookups)
        registry.gauge("engine.prefix_pages_shared").set(
            self._pool.shared_pages)
        registry.gauge("engine.pool_free_pages").set(self._pool.free_pages)
        registry.gauge("engine.pool_pages_in_use").set(
            self._pool.capacity - self._pool.free_pages)

    def qos_status(self) -> dict:
        """Per-class / per-tenant queue + in-flight occupancy and the
        scheduler's cumulative tallies — host dicts only, safe from the
        statusz thread.  Also refreshes the labeled Prometheus gauges so
        a /metricsz scrape sees current depths."""
        out = dict(self._queue.stats())
        inflight_by_class: dict = {}
        inflight_by_tenant: dict = {}
        for r in list(self._inflight.values()):
            inflight_by_class[r.priority] = (
                inflight_by_class.get(r.priority, 0) + 1)
            inflight_by_tenant[r.tenant] = (
                inflight_by_tenant.get(r.tenant, 0) + 1)
        out["inflight_by_class"] = inflight_by_class
        out["inflight_by_tenant"] = inflight_by_tenant
        out["preemptions"] = self.robust.preemptions
        self._publish_qos_gauges(out)
        return out

    def _publish_qos_gauges(self, qos: dict) -> None:
        """Mirror the per-class/per-tenant occupancy into labeled
        registry gauges (Prometheus exposition + worker heartbeats).
        Label keys ever seen are re-set every refresh so a drained class
        reads 0 instead of its last nonzero value."""
        registry = _metrics.get_registry()
        fresh: set = set()
        for name, label, table in (
                ("engine.queue_depth", "priority", qos["queue_by_class"]),
                ("engine.queue_depth", "tenant", qos["queue_by_tenant"]),
                ("engine.inflight", "priority", qos["inflight_by_class"]),
                ("engine.inflight", "tenant", qos["inflight_by_tenant"])):
            for key, n in table.items():
                gname = _metrics.labeled(name, **{label: key})
                registry.gauge(gname).set(n)
                fresh.add(gname)
        for gname in self._qos_gauge_keys - fresh:
            registry.gauge(gname).set(0)
        self._qos_gauge_keys |= fresh
        registry.gauge("engine.preemptions").set(self.robust.preemptions)

    def robustness_counters(self) -> dict:
        """Everything a chaos record needs: shed/containment tallies,
        faults fired by the armed plan, QoS scheduling tallies, and
        (paged) pool pressure."""
        out = dict(self.robust.as_dict())
        injector = faults.get()
        out["faults_fired"] = injector.fired() if injector is not None else 0
        out["qos"] = self._queue.stats()
        if self.paged:
            out["evictions"] = self.evictions
            out["pause_events"] = self.pause_events
            out["prefix_hits"] = self.prefix_hits
            out["prefix_lookups"] = self.prefix_lookups
            out["fork_groups"] = self.fork_groups
            out["pool"] = self._pool.stats()
        if self.disagg:
            out["handoff"] = self._handoff.stats()
        return out


def run_with_restarts(engine_factory, requests=(), *, attempts: int = 3,
                      snapshot_path: str | None = None,
                      max_chunks: int | None = None,
                      classifier=default_classifier) -> list[Completion]:
    """Serve ``requests`` to completion across engine crashes: the
    serving twin of the trainer's ``--run_attempts`` resume loop.

    When a transient failure escapes the engine's in-place containment
    (a :class:`RetryError`, or anything ``classifier`` calls transient),
    the unfinished requests are snapshotted, a FRESH engine is built via
    ``engine_factory()``, the snapshot is restored onto it, and serving
    resumes.  Completions harvested before a crash are final (they are
    absent from the snapshot, so nothing double-serves); replayed
    requests are token-identical to an uninterrupted run because
    trajectories depend only on (params, prime, seed, knobs).
    Non-transient failures and attempt exhaustion re-raise.
    """
    out: list[Completion] = []
    engine = engine_factory()
    for r in requests:
        engine.submit(r)
    for attempt in range(1, max(1, attempts) + 1):
        try:
            out.extend(engine.run_until_idle(max_chunks=max_chunks))
            return out
        except Exception as e:
            if attempt >= attempts or not classifier(e):
                raise
            out.extend(engine.completions[:])
            snap = engine.snapshot(snapshot_path)
            print(f"serving: attempt {attempt} crashed ({e!r}); "
                  f"restarting and replaying {len(snap['requests'])} "
                  f"request(s)", flush=True)
            engine = engine_factory()
            engine.restore(snap)
    return out
