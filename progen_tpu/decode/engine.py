"""Continuous-batching serving engine: slots, chunked decode, refill.

The batch-synchronous sampler (``decode/sampler.py``) is the wrong shape
for serving: every request in a batch waits for the slowest one, and a
new request waits for the whole batch to drain.  This engine serves a
request QUEUE through a fixed set of SLOTS (vLLM/Ragged-Paged-Attention
style, PAPERS.md), with all device programs compiled once:

* **slots** — a fixed-size batch of per-slot state (sequence row, decode
  caches, position, done flag, RNG key, top-k/temperature).  Slots are
  independent: the decode step takes a ``(S,)`` position VECTOR
  (``ProGenDecodeStep``), so slot 3 can be at position 900 while slot 4
  is at position 12;
* **chunked decode** — ``chunk_size`` single-token steps per device
  program (one compile; position/done are data, not shape).  Rows that
  finish mid-chunk stop advancing; the host sees the done-mask between
  chunks, so cost is bounded by emitted tokens plus at most one chunk of
  slack per row;
* **refill** — between chunks, finished slots are harvested (completion
  callbacks fire) and refilled from the queue via the one-pass parallel
  prefill (``decode/prefill.py``): queued primes are padded into a
  ``(S, P_pad)`` ragged batch (``P_pad`` bucketed to ``window ·
  2^k`` so admission compiles O(log) programs, then cached), prefilled
  in ONE forward, and scattered into the free slots while live slots'
  state rides through untouched.

Determinism: each request carries its own seed; a request's token
trajectory depends only on (params, prime, seed, sampling knobs), never
on which slot it lands in or what else is in flight — asserted by
``tests/test_serving.py``.

Mesh-aware: pass ``mesh``/``strategies``/``params_shardings`` and the
engine runs SPMD with params left in their training shardings and
tp-sharded caches (``_constrain_caches``), same as the samplers.

EOS convention: primes are served verbatim (no BOS prepend); generation
stops at the first sampled pad/EOS token (id 0) or after
``max_new_tokens``.  The reference's "second zero" truncation is a
sampler-level concern; a serving request's prime is explicit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import deque
from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.decode.incremental import ProGenDecodeStep, init_caches
from progen_tpu.decode.prefill import (
    _constrain_caches,
    harvest_caches,
    pad_prime_length,
)
from progen_tpu.decode.sampler import gumbel_topk_sample_batched
from progen_tpu.models.progen import ProGen, ProGenConfig

EOS_ID = 0


@dataclasses.dataclass
class Request:
    """One generation request.

    ``tokens``: the prime, served verbatim (encode + add BOS upstream if
    desired); must be non-empty and leave room for at least one new
    token.  ``top_k=None`` disables top-k; ``temperature=0`` is greedy.
    """

    uid: Any
    tokens: Sequence[int]
    max_new_tokens: int = 128
    top_k: int | None = None
    temperature: float = 1.0
    seed: int = 0
    on_complete: Callable[["Completion"], None] | None = None
    submit_time: float = dataclasses.field(default_factory=time.perf_counter)


@dataclasses.dataclass
class Completion:
    """A finished request: ``tokens`` is the generated tail only (EOS
    included when the model emitted one)."""

    uid: Any
    prime: np.ndarray
    tokens: np.ndarray
    finish_reason: str  # "eos" | "length"
    submit_time: float
    finish_time: float

    @property
    def latency(self) -> float:
        return self.finish_time - self.submit_time


class ServingEngine:
    """Slot-based continuous-batching engine over a fixed device batch.

    ``num_slots`` is the max concurrent requests; ``chunk_size`` the
    decode steps per device program; ``max_len`` the sequence budget per
    slot (prime + generated, ≤ ``config.seq_len``).
    """

    def __init__(self, config: ProGenConfig, params, *,
                 policy: Policy | None = None, num_slots: int = 8,
                 chunk_size: int = 32, max_len: int | None = None,
                 mesh: Mesh | None = None,
                 strategies: Sequence[str] = ("dp",),
                 params_shardings=None):
        self.config = config
        self.policy = policy or make_policy()
        self.num_slots = num_slots
        self.chunk_size = chunk_size
        self.max_len = min(max_len or config.seq_len, config.seq_len)
        self.mesh = mesh
        self.strategies = tuple(strategies)
        self._queue: deque[Request] = deque()
        self._inflight: dict[int, Request] = {}  # slot -> request
        self.completions: list[Completion] = []
        self.chunks_run = 0

        if params_shardings is not None:
            params = jax.device_put(params, {"params": params_shardings})
        self._params = params

        if mesh is not None:
            from progen_tpu.parallel.sharding import logical_rules

            rules = logical_rules(self.strategies)

            def trace_ctx():
                stack = contextlib.ExitStack()
                stack.enter_context(mesh)
                stack.enter_context(nn.logical_axis_rules(rules))
                return stack
        else:
            trace_ctx = contextlib.ExitStack
        self._trace_ctx = trace_ctx

        self._step_model = ProGenDecodeStep(config=config, policy=self.policy)
        self._prefill_model = ProGen(config=config, policy=self.policy)
        self._decode_chunk = jax.jit(self._decode_chunk_impl)
        self._admit = jax.jit(self._admit_impl)
        self.state = self._init_state()

    # ---------------------------------------------------------------- state

    def _init_state(self) -> dict:
        s, L = self.num_slots, self.max_len
        with self._trace_ctx():
            caches = init_caches(self.config, s, self.policy, decode_len=L)
            if self.mesh is not None:
                caches = _constrain_caches(caches, self.mesh, self.strategies)
        keys = jax.vmap(jax.random.key)(jnp.zeros((s,), jnp.uint32))
        return {
            "seq": jnp.zeros((s, L), jnp.int32),
            "caches": caches,
            "pos": jnp.zeros((s,), jnp.int32),     # index of newest token
            "start": jnp.zeros((s,), jnp.int32),   # prime length
            "stop": jnp.zeros((s,), jnp.int32),    # start + max_new (≤ L)
            "active": jnp.zeros((s,), bool),
            "done": jnp.zeros((s,), bool),
            "keys": jax.random.key_data(keys),     # raw uint32 key data
            "top_k": jnp.zeros((s,), jnp.int32),   # 0 = disabled
            "temp": jnp.ones((s,), jnp.float32),
        }

    # ------------------------------------------------------------- decoding

    def _decode_chunk_impl(self, params, state):
        cfg = self.config

        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def body(st, _):
                live = st["active"] & ~st["done"]
                pos = st["pos"]
                tok = jnp.take_along_axis(st["seq"], pos[:, None],
                                          axis=1)[:, 0]
                logits, caches = self._step_model.apply(
                    params, tok, pos, st["caches"])
                keys = jax.random.wrap_key_data(st["keys"])
                split = jax.vmap(jax.random.split)(keys)  # (S, 2) keys
                nxt = gumbel_topk_sample_batched(
                    split[:, 1], logits, st["top_k"], st["temp"]
                ).astype(jnp.int32)
                writepos = jnp.clip(pos + 1, 0, self.max_len - 1)
                cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                          axis=1)[:, 0]
                val = jnp.where(live, nxt, cur)
                seq = st["seq"].at[
                    jnp.arange(self.num_slots), writepos].set(val)
                new_pos = jnp.where(live, pos + 1, pos)
                done = st["done"] | (live & (
                    (val == EOS_ID) | (new_pos + 1 >= st["stop"])))
                # a slot's key advances only on its own live steps, so a
                # request's trajectory is independent of its neighbours
                new_keys = jnp.where(
                    live[:, None], jax.random.key_data(split[:, 0]),
                    st["keys"])
                return {**st, "seq": seq, "caches": caches, "pos": new_pos,
                        "done": done, "keys": new_keys}, None

            state, _ = jax.lax.scan(body, state, None,
                                    length=self.chunk_size)
        return state

    def _admit_impl(self, params, state, tokens, lengths, stops, seeds,
                    top_k, temp, mask):
        """Prefill ``tokens (S, P_pad)`` in one parallel forward and merge
        rows where ``mask`` into ``state`` (rows outside ``mask`` carry
        dummy primes and are discarded)."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                params, tokens, mutable=["cache"])
            caches_new = harvest_caches(cfg, varz["cache"], lengths,
                                        self.policy, self.max_len)
            if self.mesh is not None:
                caches_new = _constrain_caches(caches_new, self.mesh,
                                               self.strategies)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        # p_pad is window-aligned and may overshoot L; real tokens never do
        # (submit enforces prime + 1 <= max_len), so truncation drops pad only
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        pos = lengths
        done = (first == EOS_ID) | (pos + 1 >= stops)

        def merge(new, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        merged_caches = jax.tree.map(merge, caches_new, state["caches"])
        return {
            "seq": merge(seq, state["seq"]),
            "caches": merged_caches,
            "pos": merge(pos, state["pos"]),
            "start": merge(lengths, state["start"]),
            "stop": merge(stops, state["stop"]),
            "active": merge(jnp.ones((s,), bool), state["active"]),
            "done": merge(done, state["done"]),
            "keys": merge(jax.random.key_data(split[:, 0]), state["keys"]),
            "top_k": merge(top_k, state["top_k"]),
            "temp": merge(temp, state["temp"]),
        }

    # ----------------------------------------------------------------- API

    def submit(self, request: Request) -> None:
        n = len(request.tokens)
        if n < 1:
            raise ValueError(f"request {request.uid!r}: empty prime")
        if n + 1 > self.max_len:
            raise ValueError(
                f"request {request.uid!r}: prime length {n} leaves no room "
                f"for generation (max_len {self.max_len})"
            )
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1")
        self._queue.append(request)

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def num_active(self) -> int:
        return len(self._inflight)

    def _admit_pending(self) -> None:
        free = [i for i in range(self.num_slots) if i not in self._inflight]
        if not free or not self._queue:
            return
        batch: list[tuple[int, Request]] = []
        while free and self._queue:
            batch.append((free.pop(0), self._queue.popleft()))

        s = self.num_slots
        longest = max(len(r.tokens) for _, r in batch)
        p_pad = pad_prime_length(longest, self.config.window_size,
                                 self.config.seq_len, bucket=True)
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        mask = np.zeros((s,), bool)
        for slot, r in batch:
            t = np.asarray(r.tokens, np.int32)
            tokens[slot, : len(t)] = t
            lengths[slot] = len(t)
            stops[slot] = min(len(t) + r.max_new_tokens, self.max_len)
            seeds[slot] = np.uint32(int(r.seed) & 0xFFFFFFFF)
            top_k[slot] = 0 if r.top_k is None else int(r.top_k)
            temp[slot] = float(r.temperature)
            mask[slot] = True
            self._inflight[slot] = r

        self.state = self._admit(
            self._params, self.state, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(stops), jnp.asarray(seeds),
            jnp.asarray(top_k), jnp.asarray(temp), jnp.asarray(mask))

    def _harvest_done(self) -> list[Completion]:
        # two-phase fetch: one small transfer of the per-slot flags gates
        # the call (the common case is "nothing finished"); the big seq
        # buffer only crosses the wire when some slot actually completed
        done, active = jax.device_get(  # graftcheck: disable=host-sync
            (self.state["done"], self.state["active"]))
        ready = [i for i in range(self.num_slots)
                 if done[i] and active[i] and i in self._inflight]
        if not ready:
            return []
        seq, pos, start = jax.device_get(  # graftcheck: disable=host-sync
            (self.state["seq"], self.state["pos"], self.state["start"]))
        out = []
        now = time.perf_counter()
        act = self.state["active"]
        for i in ready:
            r = self._inflight.pop(i)
            toks = seq[i, start[i]: pos[i] + 1].copy()
            reason = "eos" if (toks.size and toks[-1] == EOS_ID) else "length"
            comp = Completion(
                uid=r.uid, prime=np.asarray(r.tokens, np.int32),
                tokens=toks, finish_reason=reason,
                submit_time=r.submit_time, finish_time=now)
            out.append(comp)
            if r.on_complete is not None:
                r.on_complete(comp)
            act = act.at[i].set(False)
        self.state = {**self.state, "active": act}
        self.completions.extend(out)
        return out

    def step(self) -> list[Completion]:
        """One engine iteration: admit queued requests into free slots,
        decode one chunk, harvest newly finished slots."""
        self._admit_pending()
        completed = self._harvest_done()  # instant EOS/length at admission
        if self._inflight:
            self.state = self._decode_chunk(self._params, self.state)
            self.chunks_run += 1
            completed += self._harvest_done()
        return completed

    def run_until_idle(self, max_chunks: int | None = None) -> list[Completion]:
        """Drain the queue and all in-flight slots; returns completions in
        finish order."""
        out: list[Completion] = []
        chunks0 = self.chunks_run
        while self._queue or self._inflight:
            out.extend(self.step())
            if (max_chunks is not None
                    and self.chunks_run - chunks0 >= max_chunks):
                raise RuntimeError(
                    f"engine exceeded {max_chunks} chunks without draining "
                    f"({self.num_active} active, {self.pending} pending)"
                )
        return out
