"""Continuous-batching serving engine: slots, chunked decode, refill.

The batch-synchronous sampler (``decode/sampler.py``) is the wrong shape
for serving: every request in a batch waits for the slowest one, and a
new request waits for the whole batch to drain.  This engine serves a
request QUEUE through a fixed set of SLOTS (vLLM/Ragged-Paged-Attention
style, PAPERS.md), with all device programs compiled once:

* **slots** — a fixed-size batch of per-slot state (sequence row, decode
  caches, position, done flag, RNG key, top-k/temperature).  Slots are
  independent: the decode step takes a ``(S,)`` position VECTOR
  (``ProGenDecodeStep``), so slot 3 can be at position 900 while slot 4
  is at position 12;
* **chunked decode** — ``chunk_size`` single-token steps per device
  program (one compile; position/done are data, not shape).  Rows that
  finish mid-chunk stop advancing; the host sees the done-mask between
  chunks, so cost is bounded by emitted tokens plus at most one chunk of
  slack per row;
* **refill** — between chunks, finished slots are harvested (completion
  callbacks fire) and refilled from the queue via the one-pass parallel
  prefill (``decode/prefill.py``): queued primes are padded into a
  ``(S, P_pad)`` ragged batch (``P_pad`` bucketed to ``window ·
  2^k`` so admission compiles O(log) programs, then cached), prefilled
  in ONE forward, and scattered into the free slots while live slots'
  state rides through untouched.

Determinism: each request carries its own seed; a request's token
trajectory depends only on (params, prime, seed, sampling knobs), never
on which slot it lands in or what else is in flight — asserted by
``tests/test_serving.py``.

Mesh-aware: pass ``mesh``/``strategies``/``params_shardings`` and the
engine runs SPMD with params left in their training shardings and
tp-sharded caches (``_constrain_caches``), same as the samplers.

EOS convention: primes are served verbatim (no BOS prepend); generation
stops at the first sampled pad/EOS token (id 0) or after
``max_new_tokens``.  The reference's "second zero" truncation is a
sampler-level concern; a serving request's prime is explicit.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from collections import deque
from functools import partial
from typing import Any, Callable, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.decode.incremental import (
    ProGenDecodeStep,
    ProGenPagedDecodeStep,
    init_caches,
    init_gate_pool,
)
from progen_tpu.decode.paging import (
    DUMP_PAGE,
    NULL_PAGE,
    RESERVED_PAGES,
    PagePool,
    SlotPages,
    pages_for_span,
    prefix_key,
)
from progen_tpu.decode.prefill import (
    _constrain_caches,
    harvest_caches,
    harvest_gate_pages,
    pad_prime_length,
)
from progen_tpu.decode.sampler import gumbel_topk_sample_batched
from progen_tpu.models.progen import ProGen, ProGenConfig

EOS_ID = 0


@dataclasses.dataclass
class Request:
    """One generation request.

    ``tokens``: the prime, served verbatim (encode + add BOS upstream if
    desired); must be non-empty and leave room for at least one new
    token.  ``top_k=None`` disables top-k; ``temperature=0`` is greedy.
    """

    uid: Any
    tokens: Sequence[int]
    max_new_tokens: int = 128
    top_k: int | None = None
    temperature: float = 1.0
    seed: int = 0
    on_complete: Callable[["Completion"], None] | None = None
    submit_time: float = dataclasses.field(default_factory=time.perf_counter)


@dataclasses.dataclass
class Completion:
    """A finished request: ``tokens`` is the generated tail only (EOS
    included when the model emitted one)."""

    uid: Any
    prime: np.ndarray
    tokens: np.ndarray
    finish_reason: str  # "eos" | "length"
    submit_time: float
    finish_time: float

    @property
    def latency(self) -> float:
        return self.finish_time - self.submit_time


class ServingEngine:
    """Slot-based continuous-batching engine over a fixed device batch.

    ``num_slots`` is the max concurrent requests; ``chunk_size`` the
    decode steps per device program; ``max_len`` the sequence budget per
    slot (prime + generated, ≤ ``config.seq_len``).

    **Paged mode** (``paged=True``): the per-slot SGU gate cache — the
    one ``max_len``-sized buffer, i.e. this architecture's pageable "KV"
    — moves into a global page pool (``decode/paging.py``): pages are
    allocated on demand as positions advance, freed (refcounted) at
    completion, and shared across requests with a common prompt prefix.
    Admission is gated by free PAGES as well as free slots; when the pool
    runs dry mid-decode, starved slots are PAUSED (their rows freeze —
    position, key and sequence do not advance, so the trajectory is
    delayed, never altered) and, if every live slot is starved, the most
    recently admitted one is evicted back to the queue head (restart
    preemption: determinism means replaying it reproduces the identical
    prefix of tokens).  Greedy outputs are token-for-token identical to
    the fixed-slot engine — the XLA fallback contraction is bit-matched
    to the dense decode path (``ops/pallas_paged_attention.py``).

    ``num_pages`` counts pool pages incl. the 2 reserved ones (default:
    full budget — every slot can reach ``max_len``); ``paged_impl`` picks
    the ragged kernel (``"pallas"``) or the gather fallback (``"xla"``).
    """

    def __init__(self, config: ProGenConfig, params, *,
                 policy: Policy | None = None, num_slots: int = 8,
                 chunk_size: int = 32, max_len: int | None = None,
                 mesh: Mesh | None = None,
                 strategies: Sequence[str] = ("dp",),
                 params_shardings=None,
                 paged: bool = False, page_size: int = 16,
                 num_pages: int | None = None, paged_impl: str = "xla",
                 prefix_cache: bool = True):
        self.config = config
        self.policy = policy or make_policy()
        self.num_slots = num_slots
        self.chunk_size = chunk_size
        self.max_len = min(max_len or config.seq_len, config.seq_len)
        self.mesh = mesh
        self.strategies = tuple(strategies)
        self._queue: deque[Request] = deque()
        self._inflight: dict[int, Request] = {}  # slot -> request
        self.completions: list[Completion] = []
        self.chunks_run = 0

        if params_shardings is not None:
            params = jax.device_put(params, {"params": params_shardings})
        self._params = params

        if mesh is not None:
            from progen_tpu.parallel.sharding import logical_rules

            rules = logical_rules(self.strategies)

            def trace_ctx():
                stack = contextlib.ExitStack()
                stack.enter_context(mesh)
                stack.enter_context(nn.logical_axis_rules(rules))
                return stack
        else:
            trace_ctx = contextlib.ExitStack
        self._trace_ctx = trace_ctx

        self.paged = paged
        if paged:
            self.page_size = page_size
            self.pages_per_row = -(-self.max_len // page_size)
            if num_pages is None:
                num_pages = RESERVED_PAGES + num_slots * self.pages_per_row
            self._pool = PagePool(num_pages, page_size,
                                  prefix_caching=prefix_cache)
            self._slot_pages: dict[int, SlotPages] = {}
            self._page_table = np.zeros((num_slots, self.pages_per_row),
                                        np.int32)
            self._paused = np.zeros((num_slots,), bool)
            self._host_stop = np.zeros((num_slots,), np.int64)
            self._admit_seq = 0
            self._admit_order: dict[int, int] = {}  # slot -> admission seq
            self.evictions = 0
            self.pause_events = 0
            self.prefix_hits = 0
            self._paged_step_model = ProGenPagedDecodeStep(
                config=config, n_rows=self.max_len, policy=self.policy,
                impl=paged_impl)
            self._decode_chunk = jax.jit(self._decode_chunk_paged_impl)
            self._admit = jax.jit(self._admit_paged_impl)
        else:
            self._step_model = ProGenDecodeStep(config=config,
                                                policy=self.policy)
            self._decode_chunk = jax.jit(self._decode_chunk_impl)
            self._admit = jax.jit(self._admit_impl)
        self._prefill_model = ProGen(config=config, policy=self.policy)
        self.state = self._init_state()

    # ---------------------------------------------------------------- state

    def _init_state(self) -> dict:
        s, L = self.num_slots, self.max_len
        with self._trace_ctx():
            caches = init_caches(self.config, s, self.policy, decode_len=L,
                                 with_sgu=not self.paged)
            if self.paged:
                caches.pop("sgu_gate")
                caches["sgu_pool"] = init_gate_pool(
                    self.config, self._pool.num_pages, self.page_size,
                    self.policy)
            if self.mesh is not None:
                caches = _constrain_caches(caches, self.mesh, self.strategies)
        keys = jax.vmap(jax.random.key)(jnp.zeros((s,), jnp.uint32))
        return {
            "seq": jnp.zeros((s, L), jnp.int32),
            "caches": caches,
            "pos": jnp.zeros((s,), jnp.int32),     # index of newest token
            "start": jnp.zeros((s,), jnp.int32),   # prime length
            "stop": jnp.zeros((s,), jnp.int32),    # start + max_new (≤ L)
            "active": jnp.zeros((s,), bool),
            "done": jnp.zeros((s,), bool),
            "keys": jax.random.key_data(keys),     # raw uint32 key data
            "top_k": jnp.zeros((s,), jnp.int32),   # 0 = disabled
            "temp": jnp.ones((s,), jnp.float32),
        }

    # ------------------------------------------------------------- decoding

    def _decode_chunk_impl(self, params, state):
        cfg = self.config

        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def body(st, _):
                live = st["active"] & ~st["done"]
                pos = st["pos"]
                tok = jnp.take_along_axis(st["seq"], pos[:, None],
                                          axis=1)[:, 0]
                logits, caches = self._step_model.apply(
                    params, tok, pos, st["caches"])
                keys = jax.random.wrap_key_data(st["keys"])
                split = jax.vmap(jax.random.split)(keys)  # (S, 2) keys
                nxt = gumbel_topk_sample_batched(
                    split[:, 1], logits, st["top_k"], st["temp"]
                ).astype(jnp.int32)
                writepos = jnp.clip(pos + 1, 0, self.max_len - 1)
                cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                          axis=1)[:, 0]
                val = jnp.where(live, nxt, cur)
                seq = st["seq"].at[
                    jnp.arange(self.num_slots), writepos].set(val)
                new_pos = jnp.where(live, pos + 1, pos)
                done = st["done"] | (live & (
                    (val == EOS_ID) | (new_pos + 1 >= st["stop"])))
                # a slot's key advances only on its own live steps, so a
                # request's trajectory is independent of its neighbours
                new_keys = jnp.where(
                    live[:, None], jax.random.key_data(split[:, 0]),
                    st["keys"])
                return {**st, "seq": seq, "caches": caches, "pos": new_pos,
                        "done": done, "keys": new_keys}, None

            state, _ = jax.lax.scan(body, state, None,
                                    length=self.chunk_size)
        return state

    def _admit_impl(self, params, state, tokens, lengths, stops, seeds,
                    top_k, temp, mask):
        """Prefill ``tokens (S, P_pad)`` in one parallel forward and merge
        rows where ``mask`` into ``state`` (rows outside ``mask`` carry
        dummy primes and are discarded)."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                params, tokens, mutable=["cache"])
            caches_new = harvest_caches(cfg, varz["cache"], lengths,
                                        self.policy, self.max_len)
            if self.mesh is not None:
                caches_new = _constrain_caches(caches_new, self.mesh,
                                               self.strategies)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        # p_pad is window-aligned and may overshoot L; real tokens never do
        # (submit enforces prime + 1 <= max_len), so truncation drops pad only
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        pos = lengths
        done = (first == EOS_ID) | (pos + 1 >= stops)

        def merge(new, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        merged_caches = jax.tree.map(merge, caches_new, state["caches"])
        return {
            "seq": merge(seq, state["seq"]),
            "caches": merged_caches,
            "pos": merge(pos, state["pos"]),
            "start": merge(lengths, state["start"]),
            "stop": merge(stops, state["stop"]),
            "active": merge(jnp.ones((s,), bool), state["active"]),
            "done": merge(done, state["done"]),
            "keys": merge(jax.random.key_data(split[:, 0]), state["keys"]),
            "top_k": merge(top_k, state["top_k"]),
            "temp": merge(temp, state["temp"]),
        }

    # -------------------------------------------------------- paged decoding

    _RING_KEYS = ("attn_prev", "ff_prev", "k", "v")

    def _decode_chunk_paged_impl(self, params, state, table, paused):
        """Paged twin of ``_decode_chunk_impl``: the page ``table`` and
        ``paused`` mask ride in as data (host-side allocation decisions
        never retrace the program).  Paused rows run the step but are
        fully masked — sequence/position/key freeze AND their ring/carry
        writes are dropped (a paused row's carries still hold position
        ``pos-1``'s activations; letting the discarded speculative step
        overwrite them would corrupt the real step after unpausing).
        Pool writes are masked inside the step via ``write_ok``."""
        with self._trace_ctx():
            if self.mesh is not None:
                state = {**state, "caches": _constrain_caches(
                    state["caches"], self.mesh, self.strategies)}

            def body(st, _):
                live = st["active"] & ~st["done"] & ~paused
                pos = st["pos"]
                tok = jnp.take_along_axis(st["seq"], pos[:, None],
                                          axis=1)[:, 0]
                logits, caches = self._paged_step_model.apply(
                    params, tok, pos, st["caches"], table, live)

                def mrg(new, old):
                    m = live.reshape((-1,) + (1,) * (old.ndim - 1))
                    return jnp.where(m, new, old)

                caches = {
                    **{k: jax.tree.map(mrg, caches[k], st["caches"][k])
                       for k in self._RING_KEYS},
                    "sgu_pool": caches["sgu_pool"],
                }
                keys = jax.random.wrap_key_data(st["keys"])
                split = jax.vmap(jax.random.split)(keys)  # (S, 2) keys
                nxt = gumbel_topk_sample_batched(
                    split[:, 1], logits, st["top_k"], st["temp"]
                ).astype(jnp.int32)
                writepos = jnp.clip(pos + 1, 0, self.max_len - 1)
                cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                          axis=1)[:, 0]
                val = jnp.where(live, nxt, cur)
                seq = st["seq"].at[
                    jnp.arange(self.num_slots), writepos].set(val)
                new_pos = jnp.where(live, pos + 1, pos)
                done = st["done"] | (live & (
                    (val == EOS_ID) | (new_pos + 1 >= st["stop"])))
                # key advances only on the slot's own live steps (see the
                # dense body) — pausing therefore delays, never alters
                new_keys = jnp.where(
                    live[:, None], jax.random.key_data(split[:, 0]),
                    st["keys"])
                return {**st, "seq": seq, "caches": caches, "pos": new_pos,
                        "done": done, "keys": new_keys}, None

            state, _ = jax.lax.scan(body, state, None,
                                    length=self.chunk_size)
        return state

    def _admit_paged_impl(self, params, state, tokens, lengths, stops,
                          seeds, top_k, temp, mask, table, wtable):
        """Paged twin of ``_admit_impl``: rings/carries harvest and merge
        as in the dense path, but gate rows scatter straight into the
        page pool through the WRITE table (``wtable`` — private pages
        only; prefix-shared and dummy rows dump)."""
        cfg = self.config
        with self._trace_ctx():
            logits, varz = self._prefill_model.apply(
                params, tokens, mutable=["cache"])
            caches_new = harvest_caches(cfg, varz["cache"], lengths,
                                        self.policy, self.max_len,
                                        with_sgu=False)
            pool_new = harvest_gate_pages(
                cfg, varz["cache"], lengths,
                state["caches"]["sgu_pool"], wtable, self.policy)
            if self.mesh is not None:
                caches_new = _constrain_caches(caches_new, self.mesh,
                                               self.strategies)

        last = jnp.take_along_axis(
            logits, (lengths - 1)[:, None, None], axis=1
        )[:, 0].astype(jnp.float32)
        keys = jax.vmap(jax.random.key)(seeds.astype(jnp.uint32))
        split = jax.vmap(jax.random.split)(keys)
        first = gumbel_topk_sample_batched(
            split[:, 1], last, top_k, temp).astype(jnp.int32)

        s, L = self.num_slots, self.max_len
        p_pad = tokens.shape[1]
        tok_L = tokens[:, :L] if p_pad >= L else jnp.pad(
            tokens, ((0, 0), (0, L - p_pad)))
        seq = tok_L * (jnp.arange(L)[None, :] < lengths[:, None])
        seq = seq.at[jnp.arange(s), lengths].set(first)
        pos = lengths
        done = (first == EOS_ID) | (pos + 1 >= stops)

        def merge(new, old):
            m = mask.reshape((-1,) + (1,) * (old.ndim - 1))
            return jnp.where(m, new, old)

        merged_caches = {
            **{k: jax.tree.map(merge, caches_new[k], state["caches"][k])
               for k in self._RING_KEYS},
            "sgu_pool": pool_new,
        }
        return {
            "seq": merge(seq, state["seq"]),
            "caches": merged_caches,
            "pos": merge(pos, state["pos"]),
            "start": merge(lengths, state["start"]),
            "stop": merge(stops, state["stop"]),
            "active": merge(jnp.ones((s,), bool), state["active"]),
            "done": merge(done, state["done"]),
            "keys": merge(jax.random.key_data(split[:, 0]), state["keys"]),
            "top_k": merge(top_k, state["top_k"]),
            "temp": merge(temp, state["temp"]),
        }

    # ----------------------------------------------------------------- API

    def submit(self, request: Request) -> None:
        n = len(request.tokens)
        if n < 1:
            raise ValueError(f"request {request.uid!r}: empty prime")
        if n + 1 > self.max_len:
            raise ValueError(
                f"request {request.uid!r}: prime length {n} leaves no room "
                f"for generation (max_len {self.max_len})"
            )
        if request.max_new_tokens < 1:
            raise ValueError(
                f"request {request.uid!r}: max_new_tokens must be >= 1")
        if self.paged:
            stop = min(n + request.max_new_tokens, self.max_len)
            worst = pages_for_span(stop - 1, self.page_size)
            if worst > self._pool.capacity:
                raise ValueError(
                    f"request {request.uid!r}: needs up to {worst} pages "
                    f"but the pool only has {self._pool.capacity} — "
                    f"raise num_pages or lower max_new_tokens")
        self._queue.append(request)

    @property
    def pending(self) -> int:
        return len(self._queue)

    @property
    def num_active(self) -> int:
        return len(self._inflight)

    def _admit_pending(self) -> None:
        if self.paged:
            self._admit_pending_paged()
            return
        free = [i for i in range(self.num_slots) if i not in self._inflight]
        if not free or not self._queue:
            return
        batch: list[tuple[int, Request]] = []
        while free and self._queue:
            batch.append((free.pop(0), self._queue.popleft()))

        s = self.num_slots
        longest = max(len(r.tokens) for _, r in batch)
        p_pad = pad_prime_length(longest, self.config.window_size,
                                 self.config.seq_len, bucket=True)
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        mask = np.zeros((s,), bool)
        for slot, r in batch:
            t = np.asarray(r.tokens, np.int32)
            tokens[slot, : len(t)] = t
            lengths[slot] = len(t)
            stops[slot] = min(len(t) + r.max_new_tokens, self.max_len)
            seeds[slot] = np.uint32(int(r.seed) & 0xFFFFFFFF)
            top_k[slot] = 0 if r.top_k is None else int(r.top_k)
            temp[slot] = float(r.temperature)
            mask[slot] = True
            self._inflight[slot] = r

        self.state = self._admit(
            self._params, self.state, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(stops), jnp.asarray(seeds),
            jnp.asarray(top_k), jnp.asarray(temp), jnp.asarray(mask))

    def _admit_pending_paged(self) -> None:
        """FIFO admission gated by free slots AND free pages.

        The head of the queue is admitted only if the pool can cover its
        whole prime plus the first sampled token WITHOUT prefix sharing
        (a conservative bound — actual planning below shares whatever it
        can, so the allocation never exceeds the reservation); a blocked
        head DEFERS everything behind it (no starvation reordering).
        """
        free = [i for i in range(self.num_slots) if i not in self._inflight]
        batch: list[tuple[int, Request]] = []
        reserved = 0
        while free and self._queue:
            r = self._queue[0]
            need = pages_for_span(len(r.tokens), self.page_size)
            if not self._pool.can_allocate(reserved + need):
                break  # head-of-line blocks: deferral, not reordering
            reserved += need
            batch.append((free.pop(0), self._queue.popleft()))
        if not batch:
            return

        s = self.num_slots
        longest = max(len(r.tokens) for _, r in batch)
        p_pad = pad_prime_length(longest, self.config.window_size,
                                 self.config.seq_len, bucket=True)
        tokens = np.zeros((s, p_pad), np.int32)
        lengths = np.ones((s,), np.int32)  # dummy rows: 1-token prime
        stops = np.full((s,), 2, np.int32)
        seeds = np.zeros((s,), np.uint32)
        top_k = np.zeros((s,), np.int32)
        temp = np.ones((s,), np.float32)
        mask = np.zeros((s,), bool)
        wtable = np.full((s, self.pages_per_row), DUMP_PAGE, np.int32)
        for slot, r in batch:
            t = np.asarray(r.tokens, np.int32)
            tokens[slot, : len(t)] = t
            lengths[slot] = len(t)
            stops[slot] = min(len(t) + r.max_new_tokens, self.max_len)
            seeds[slot] = np.uint32(int(r.seed) & 0xFFFFFFFF)
            top_k[slot] = 0 if r.top_k is None else int(r.top_k)
            temp[slot] = float(r.temperature)
            mask[slot] = True
            self._inflight[slot] = r
            self._host_stop[slot] = stops[slot]
            self._admit_order[slot] = self._admit_seq
            self._admit_seq += 1
            self._paused[slot] = False
            self._plan_slot_pages(slot, r, p_pad, wtable)

        self.state = self._admit(
            self._params, self.state, jnp.asarray(tokens),
            jnp.asarray(lengths), jnp.asarray(stops), jnp.asarray(seeds),
            jnp.asarray(top_k), jnp.asarray(temp), jnp.asarray(mask),
            jnp.asarray(self._page_table), jnp.asarray(wtable))

    def _plan_slot_pages(self, slot: int, r: Request, p_pad: int,
                         wtable: np.ndarray) -> None:
        """Build the slot's page list for rows ``[0, P]`` (prime + first
        sampled token): longest run of prefix-cache hits first, fresh
        private pages for the rest.  Fills the slot's ``_page_table`` row
        and its ``wtable`` row (private pages only — shared pages were
        filled by the request that first computed them and MUST stay
        read-only: rewriting them from a different prefill batch shape
        could perturb the sharer's bits)."""
        ps = self.page_size
        p = len(r.tokens)
        n_pages = p // ps + 1  # decode writes row P before any page grows
        n_full = p // ps       # full pages strictly inside the prime
        shared: list[int] = []
        for j in range(n_full):
            pid = self._pool.lookup_prefix(prefix_key(p_pad, r.tokens,
                                                      (j + 1) * ps))
            if pid is None:
                break
            shared.append(pid)
        fresh = self._pool.allocate(n_pages - len(shared))
        assert fresh is not None, "admission reserved pages conservatively"
        for pid in shared:
            self._pool.retain(pid)
        self.prefix_hits += len(shared)
        pages = shared + fresh
        for j in range(len(shared), n_full):
            self._pool.register_prefix(
                prefix_key(p_pad, r.tokens, (j + 1) * ps), pages[j])
        self._slot_pages[slot] = SlotPages(pages=pages, shared=len(shared))
        self._page_table[slot, :] = NULL_PAGE
        self._page_table[slot, : n_pages] = pages
        wtable[slot, : n_pages] = [DUMP_PAGE] * len(shared) + fresh

    def _free_slot_pages(self, slot: int) -> None:
        sp = self._slot_pages.pop(slot, None)
        if sp is None:
            return
        for pid in sp.pages:
            self._pool.release(pid)
        self._page_table[slot, :] = NULL_PAGE
        self._paused[slot] = False
        self._admit_order.pop(slot, None)

    def _evict_slot(self, slot: int) -> None:
        """Restart preemption: free the slot's pages and push its request
        back to the FRONT of the queue.  Replaying from scratch is safe —
        a trajectory depends only on (params, prime, seed, knobs), so the
        re-decode reproduces the identical token prefix."""
        r = self._inflight.pop(slot)
        self._free_slot_pages(slot)
        self.state = {**self.state, "active":
                      self.state["active"].at[slot].set(False)}
        self._queue.appendleft(r)
        self.evictions += 1

    def _ensure_chunk_pages(self) -> None:
        """Before each chunk, grow every live slot's page list to cover
        all positions the chunk can write (``[pos, min(pos+chunk,
        stop)-1]``).  Slots the pool cannot cover are PAUSED for this
        chunk (their rows freeze entirely); if the pool starves every
        live slot, the youngest is evicted until someone can run."""
        if not self._inflight:
            return
        pos = jax.device_get(  # graftcheck: disable=host-sync
            self.state["pos"])
        for _ in range(len(self._inflight) + 1):
            slots = sorted(self._inflight, key=self._admit_order.__getitem__)
            for slot in slots:
                # last position the chunk can consume: done fires when
                # new_pos + 1 >= stop, so a live slot never consumes past
                # stop - 2; gate rows are written at consumed positions
                last = min(int(pos[slot]) + self.chunk_size - 1,
                           int(self._host_stop[slot]) - 2)
                need = pages_for_span(last, self.page_size)
                sp = self._slot_pages[slot]
                delta = need - len(sp.pages)
                if delta <= 0:
                    self._paused[slot] = False
                    continue
                fresh = self._pool.allocate(delta)
                if fresh is None:
                    if not self._paused[slot]:
                        self.pause_events += 1
                    self._paused[slot] = True
                    continue
                base = len(sp.pages)
                sp.pages.extend(fresh)
                self._page_table[slot, base: base + delta] = fresh
                self._paused[slot] = False
            if any(not self._paused[s] for s in self._inflight):
                return
            # every live slot starved: evict the most recently admitted
            victim = max(self._inflight, key=self._admit_order.__getitem__)
            if len(self._inflight) == 1:
                raise RuntimeError(
                    f"page pool too small for any progress: slot {victim} "
                    f"needs pages beyond capacity {self._pool.capacity} "
                    f"with nothing left to evict")
            self._evict_slot(victim)

    def _harvest_done(self) -> list[Completion]:
        # two-phase fetch: one small transfer of the per-slot flags gates
        # the call (the common case is "nothing finished"); the big seq
        # buffer only crosses the wire when some slot actually completed
        done, active = jax.device_get(  # graftcheck: disable=host-sync
            (self.state["done"], self.state["active"]))
        ready = [i for i in range(self.num_slots)
                 if done[i] and active[i] and i in self._inflight]
        if not ready:
            return []
        seq, pos, start = jax.device_get(  # graftcheck: disable=host-sync
            (self.state["seq"], self.state["pos"], self.state["start"]))
        out = []
        now = time.perf_counter()
        act = self.state["active"]
        for i in ready:
            r = self._inflight.pop(i)
            if self.paged:
                self._free_slot_pages(i)
            toks = seq[i, start[i]: pos[i] + 1].copy()
            reason = "eos" if (toks.size and toks[-1] == EOS_ID) else "length"
            comp = Completion(
                uid=r.uid, prime=np.asarray(r.tokens, np.int32),
                tokens=toks, finish_reason=reason,
                submit_time=r.submit_time, finish_time=now)
            out.append(comp)
            if r.on_complete is not None:
                r.on_complete(comp)
            act = act.at[i].set(False)
        self.state = {**self.state, "active": act}
        self.completions.extend(out)
        return out

    def step(self) -> list[Completion]:
        """One engine iteration: admit queued requests into free slots,
        decode one chunk, harvest newly finished slots."""
        self._admit_pending()
        completed = self._harvest_done()  # instant EOS/length at admission
        if self._inflight:
            if self.paged:
                self._ensure_chunk_pages()
                self.state = self._decode_chunk(
                    self._params, self.state,
                    jnp.asarray(self._page_table),
                    jnp.asarray(self._paused))
            else:
                self.state = self._decode_chunk(self._params, self.state)
            self.chunks_run += 1
            completed += self._harvest_done()
        return completed

    def run_until_idle(self, max_chunks: int | None = None) -> list[Completion]:
        """Drain the queue and all in-flight slots; returns completions in
        finish order."""
        out: list[Completion] = []
        chunks0 = self.chunks_run
        while self._queue or self._inflight:
            out.extend(self.step())
            if (max_chunks is not None
                    and self.chunks_run - chunks0 >= max_chunks):
                raise RuntimeError(
                    f"engine exceeded {max_chunks} chunks without draining "
                    f"({self.num_active} active, {self.pending} pending)"
                )
        return out
