"""One-pass parallel prefill: prime the decode caches with ONE forward.

The sampler historically teacher-forced the prime through O(P) sequential
single-token decode steps — P latency-bound dispatches of tiny matmuls.
Serving throughput on TPU is won by splitting prefill from decode (the
Ragged Paged Attention lesson, PAPERS.md): the prime is processed by the
existing batched PARALLEL ProGen forward ONCE — MXU-shaped matmuls over
all P positions — and the per-layer state the incremental decoder needs
is harvested from sown intermediates into the decode caches:

* **k/v rings** — the parallel forward sows post-rotary k/v ``(B, H, P,
  Dh)`` per layer (``models/progen.py``); ring slot ``s`` receives the
  LAST prime position congruent to ``s`` mod ``2w`` (exactly what a
  sequential scan would have left there), slots with no such position
  stay zero (the phantom zero-pad window before position 0);
* **token-shift carries** — each block sows its post-norm (pre-shift)
  activations; the carry is row ``P-1``;
* **SGU gate caches** — the gMLP layers sow the normed gate activations;
  rows ``[0, P)`` are copied in, later rows stay zero (they are written
  by decode before they are causally readable).

Ragged primes: ``lengths`` is a per-row vector, so one padded ``(B,
P_pad)`` prefill call harvests caches for rows of different prime
lengths — the continuous-batching engine admits a mixed batch of queued
requests in one forward.  Exactness vs the sequential path is asserted
by ``tests/test_serving.py`` (cache parity + logits parity against
``teacher_forced_logits``).

``P_pad`` must be a multiple of ``window_size`` (the parallel attention's
window layout) and ≤ ``seq_len``; right-padding with any token is safe —
causality keeps positions ``< lengths[b]`` independent of the pad tail,
and every harvested value is masked to real positions.
"""

from __future__ import annotations

import contextlib
from functools import partial
from typing import Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from progen_tpu.core.precision import Policy, make_policy
from progen_tpu.models.progen import ProGen, ProGenConfig


def pad_prime_length(p: int, window_size: int, seq_len: int,
                     bucket: bool = False) -> int:
    """Padded prefill length for a ``p``-token prime.

    Always a multiple of ``window_size`` and capped at ``seq_len``.  With
    ``bucket=True`` the length additionally rounds up to ``window_size *
    2^k`` so the serving engine compiles O(log(seq_len/window)) prefill
    programs instead of one per distinct prime length.
    """
    if not (0 < p <= seq_len):
        raise ValueError(f"prime length {p} must be in (0, {seq_len}]")
    windows = -(-p // window_size)
    if bucket:
        b = 1
        while b < windows:
            b *= 2
        windows = b
    return min(windows * window_size, seq_len)


def prime_buckets(window_size: int, seq_len: int,
                  max_prime: int | None = None) -> list[int]:
    """Every bucketed prefill length a serving engine can dispatch:
    ``window_size * 2^k`` capped at ``seq_len``, for primes up to
    ``max_prime`` (default ``seq_len``).  This is the admission program
    grid an AOT warmup must compile — O(log(seq_len/window)) shapes.
    """
    cap = min(max_prime or seq_len, seq_len)
    out: list[int] = []
    p = 1
    while p <= cap:
        b = pad_prime_length(p, window_size, seq_len, bucket=True)
        if not out or b != out[-1]:
            out.append(b)
        if b >= cap:
            break
        p = b + 1
    return out


def _constrain_caches(caches, mesh: Mesh, strategies: Sequence[str]):
    """Pin the decode caches' layouts over the mesh.

    Only tensor parallelism shards real decode state: the k/v rings split
    on heads and the SGU gate cache on its channel half, matching the tp
    rule table (``parallel/sharding.py``) so the per-step attention and
    gate contractions stay local to each tensor shard.  Everything else
    (tiny per-block carries) replicates — decode batches are small and
    fsdp's win is the PARAMS staying sharded, which they do via
    ``params_shardings``.
    """
    if "tp" not in strategies or mesh.shape.get("tensor", 1) <= 1:
        return caches
    wsc = jax.lax.with_sharding_constraint
    kv = NamedSharding(mesh, PartitionSpec(None, "tensor", None, None))
    gate = NamedSharding(mesh, PartitionSpec(None, None, "tensor"))
    out = {
        **caches,
        "k": [wsc(x, kv) for x in caches["k"]],
        "v": [wsc(x, kv) for x in caches["v"]],
    }
    if caches.get("sgu_gate"):
        out["sgu_gate"] = {k: wsc(v, gate) for k, v in
                           caches["sgu_gate"].items()}
    if caches.get("sgu_pool"):
        # pooled gate rows shard on the channel half like the dense cache
        out["sgu_pool"] = {k: wsc(v, gate) for k, v in
                           caches["sgu_pool"].items()}
    return out


def _take_row(x, idx):
    """``x (B, L, ...)``, ``idx (B,)`` -> ``x[b, idx[b]] (B, ...)``."""
    return jax.vmap(lambda row, i: jax.lax.dynamic_index_in_dim(
        row, i, axis=0, keepdims=False))(x, idx)


def harvest_caches(config: ProGenConfig, sown: dict, lengths, policy: Policy,
                   decode_len: int, with_sgu: bool = True) -> dict:
    """Build decode caches from the parallel forward's sown "cache"
    collection, per-row masked to ``lengths``.

    ``with_sgu=False`` skips the dense per-slot gate cache (the paged
    engine scatters gate rows straight into the global page pool via
    :func:`harvest_gate_pages` instead — no ``(B, n_rows, half)`` slab is
    ever materialized).
    """
    c = config
    pol = policy
    ring = 2 * c.window_size
    n_rows = min(decode_len, c.seq_len)
    last = lengths - 1  # (B,)

    caches = {"attn_prev": [], "ff_prev": [], "k": [], "v": [], "sgu_gate": {}}
    for i in range(c.depth):
        attn = sown[f"attn{i}"]
        k_all = attn["k"][0]   # (B, H, P_pad, Dh) post-rotary
        v_all = attn["v"][0]
        prev_a = attn["prev"][0]  # (B, P_pad, dim) post-norm
        ff = sown[f"ff{i}"]
        prev_f = ff["prev"][0]

        caches["attn_prev"].append(_take_row(prev_a, last))
        caches["ff_prev"].append(_take_row(prev_f, last))

        # ring slot s <- last prime position congruent to s (mod ring);
        # no such position (short primes) -> the slot stays zero, the
        # phantom zero-pad window the sequential path also leaves there
        s = jnp.arange(ring)[None, :]
        q_s = last[:, None] - jnp.mod(last[:, None] - s, ring)  # (B, ring)
        live = q_s >= 0
        idx = jnp.clip(q_s, 0)[:, None, :, None]  # (B, 1, ring, 1)
        k_ring = jnp.take_along_axis(k_all, idx, axis=2)
        v_ring = jnp.take_along_axis(v_all, idx, axis=2)
        m = live[:, None, :, None]
        caches["k"].append(jnp.where(m, k_ring, 0).astype(pol.compute_dtype))
        caches["v"].append(jnp.where(m, v_ring, 0).astype(pol.compute_dtype))

        if c.layer_uses_gmlp(i) and with_sgu:
            gate = ff["sgu"]["gate"][0]  # (B, P_pad, hidden/2) normed
            b, p_pad, half = gate.shape
            rows = jnp.zeros((b, n_rows, half), pol.compute_dtype)
            upto = min(p_pad, n_rows)
            keep = (jnp.arange(upto)[None, :, None] < lengths[:, None, None])
            rows = rows.at[:, :upto].set(
                jnp.where(keep, gate[:, :upto], 0).astype(pol.compute_dtype))
            caches["sgu_gate"][str(i)] = rows
    return caches


def harvest_gate_pages(config: ProGenConfig, sown: dict, lengths, pool: dict,
                       wtable, policy: Policy, pool_scale: dict | None = None):
    """Scatter the prefill's sown gate rows straight into the page pool.

    The paged engine's admission path: instead of building a contiguous
    ``(B, n_rows, half)`` gate cache, each prime row ``i`` of request
    ``b`` is scattered to pool page ``wtable[b, i // page_size]`` at
    offset ``i % page_size``.  ``wtable`` is the WRITE table: it names the
    request's freshly allocated private pages and holds ``DUMP_PAGE`` for
    pages it must not write — prefix-cache hits (read-only, filled by the
    first request that computed them) and unowned tail entries.  Pad rows
    (``i >= lengths[b]``) are dumped too, so the scatter stays dense.

    With ``pool_scale`` (the f32 twin of an int8 pool, see
    ``init_gate_scale``) every gate row is quantized per-row before the
    scatter and the call returns ``(new_pool, new_scale)``.
    """
    from progen_tpu.decode.paging import DUMP_PAGE
    from progen_tpu.ops.quant import quantize_rows

    c = config
    new_pool = dict(pool)
    new_scale = dict(pool_scale) if pool_scale is not None else None
    for i in range(c.depth):
        if not c.layer_uses_gmlp(i):
            continue
        gate = sown[f"ff{i}"]["sgu"]["gate"][0]  # (B, P_pad, half) normed
        b, p_pad, half = gate.shape
        layer_pool = pool[str(i)]  # (num_pages, page_size, half)
        page_size = layer_pool.shape[1]
        pages_per_row = wtable.shape[1]
        rows = jnp.arange(p_pad)
        # the window-aligned P_pad can overshoot the table span; clamp the
        # page index — every overshooting row is >= lengths and dumped
        page_idx = jnp.minimum(rows // page_size, pages_per_row - 1)
        tgt = wtable[:, page_idx]  # (B, P_pad)
        tgt = jnp.where(rows[None, :] < lengths[:, None], tgt, DUMP_PAGE)
        off = jnp.broadcast_to((rows % page_size)[None, :], (b, p_pad))
        if new_scale is None:
            new_pool[str(i)] = layer_pool.at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(gate.astype(layer_pool.dtype).reshape(-1, half))
        else:
            q, s = quantize_rows(gate)  # (B, P_pad, half) int8, (B, P_pad)
            new_pool[str(i)] = layer_pool.at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(q.reshape(-1, half))
            new_scale[str(i)] = pool_scale[str(i)].at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(s.reshape(-1))
    if new_scale is not None:
        return new_pool, new_scale
    return new_pool


def scatter_gate_rows(config: ProGenConfig, gate_rows: dict, lengths,
                      pool: dict, wtable, pool_scale: dict | None = None):
    """Scatter DENSE per-row gate slabs into the page pool.

    The disaggregated admission path (``decode/handoff.py``): the
    prefill worker hands off ``(B, n_rows, half)`` gate slabs per gMLP
    layer (keyed ``str(i)`` like the dense cache), and the decode pool's
    merge program scatters each handle row ``i < lengths[b]`` to page
    ``wtable[b, i // page_size]`` at offset ``i % page_size`` — the same
    contract as :func:`harvest_gate_pages`, with the slab (not the sown
    prefill intermediates) as the source.  ``wtable`` rows for prefix-
    shared pages, unadmitted handle rows and pad tails hold
    ``DUMP_PAGE``.

    Handle slabs ride the handoff in the COMPUTE dtype regardless of the
    pool's format (the prefill worker cannot know the decode pool's page
    layout); with ``pool_scale`` the rows are quantized here, at the
    merge, and the call returns ``(new_pool, new_scale)``.
    """
    from progen_tpu.decode.paging import DUMP_PAGE
    from progen_tpu.ops.quant import quantize_rows

    new_pool = dict(pool)
    new_scale = dict(pool_scale) if pool_scale is not None else None
    for i in range(config.depth):
        if not config.layer_uses_gmlp(i):
            continue
        gate = gate_rows[str(i)]  # (B, n_rows, half)
        b, n_rows, half = gate.shape
        layer_pool = pool[str(i)]  # (num_pages, page_size, half)
        page_size = layer_pool.shape[1]
        pages_per_row = wtable.shape[1]
        rows = jnp.arange(n_rows)
        page_idx = jnp.minimum(rows // page_size, pages_per_row - 1)
        tgt = wtable[:, page_idx]  # (B, n_rows)
        tgt = jnp.where(rows[None, :] < lengths[:, None], tgt, DUMP_PAGE)
        off = jnp.broadcast_to((rows % page_size)[None, :], (b, n_rows))
        if new_scale is None:
            new_pool[str(i)] = layer_pool.at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(gate.astype(layer_pool.dtype).reshape(-1, half))
        else:
            q, s = quantize_rows(gate)
            new_pool[str(i)] = layer_pool.at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(q.reshape(-1, half))
            new_scale[str(i)] = pool_scale[str(i)].at[
                tgt.reshape(-1), off.reshape(-1)
            ].set(s.reshape(-1))
    if new_scale is not None:
        return new_pool, new_scale
    return new_pool


def make_embedder(config: ProGenConfig, policy: Policy | None = None,
                  mesh: Mesh | None = None,
                  strategies: Sequence[str] = ("dp",),
                  weights: str = "bf16"):
    """Build ``embed(params, tokens, lengths) -> (B, dim) f32``: the
    embeddings-endpoint program.

    Reuses the one-pass prefill forward with ``sow_final_hidden=True`` —
    the model sows ONLY the final post-norm hidden states (no per-layer
    decode carries are materialized; the unused logits head is dead code
    XLA eliminates) — then mean-pools over each row's real positions
    (``< lengths[b]``; the window-aligned pad tail never contributes).
    Same ragged ``(B, P_pad)`` + ``lengths`` contract as
    :func:`make_prefiller`, so the serving engine warms one embed program
    per prime bucket.
    """
    policy = policy or make_policy()
    model = ProGen(config=config, policy=policy, mesh=None,
                   sow_final_hidden=True, weights=weights)

    if mesh is not None:
        from progen_tpu.parallel.sharding import logical_rules

        rules = logical_rules(strategies)
        jit_kwargs = {"out_shardings": NamedSharding(mesh, PartitionSpec())}

        def trace_ctx():
            stack = contextlib.ExitStack()
            stack.enter_context(mesh)
            stack.enter_context(nn.logical_axis_rules(rules))
            return stack
    else:
        jit_kwargs = {}
        trace_ctx = contextlib.ExitStack

    @partial(jax.jit, **jit_kwargs)
    def embed(params, tokens, lengths):
        b, p_pad = tokens.shape
        if p_pad % config.window_size != 0 or p_pad > config.seq_len:
            raise ValueError(
                f"padded prime length {p_pad} must be a multiple of "
                f"window_size {config.window_size} and <= seq_len "
                f"{config.seq_len}"
            )
        lengths = jnp.asarray(lengths, jnp.int32)
        with trace_ctx():
            _, varz = model.apply(params, tokens, mutable=["cache"])
        h = varz["cache"]["final_hidden"][0].astype(jnp.float32)
        keep = (jnp.arange(p_pad)[None, :] < lengths[:, None])
        pooled = jnp.sum(h * keep[:, :, None].astype(jnp.float32), axis=1)
        return pooled / jnp.maximum(lengths, 1).astype(jnp.float32)[:, None]

    return embed


def make_prefiller(config: ProGenConfig, policy: Policy | None = None,
                   mesh: Mesh | None = None,
                   strategies: Sequence[str] = ("dp",),
                   weights: str = "bf16"):
    """Build ``prefill(params, tokens, lengths, decode_len)``.

    ``tokens``: ``(B, P_pad)`` int prime tokens, right-padded; ``P_pad``
    must be a multiple of ``window_size`` and ≤ ``seq_len`` (see
    :func:`pad_prime_length`).  ``lengths``: ``(B,)`` actual prime lengths
    (1 ≤ length ≤ P_pad), may differ per row.  ``decode_len``: static —
    positions the subsequent decode will visit (sizes the SGU caches,
    matching ``init_caches(..., decode_len=...)``).

    Returns ``(last_logits (B, V) f32, caches)``: the logits at each
    row's LAST prime position (sample the first new token from these) and
    decode caches identical to sequentially teacher-forcing the prime.
    """
    policy = policy or make_policy()
    model = ProGen(config=config, policy=policy, mesh=None, weights=weights)

    if mesh is not None:
        from progen_tpu.parallel.sharding import logical_rules

        rules = logical_rules(strategies)
        jit_kwargs = {"out_shardings": NamedSharding(mesh, PartitionSpec())}

        def trace_ctx():
            stack = contextlib.ExitStack()
            stack.enter_context(mesh)
            stack.enter_context(nn.logical_axis_rules(rules))
            return stack
    else:
        jit_kwargs = {}
        trace_ctx = contextlib.ExitStack

    @partial(jax.jit, static_argnames=("decode_len",), **jit_kwargs)
    def prefill(params, tokens, lengths, decode_len):
        b, p_pad = tokens.shape
        if p_pad % config.window_size != 0 or p_pad > config.seq_len:
            raise ValueError(
                f"padded prime length {p_pad} must be a multiple of "
                f"window_size {config.window_size} and <= seq_len "
                f"{config.seq_len}"
            )
        lengths = jnp.asarray(lengths, jnp.int32)
        with trace_ctx():
            logits, varz = model.apply(params, tokens, mutable=["cache"])
            caches = harvest_caches(config, varz["cache"], lengths, policy,
                                    decode_len)
            if mesh is not None:
                caches = _constrain_caches(caches, mesh, strategies)
        last_logits = _take_row(logits, lengths - 1).astype(jnp.float32)
        return last_logits, caches

    return prefill
