"""Speculative decoding: draft-propose / target-verify / draft-commit.

One speculative ROUND replaces up to ``k + 1`` sequential target-model
dispatches with three fused scans inside a single device program:

1. **draft propose** — a tiny draft ProGen (``models/configs
   .draft_config_for``) runs ``k`` cached single-token steps on a
   THROWAWAY copy of its caches and proposes ``d_1..d_k``.  Each
   proposal is sampled with the SAME subkey the target would consume for
   that step (the per-slot key chain is re-derived, not committed), so
   sampled requests accept exactly when draft and target sampling agree
   bit-for-bit — determinism never depends on the draft;
2. **target verify** — ``k + 1`` target steps over ``(tok, d_1, ..,
   d_k)`` reuse the chunked-sampler machinery from the engine's chunk
   body (live-masked scan with early exit): step ``j`` samples ``s_j``
   from the TRUE target logits with the slot's authoritative key chain
   and emits it iff the slot is still live; the slot stays live for step
   ``j + 1`` iff ``s_j == d_{j+1}`` and the stop rule did not fire.  All
   cache/ring/carry writes merge under the live mask, so a rejected
   step's writes roll back for free — the ``j = k`` step is the bonus
   token a fully-accepted round gets on top;
3. **draft commit** — the draft's REAL caches re-consume the verified
   inputs under the recorded live masks, so draft and target state stay
   position-aligned for the next round.

Because every emitted token is sampled from the target's own logits with
the target's own key chain, the output is TOKEN-IDENTICAL to non-
speculative decoding — greedy and sampled alike, for ANY draft.  The
draft only decides how many of the ``k + 1`` verify steps are usable
(``accepted-tokens/round``).  :func:`spec_acceptance` is the pure
acceptance rule, unit-testable against a hand-computed oracle.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.decode.sampler import (
    gumbel_topk_sample_batched,
    split_keys_batched,
)
from progen_tpu.models.progen import ProGenConfig


def check_draft_config(target: ProGenConfig, draft: ProGenConfig) -> None:
    """The draft must agree with the target on everything that gives
    tokens and positions their meaning; capacity knobs are free."""
    for field in ("num_tokens", "window_size", "seq_len"):
        t, d = getattr(target, field), getattr(draft, field)
        if t != d:
            raise ValueError(
                f"draft config {field}={d} != target {field}={t}: the "
                f"draft proposes tokens in the target's vocabulary at "
                f"the target's positions (see draft_config_for)")


def spec_acceptance(sampled, proposed, done):
    """Pure acceptance rule for one speculative round.

    ``sampled (.., k+1)``: the target's verified tokens ``s_0..s_k``;
    ``proposed (.., k)``: the draft's ``d_1..d_k`` (``proposed[j]`` is
    the guess for ``sampled[j]``); ``done (.., k+1)``: whether step
    ``j``'s stop rule fired (EOS or length).  Returns ``(live, emitted)``
    where ``live[.., j]`` says step ``j``'s token was emitted and
    ``emitted`` counts them: step 0 is always live (for a live slot);
    step ``j + 1`` is live iff step ``j`` was, matched its proposal, and
    did not finish.  The final step never has a proposal to match — it is
    the bonus token of a fully-accepted round.
    """
    sampled = np.asarray(sampled)
    proposed = np.asarray(proposed)
    done = np.asarray(done)
    k1 = sampled.shape[-1]
    if proposed.shape[-1] != k1 - 1 or done.shape[-1] != k1:
        raise ValueError("want sampled (.., k+1), proposed (.., k), "
                         "done (.., k+1)")
    live = np.ones(sampled.shape[:-1], bool)
    lives = []
    for j in range(k1):
        lives.append(live)
        match = (sampled[..., j] == proposed[..., j]) if j < k1 - 1 \
            else np.zeros_like(live)
        live = live & match & ~done[..., j]
    live_mat = np.stack(lives, axis=-1)
    return live_mat, live_mat.sum(axis=-1)


def spec_round(state: dict, *, spec_k: int, max_len: int, eos_id: int,
               target_step: Callable, draft_step: Callable,
               merge_caches: Callable, live0) -> tuple[dict, jnp.ndarray]:
    """One speculative round over the engine's slot state (traced inside
    the spec decode-chunk program).

    ``target_step(tok, pos, caches, live) -> (logits, caches)`` and
    ``draft_step(tok, pos, draft_caches) -> (logits, draft_caches)`` are
    the engine's step closures (``live`` feeds the paged pool's
    ``write_ok``); ``merge_caches(live, new, old)`` is the engine's
    live-mask cache merge (ring keys only in paged mode — pool writes
    are already masked inside the step).  ``live0`` is the slots allowed
    to advance this round (active, not done, not paused).

    Returns ``(state, emitted)`` with ``emitted (S,)`` the tokens each
    slot produced (0 for slots dead at round start, up to ``spec_k + 1``
    for a fully-accepted round).
    """
    s = state["pos"].shape[0]
    pos0 = state["pos"]
    tok0 = jnp.take_along_axis(state["seq"], pos0[:, None], axis=1)[:, 0]
    # infilling logit masks ride the slot state as (S, max_len, V) rows
    # indexed by WRITE position; absent for direct callers (None = all-pass)
    lmask = state.get("lmask")

    def mask_rows(writepos):
        if lmask is None:
            return None
        return jnp.take_along_axis(
            lmask, writepos[:, None, None], axis=1)[:, 0]

    # -- draft propose: throwaway cache copy, re-derived key chain.  The
    # chain advances unconditionally (dead slots' proposals are garbage
    # and never consumed); positions clamp so a slot racing past its stop
    # mid-round cannot index past the gMLP weight rows.  The mask row for
    # the position the TARGET would write this step applies to the draft
    # sample too — draft and target see identical constrained logits, so
    # acceptance (and therefore token-identity) is preserved under masks.
    def propose_body(carry, _):
        dc, kd, tok, dpos = carry
        logits, dc = draft_step(tok, dpos, dc)
        kd, sub = split_keys_batched(kd)
        d = gumbel_topk_sample_batched(
            sub, logits, state["top_k"], state["temp"],
            mask=mask_rows(jnp.clip(dpos + 1, 0, max_len - 1))).astype(
                jnp.int32)
        return (dc, kd, d, jnp.minimum(dpos + 1, max_len - 1)), d

    (_, _, _, _), proposed = jax.lax.scan(
        propose_body, (state["draft_caches"], state["keys"], tok0, pos0),
        None, length=spec_k)  # proposed[j] (S,) = d_{j+1}, guess for s_j

    # -- target verify: k+1 live-masked steps; input j is the current
    # token at j=0, the draft's d_j after; guess j is d_{j+1} (none for
    # the final bonus step, so it always ends the round)
    inputs = jnp.concatenate([tok0[None], proposed], axis=0)
    guesses = jnp.concatenate(
        [proposed, jnp.full((1, s), -1, jnp.int32)], axis=0)
    verify_state = {k: v for k, v in state.items() if k != "draft_caches"}

    def verify_body(carry, xs):
        st, live = carry
        inp, guess = xs
        pos = st["pos"]
        logits, caches = target_step(inp, pos, st["caches"], live)
        caches = merge_caches(live, caches, st["caches"])
        kd, sub = split_keys_batched(st["keys"])
        writepos = jnp.clip(pos + 1, 0, max_len - 1)
        nxt = gumbel_topk_sample_batched(
            sub, logits, st["top_k"], st["temp"],
            mask=mask_rows(writepos)).astype(jnp.int32)
        cur = jnp.take_along_axis(st["seq"], writepos[:, None],
                                  axis=1)[:, 0]
        val = jnp.where(live, nxt, cur)
        seq = st["seq"].at[jnp.arange(s), writepos].set(val)
        new_pos = jnp.where(live, pos + 1, pos)
        done_now = live & ((val == eos_id) | (new_pos + 1 >= st["stop"]))
        new_keys = jnp.where(live[:, None], kd, st["keys"])
        st = {**st, "seq": seq, "caches": caches, "pos": new_pos,
              "done": st["done"] | done_now, "keys": new_keys}
        return (st, live & (nxt == guess) & ~done_now), live

    (verified, _), lives = jax.lax.scan(
        verify_body, (verify_state, live0), (inputs, guesses))

    # -- draft commit: the real draft caches consume the same inputs
    # under the recorded live masks, staying aligned with the target
    def commit_body(carry, xs):
        dc, dpos = carry
        inp, live = xs
        _, dc_new = draft_step(inp, dpos, dc)

        def mrg(n, o):
            m = live.reshape((-1,) + (1,) * (o.ndim - 1))
            return jnp.where(m, n, o)

        dc = jax.tree.map(mrg, dc_new, dc)
        return (dc, jnp.where(live, jnp.minimum(dpos + 1, max_len - 1),
                              dpos)), None

    (draft_caches, _), _ = jax.lax.scan(
        commit_body, (state["draft_caches"], pos0), (inputs, lives))

    emitted = jnp.sum(lives.astype(jnp.int32), axis=0)
    return {**verified, "draft_caches": draft_caches}, emitted
