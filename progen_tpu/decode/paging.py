"""Host-side page-pool bookkeeping for the paged serving engine.

The fixed-slot ServingEngine prices every request at the worst case: one
slot owns ``max_len`` rows of SGU gate cache for its whole lifetime, so
HBM per request is ``max_len`` rows even when the request uses 40.  The
paged mode (vLLM / "Ragged Paged Attention", PAPERS.md) replaces the
per-slot allocation with a GLOBAL POOL of fixed-size pages (``page_size``
token rows each) and a per-request PAGE TABLE mapping row index
``i -> pool page table[i // page_size]``:

* pages are allocated on demand as a request's position advances and
  freed (refcounted) when it completes — concurrency is bounded by
  actual live tokens, not ``slots x max_len``;
* requests sharing a prompt prefix share the read-only pages that are
  fully inside the common prefix (hash-keyed prefix cache), so a popular
  prompt's gate rows exist once in HBM no matter how many requests are
  decoding from it.

This module is the HOST side only: free lists, refcounts and the prefix
index are plain Python (they make per-request decisions between device
dispatches).  The device side — the pooled gate arrays, the page-table
walk in the decode step, and the ragged paged mix kernel — lives in
``decode/incremental.py`` and ``ops/pallas_paged_attention.py``.

Two pool pages are reserved:

* page 0 (``NULL_PAGE``) is all-zeros and never written: page-table
  entries for slots a request does not own point here, so the XLA
  gather fallback reads exact zeros for unowned rows (bit-matching the
  dense engine's zero-initialized cache tail);
* page 1 (``DUMP_PAGE``) is a write sink that is never read: masked
  scatter lanes (pad rows, prefix-shared pages, non-live slots) are
  redirected here instead of needing a predicated scatter.
"""

from __future__ import annotations

import dataclasses
import hashlib
from collections import OrderedDict
from typing import Sequence

NULL_PAGE = 0
DUMP_PAGE = 1
RESERVED_PAGES = 2


def pages_for_span(last_row: int, page_size: int) -> int:
    """Number of pages covering rows ``[0, last_row]`` inclusive."""
    if last_row < 0:
        return 0
    return last_row // page_size + 1


def token_span_digest(tokens: Sequence[int], upto: int) -> str:
    """Content hash of the first ``upto`` prime tokens.  Shared between
    ``prefix_key`` (pool-local identity) and the fleet router's digest
    matching: the router scores replicas by ``(upto, digest)`` alone, so
    it can rank placements without knowing which prefill bucket a worker
    will land the request in."""
    h = hashlib.blake2b(digest_size=16)
    for t in tokens[:upto]:
        h.update(b"%d," % int(t))
    return h.hexdigest()


def prefix_key(p_pad: int, tokens: Sequence[int], upto: int) -> tuple:
    """Hash key for the prefix page covering rows ``[upto-page_size,
    upto)``: the first ``upto`` prime tokens plus the padded prefill
    length.  ``p_pad`` is part of the key because gate rows are only
    guaranteed BIT-identical across requests when they came out of the
    same-shape prefill program (same summation trees); two requests whose
    primes land in different prefill buckets recompute rather than share.
    """
    return (p_pad, upto, token_span_digest(tokens, upto))


@dataclasses.dataclass
class SlotPages:
    """Pages owned by one in-flight request, in row order: ``pages[j]``
    covers rows ``[j*page_size, (j+1)*page_size)``.  The first ``shared``
    entries are prefix-cache hits (read-only; prefill/decode never write
    them)."""

    pages: list[int]
    shared: int


class PagePool:
    """Free list + refcounts + LRU prefix index over ``num_pages`` pages.

    ``num_pages`` counts the DEVICE pool's first axis, including the two
    reserved pages; ``capacity`` is the allocatable remainder.  Reference
    counting: every in-flight request holds one reference per page in its
    table (shared or private), and the prefix index holds one reference
    per cached page.  A page returns to the free list when its count hits
    zero; cached pages idle at refcount 1 and are reclaimed LRU-first
    when an allocation would otherwise fail.
    """

    def __init__(self, num_pages: int, page_size: int, *,
                 prefix_caching: bool = True, gate_dtype: str = "bf16"):
        if num_pages < RESERVED_PAGES + 1:
            raise ValueError(
                f"num_pages {num_pages} leaves no allocatable pages "
                f"({RESERVED_PAGES} are reserved)")
        if page_size < 1:
            raise ValueError(f"page_size must be >= 1, got {page_size}")
        if gate_dtype not in ("bf16", "int8"):
            raise ValueError(f"gate_dtype {gate_dtype!r}: want 'bf16' "
                             "or 'int8'")
        self.num_pages = num_pages
        self.page_size = page_size
        self.prefix_caching = prefix_caching
        # bookkeeping only — the device pools live in engine state; the
        # pool records the page format so stats/capacity reports can say
        # what a page costs (int8 rows are ~2x denser than bf16)
        self.gate_dtype = gate_dtype
        # LIFO free list: recently-freed pages are reused first, which
        # keeps the working set dense and makes tests deterministic
        self._free: list[int] = list(range(num_pages - 1,
                                           RESERVED_PAGES - 1, -1))
        self._ref: dict[int, int] = {}
        self._prefix: OrderedDict[tuple, int] = OrderedDict()
        self._key_of: dict[int, tuple] = {}

    # ------------------------------------------------------------- queries

    @property
    def capacity(self) -> int:
        return self.num_pages - RESERVED_PAGES

    @property
    def free_pages(self) -> int:
        return len(self._free)

    @property
    def cached_pages(self) -> int:
        return len(self._prefix)

    def refcount(self, pid: int) -> int:
        return self._ref.get(pid, 0)

    def _evictable(self) -> int:
        # cached pages held only by the index (refcount 1) can be dropped
        return sum(1 for pid in self._prefix.values()
                   if self._ref.get(pid, 0) == 1)

    def can_allocate(self, n: int) -> bool:
        return len(self._free) + self._evictable() >= n

    # ---------------------------------------------------------- allocation

    def allocate(self, n: int) -> list[int] | None:
        """``n`` fresh private pages (refcount 1 each), or None when the
        pool cannot supply them even after evicting idle cached pages."""
        if n < 0:
            raise ValueError(f"cannot allocate {n} pages")
        if not self.can_allocate(n):
            return None
        while len(self._free) < n:
            self._evict_one_cached()
        out = [self._free.pop() for _ in range(n)]
        for pid in out:
            self._ref[pid] = 1
        return out

    def _evict_one_cached(self) -> None:
        for key, pid in self._prefix.items():  # insertion order = LRU
            if self._ref.get(pid, 0) == 1:
                del self._prefix[key]
                del self._key_of[pid]
                self._release_ref(pid)
                return
        raise RuntimeError("no evictable cached page")  # guarded by caller

    def retain(self, pid: int) -> None:
        if pid < RESERVED_PAGES:
            raise ValueError(f"cannot retain reserved page {pid}")
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"retain of unallocated page {pid}")
        self._ref[pid] += 1

    def release(self, pid: int) -> None:
        if self._ref.get(pid, 0) < 1:
            raise ValueError(f"release of unallocated page {pid}")
        self._release_ref(pid)

    def _release_ref(self, pid: int) -> None:
        self._ref[pid] -= 1
        if self._ref[pid] == 0:
            del self._ref[pid]
            self._free.append(pid)

    # -------------------------------------------------------- prefix cache

    def lookup_prefix(self, key: tuple) -> int | None:
        """Cached page for ``key`` (touches LRU), or None."""
        if not self.prefix_caching:
            return None
        pid = self._prefix.get(key)
        if pid is not None:
            self._prefix.move_to_end(key)
        return pid

    def register_prefix(self, key: tuple, pid: int) -> None:
        """Publish a just-filled full-prefix page for future sharing; the
        index takes its own reference."""
        if not self.prefix_caching or key in self._prefix or \
                pid in self._key_of:
            return
        self._prefix[key] = pid
        self._key_of[pid] = key
        self._ref[pid] = self._ref.get(pid, 0) + 1

    def unregister_prefix(self, pid: int) -> None:
        """Withdraw ``pid`` from the prefix index (no-op when it was never
        published).  Needed when the prefill that was going to FILL a
        registered page fails after planning: the index must not serve a
        page holding garbage.  The index's reference is dropped; any
        in-flight sharer keeps theirs."""
        key = self._key_of.pop(pid, None)
        if key is None:
            return
        del self._prefix[key]
        self._release_ref(pid)

    # ---------------------------------------------------------------- stats

    @property
    def shared_pages(self) -> int:
        """Page-holder edges beyond the index's own reference: a cached
        page referenced by ``k`` in-flight requests contributes ``k``.
        Zero when nothing is actively sharing."""
        return sum(self._ref.get(pid, 0) - 1
                   for pid in self._prefix.values()
                   if self._ref.get(pid, 0) > 1)

    def prefix_digest(self) -> dict:
        """Compact JSON-safe advertisement of cache contents for the
        fleet router: one ``[p_pad, upto, digest, refcount]`` row per
        cached prefix page in LRU order (coldest first), plus pool
        pressure.  Cheap enough to ride every heartbeat — the index is
        bounded by the pool size."""
        return {
            "page_size": self.page_size,
            "keys": [[k[0], k[1], k[2], self._ref.get(pid, 0)]
                     for k, pid in self._prefix.items()],
            "free": self.free_pages,
            "cached": self.cached_pages,
            "capacity": self.capacity,
        }

    def stats(self) -> dict:
        """Host-side accounting snapshot (robustness/chaos records)."""
        return {
            "num_pages": self.num_pages,
            "capacity": self.capacity,
            "free_pages": self.free_pages,
            "cached_pages": self.cached_pages,
            "shared_pages": self.shared_pages,
            "pages_in_use": self.capacity - self.free_pages,
            "gate_dtype": self.gate_dtype,
        }
