"""Prefill→decode handoff: cache handles over a bounded queue.

Disaggregated serving (docs/SERVING.md §6) splits the engine's step into
a PREFILL stage and a DECODE stage on the same mesh.  The prefill worker
runs the bucketed parallel prefill as its own jit program and produces a
:class:`Handle`: a self-contained slab of per-row decode state (caches,
sequence row with the first sampled token, position/stop/key/sampling
knobs) for up to ``prefill_batch`` requests, shaped ``(num_slots, ...)``
so the decode pool's merge program can DONATE it — the handed-off cache
buffers move into the slot state instead of being copied.

The queue between the stages is BOUNDED (``handoff_depth`` handles): a
full queue skips the prefill round (backpressure — prefilled state is
the expensive thing to hold), while :meth:`HandoffQueue.requeue` puts a
handle back at the FRONT after a transiently failed merge without
counting against the bound (the handle was already admitted once; a
crash-replay loop must not deadlock against its own backpressure).

This module is pure host-side bookkeeping between dispatches — handles
carry device arrays, but nothing here may force a sync (enforced by a
graftcheck host-sync zone, like ``decode/paging.py``).
"""

from __future__ import annotations

import dataclasses
from collections import deque
from typing import Any


@dataclasses.dataclass
class Handle:
    """One prefill worker product awaiting decode admission.

    ``requests``: the admitted requests in row order (row ``i`` of the
    state slabs belongs to ``requests[i]``; later rows are dummy).
    ``state``: device arrays, ``(num_slots, ...)``-shaped — seq, caches
    (dense gate rows even in paged mode; the merge scatters them into
    the pool), pos/start/stop/done/keys/top_k/temp, plus draft caches
    under speculative decoding.  ``p_pad``: the prefill bucket that
    produced it (observability; the merge program is bucket-agnostic).
    """

    requests: list
    state: dict[str, Any]
    p_pad: int


class HandoffQueue:
    """Bounded FIFO of :class:`Handle`\\ s between the serving stages."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"handoff depth must be >= 1, got {depth}")
        self.depth = depth
        self._q: deque[Handle] = deque()
        self.puts = 0
        self.gets = 0
        self.rejects = 0

    def full(self) -> bool:
        return len(self._q) >= self.depth

    def put(self, handle: Handle) -> bool:
        """Append; False (and a ``rejects`` tick) when at depth — the
        caller should have checked :meth:`full` before paying for the
        prefill, so a reject indicates lost work."""
        if self.full():
            self.rejects += 1
            return False
        self._q.append(handle)
        self.puts += 1
        return True

    def requeue(self, handle: Handle) -> None:
        """Return a handle to the FRONT (failed merge retry path); not
        depth-bounded, see module docstring."""
        self._q.appendleft(handle)

    def get(self) -> Handle:
        self.gets += 1
        return self._q.popleft()

    def peek(self) -> Handle:
        return self._q[0]

    def num_requests(self) -> int:
        """Requests captured in queued handles (snapshot accounting)."""
        return sum(len(h.requests) for h in self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __iter__(self):
        return iter(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)

    def stats(self) -> dict:
        return {"depth": self.depth, "queued": len(self._q),
                "puts": self.puts, "gets": self.gets,
                "rejects": self.rejects}
