"""Prefill→decode handoff: cache handles over a bounded queue.

Disaggregated serving (docs/SERVING.md §6) splits the engine's step into
a PREFILL stage and a DECODE stage on the same mesh.  The prefill worker
runs the bucketed parallel prefill as its own jit program and produces a
:class:`Handle`: a self-contained slab of per-row decode state (caches,
sequence row with the first sampled token, position/stop/key/sampling
knobs) for up to ``prefill_batch`` requests, shaped ``(num_slots, ...)``
so the decode pool's merge program can DONATE it — the handed-off cache
buffers move into the slot state instead of being copied.

The queue between the stages is BOUNDED (``handoff_depth`` handles): a
full queue skips the prefill round (backpressure — prefilled state is
the expensive thing to hold), while :meth:`HandoffQueue.requeue` puts a
handle back at the FRONT after a transiently failed merge without
counting against the bound (the handle was already admitted once; a
crash-replay loop must not deadlock against its own backpressure).

The :class:`HandoffQueue` is pure host-side bookkeeping between
dispatches — handles carry device arrays, but nothing in the queue may
force a sync (enforced by a graftcheck host-sync zone, like
``decode/paging.py``).  The module-level ``serialize_handle`` /
``deserialize_handle`` functions below are the opposite: they ARE the
cross-process transport (docs/SERVING.md §7) and sync by design
(``device_get`` on send, ``device_put`` on receive) — they run on
transport threads, never on the admission path, and are deliberately
OUTSIDE the host-sync zone.

Wire format (one handle = one frame)::

    <4sHHIQII> prefix (28 bytes, little-endian):
        magic  b"PGHF" | version u16 | reserved u16
        header_len u32 | payload_len u64
        header_crc u32 | payload_crc u32 (zlib.crc32)
    header: UTF-8 JSON — request rows, p_pad, and a manifest of
        (path, dtype, shape, offset, nbytes) per state leaf
    payload: the raw array bytes, concatenated at manifest offsets

A payload CRC mismatch raises :class:`FrameCorrupt` — the prefix and
header survived, so the stream is still framed and the router can shed
or replay exactly the requests named in the header.  A bad magic /
version / truncated read raises :class:`FrameDesync` — the stream can
no longer be trusted and the connection is poisoned (the supervisor
restarts the stage).  Both are typed: a corrupt frame sheds, never
crashes.
"""

from __future__ import annotations

import dataclasses
import json
import struct
import time
import zlib
from collections import deque
from typing import Any, Sequence

from progen_tpu.observe import trace as _trace


@dataclasses.dataclass
class Handle:
    """One prefill worker product awaiting decode admission.

    ``requests``: the admitted requests in row order (row ``i`` of the
    state slabs belongs to ``requests[i]``; later rows are dummy).
    ``state``: device arrays, ``(num_slots, ...)``-shaped — seq, caches
    (dense gate rows even in paged mode; the merge scatters them into
    the pool), pos/start/stop/done/keys/top_k/temp, plus draft caches
    under speculative decoding.  ``p_pad``: the prefill bucket that
    produced it (observability; the merge program is bucket-agnostic).
    """

    requests: list
    state: dict[str, Any]
    p_pad: int


class HandoffQueue:
    """Bounded FIFO of :class:`Handle`\\ s between the serving stages."""

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"handoff depth must be >= 1, got {depth}")
        self.depth = depth
        self._q: deque[Handle] = deque()
        self.puts = 0
        self.gets = 0
        self.rejects = 0

    def full(self) -> bool:
        return len(self._q) >= self.depth

    def put(self, handle: Handle) -> bool:
        """Append; False (and a ``rejects`` tick) when at depth — the
        caller should have checked :meth:`full` before paying for the
        prefill, so a reject indicates lost work."""
        if self.full():
            self.rejects += 1
            return False
        self._q.append(handle)
        self.puts += 1
        return True

    def requeue(self, handle: Handle) -> None:
        """Return a handle to the FRONT (failed merge retry path); not
        depth-bounded, see module docstring."""
        self._q.appendleft(handle)

    def get(self) -> Handle:
        self.gets += 1
        return self._q.popleft()

    def peek(self) -> Handle:
        return self._q[0]

    def num_requests(self) -> int:
        """Requests captured in queued handles (snapshot accounting)."""
        return sum(len(h.requests) for h in self._q)

    def __len__(self) -> int:
        return len(self._q)

    def __iter__(self):
        return iter(self._q)

    def __bool__(self) -> bool:
        return bool(self._q)

    def stats(self) -> dict:
        return {"depth": self.depth, "queued": len(self._q),
                "puts": self.puts, "gets": self.gets,
                "rejects": self.rejects}


# --------------------------------------------------------------- wire format
#
# Transport layer: everything below may sync (device_get / device_put);
# it runs on transport threads only — see module docstring.

FRAME_MAGIC = b"PGHF"
FRAME_VERSION = 1
_PREFIX = struct.Struct("<4sHHIQII")
FRAME_PREFIX_LEN = _PREFIX.size  # 28


class FrameError(Exception):
    """A frame failed to decode.  Never escapes the serving runtime as a
    crash: subclasses pick the recovery (shed vs restart)."""


class FrameCorrupt(FrameError):
    """Payload CRC mismatch with an intact prefix+header: the stream is
    still framed — shed/replay the requests named in the header and keep
    the connection."""

    def __init__(self, msg: str, header: dict | None = None):
        super().__init__(msg)
        self.header = header


class FrameDesync(FrameError):
    """Bad magic/version, header corruption, or a truncated read: the
    byte stream can no longer be trusted — poison the connection and let
    stage supervision restart the peer."""


def pack_frame(header: dict, payload_parts: Sequence = ()) -> bytes:
    """Assemble one wire frame from a JSON-able header and raw payload
    parts (bytes-likes, concatenated in order)."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    parts = [memoryview(p).cast("B") for p in payload_parts]
    payload_len = sum(p.nbytes for p in parts)
    payload_crc = 0
    for p in parts:
        payload_crc = zlib.crc32(p, payload_crc)
    out = bytearray(_PREFIX.size + len(hdr) + payload_len)
    _PREFIX.pack_into(out, 0, FRAME_MAGIC, FRAME_VERSION, 0, len(hdr),
                      payload_len, zlib.crc32(hdr), payload_crc)
    out[_PREFIX.size:_PREFIX.size + len(hdr)] = hdr
    off = _PREFIX.size + len(hdr)
    for p in parts:
        out[off:off + p.nbytes] = p
        off += p.nbytes
    return bytes(out)


def parse_prefix(prefix: bytes) -> tuple[int, int, int, int]:
    """Validate a 28-byte frame prefix; returns ``(header_len,
    payload_len, header_crc, payload_crc)``.  :class:`FrameDesync` on a
    short read, bad magic, or unknown version."""
    if len(prefix) < _PREFIX.size:
        raise FrameDesync(
            f"truncated frame prefix: {len(prefix)} < {_PREFIX.size} bytes")
    magic, version, _, hlen, plen, hcrc, pcrc = _PREFIX.unpack_from(prefix)
    if magic != FRAME_MAGIC:
        raise FrameDesync(f"bad frame magic {magic!r}")
    if version != FRAME_VERSION:
        raise FrameDesync(f"unsupported frame version {version}")
    return hlen, plen, hcrc, pcrc


def unpack_frame(buf) -> tuple[dict, memoryview]:
    """Split one complete frame back into ``(header, payload_view)``.

    ``payload_view`` is a zero-copy view into ``buf``.  Raises
    :class:`FrameDesync` (untrustworthy stream) or :class:`FrameCorrupt`
    (payload CRC with a good header — ``.header`` names the casualties).
    """
    view = memoryview(buf).cast("B")
    hlen, plen, hcrc, pcrc = parse_prefix(bytes(view[:_PREFIX.size]))
    end = _PREFIX.size + hlen + plen
    if view.nbytes < end:
        raise FrameDesync(
            f"truncated frame: have {view.nbytes} bytes, need {end}")
    hdr_bytes = view[_PREFIX.size:_PREFIX.size + hlen]
    if zlib.crc32(hdr_bytes) != hcrc:
        raise FrameDesync("frame header CRC mismatch")
    try:
        header = json.loads(bytes(hdr_bytes))
    except ValueError as e:
        raise FrameDesync(f"frame header is not JSON: {e}") from e
    payload = view[_PREFIX.size + hlen:end]
    if zlib.crc32(payload) != pcrc:
        raise FrameCorrupt("frame payload CRC mismatch", header=header)
    return header, payload


def _flatten_state(state, prefix: str = "") -> list:
    """Deterministic (sorted-key, '/'-joined path) flatten of a handle
    state tree into ``[(path, leaf), ...]``.  List/tuple nodes (e.g.
    per-layer cache stacks) use ``#i``/``@i`` index segments so the
    receiver rebuilds the exact container types."""
    out = []
    if isinstance(state, dict):
        items = [(str(k), state[k]) for k in sorted(state)]
    elif isinstance(state, (list, tuple)):
        marker = "#" if isinstance(state, list) else "@"
        items = [(f"{marker}{i}", v) for i, v in enumerate(state)]
    else:
        raise TypeError(f"unsupported state node {type(state).__name__}")
    for k, v in items:
        path = f"{prefix}{k}"
        if isinstance(v, (dict, list, tuple)):
            out.extend(_flatten_state(v, prefix=path + "/"))
        else:
            out.append((path, v))
    return out


def _unflatten_state(pairs) -> dict:
    tree: dict = {}
    for path, leaf in pairs:
        node = tree
        *parents, last = path.split("/")
        for p in parents:
            node = node.setdefault(p, {})
        node[last] = leaf
    return _rebuild_containers(tree)


def _rebuild_containers(node):
    """Turn ``#i``/``@i``-keyed dicts from :func:`_unflatten_state` back
    into lists/tuples, depth-first."""
    if not isinstance(node, dict):
        return node
    rebuilt = {k: _rebuild_containers(v) for k, v in node.items()}
    if rebuilt and all(k[:1] in "#@" and k[1:].isdigit() for k in rebuilt):
        marker = next(iter(rebuilt))[0]
        seq = [rebuilt[f"{marker}{i}"] for i in range(len(rebuilt))]
        return tuple(seq) if marker == "@" else seq
    return rebuilt


def request_to_wire(r, *, now: float | None = None) -> dict:
    """Host-side request row for a frame header.  ``perf_counter``
    instants don't cross processes, so an absolute deadline travels as
    its REMAINING budget (mirrors ``ServingEngine._snap_request``)."""
    if now is None:
        now = time.perf_counter()
    entry = {
        "uid": r.uid,
        "tokens": [int(t) for t in r.tokens],
        "max_new_tokens": int(r.max_new_tokens),
        "top_k": None if r.top_k is None else int(r.top_k),
        "temperature": float(r.temperature),
        "seed": int(r.seed),
        # trace context: the per-request trace id (its uid) plus the
        # sender's clock instant, so the receiving process can attribute
        # queue-wait to this request on an offset-corrected timeline
        # (docs/OBSERVABILITY.md)
        "trace": {"id": r.uid, "clock": now},
    }
    if getattr(r, "logit_mask", None) is not None:
        from progen_tpu.workloads.infill import mask_to_wire
        entry["logit_mask"] = mask_to_wire(r.logit_mask)
    tenant = int(getattr(r, "tenant", 0))
    if tenant != 0:
        entry["tenant"] = tenant
    priority = int(getattr(r, "priority", 0))
    if priority != 0:
        entry["priority"] = priority
    deadline = r.deadline
    if deadline is None and r.ttl is not None:
        deadline = r.submit_time + r.ttl
    if deadline is not None:
        entry["deadline_remaining"] = max(0.0, deadline - now)
    return entry


def request_from_wire(d: dict, *, now: float | None = None,
                      on_complete=None, vocab: int | None = None):
    """Rebuild a :class:`~progen_tpu.decode.engine.Request` in the
    receiving process; the deadline resumes from its remaining budget.
    ``vocab`` sizes a decoded infill mask (required when one rides)."""
    from progen_tpu.decode.engine import Request

    if now is None:
        now = time.perf_counter()
    lmask = None
    if d.get("logit_mask") is not None:
        if vocab is None:
            raise ValueError("request carries a logit_mask but the "
                             "receiver passed no vocab size")
        from progen_tpu.workloads.infill import mask_from_wire
        lmask = mask_from_wire(d["logit_mask"], vocab)
    r = Request(
        uid=d["uid"], tokens=list(d["tokens"]),
        max_new_tokens=int(d["max_new_tokens"]),
        top_k=d.get("top_k"), temperature=float(d.get("temperature", 1.0)),
        seed=int(d.get("seed", 0)), on_complete=on_complete,
        submit_time=now, logit_mask=lmask, tenant=int(d.get("tenant", 0)),
        priority=int(d.get("priority", 0)))
    if "deadline_remaining" in d:
        r.deadline = now + float(d["deadline_remaining"])
    tc = d.get("trace")
    if tc:
        # land the sender's clock instant on this process's timeline so
        # the offset-corrected merge can attribute cross-process queue
        # wait to the request (docs/OBSERVABILITY.md)
        _trace.get_tracer().event("request.arrive", trace=tc.get("id"),
                                  sender_clock=tc.get("clock"),
                                  recv_clock=now)
    return r


def serialize_handle(handle: Handle, *, extra_header: dict | None = None,
                     counters=None) -> bytes:
    """One prefill product → one wire frame.

    A single batched ``device_get`` pulls the whole state tree to host
    (one sync, not one per leaf), each leaf is appended at its manifest
    offset, and the header records ``(path, dtype, shape, offset,
    nbytes)`` so the receiver can rebuild the tree with zero-copy
    ``np.frombuffer`` views.  ``extra_header`` keys (batch ids, routing
    tags) are merged into the header verbatim.
    """
    import jax
    import numpy as np

    t0 = time.perf_counter()
    pairs = _flatten_state(handle.state)
    host = jax.device_get([leaf for _, leaf in pairs])
    manifest = []
    parts = []
    off = 0
    for (path, _), arr in zip(pairs, host):
        arr = np.ascontiguousarray(arr)
        manifest.append([path, str(arr.dtype), list(arr.shape), off,
                         arr.nbytes])
        # uint8 reinterpret: extension dtypes (bfloat16) reject the
        # buffer protocol directly
        parts.append(memoryview(arr.reshape(-1).view(np.uint8)))
        off += arr.nbytes
    header = {
        "type": "handle",
        "p_pad": int(handle.p_pad),
        "reqs": [request_to_wire(r) for r in handle.requests],
        "manifest": manifest,
    }
    if extra_header:
        header.update(extra_header)
    frame = pack_frame(header, parts)
    dt = time.perf_counter() - t0
    if counters is not None:
        counters.ser_s += dt
    _trace.get_tracer().add("handoff.serialize", t0, dt,
                            uids=[r.uid for r in handle.requests],
                            nbytes=len(frame))
    return frame


def slab_axis(path: str, shape, group_size: int) -> int | None:
    """The slab rule for tensor-parallel decode groups: which axis of a
    handle-state leaf is split across the group's shards.

    One pure function IS the wire contract — the cluster applies it when
    fanning a prefill frame out into per-shard slabs, and every group
    shard applies the inverse when reassembling its slab into a global
    array, so sender and receivers can never disagree.  Rule: a leaf of
    rank >= 2 whose LAST axis divides by ``group_size`` splits on that
    axis (cache hidden dims — the tp-sharded activations); everything
    else (token rows, per-slot scalars) replicates.  ``path`` is part of
    the signature so a future format revision can special-case leaves
    without changing call sites.
    """
    del path  # today's rule is shape-only; see docstring
    if group_size <= 1 or len(shape) < 2:
        return None
    last = len(shape) - 1
    if shape[last] >= group_size and shape[last] % group_size == 0:
        return last
    return None


def split_handle_frame(header: dict, payload, group_size: int) -> list[bytes]:
    """Fan one full handle frame out into ``group_size`` per-shard slab
    frames for a multi-process tensor-parallel decode replica.

    Pure numpy on the already-received payload bytes — no JAX, no device
    work; this runs on the driver's relay path at forward time.  Every
    slab frame carries the SAME group-consistent header (requests, batch
    id, routing tags) plus a ``slab`` section naming this shard's rank,
    the group size, and the per-leaf split axes; its manifest describes
    the shard-local slab shapes so :func:`deserialize_handle_sharded`
    (and plain :func:`unpack_frame`) parse it like any other frame.
    """
    import numpy as np

    if group_size <= 1:
        raise ValueError(f"group_size must be > 1, got {group_size}")
    view = memoryview(payload).cast("B")
    leaves = []
    split: dict[str, int] = {}
    for path, dtype, shape, off, nbytes in header["manifest"]:
        arr = np.frombuffer(view[off:off + nbytes],
                            dtype=_np_dtype(dtype)).reshape(shape)
        axis = slab_axis(path, shape, group_size)
        if axis is not None:
            split[path] = axis
        leaves.append((path, dtype, arr, axis))
    base = {k: v for k, v in header.items() if k != "manifest"}
    frames = []
    for shard in range(group_size):
        manifest = []
        parts = []
        off = 0
        for path, dtype, arr, axis in leaves:
            if axis is None:
                part = arr
            else:
                w = arr.shape[axis] // group_size
                sl = [slice(None)] * arr.ndim
                sl[axis] = slice(shard * w, (shard + 1) * w)
                part = np.ascontiguousarray(arr[tuple(sl)])
            manifest.append([path, dtype, list(part.shape), off,
                             part.nbytes])
            parts.append(memoryview(
                np.ascontiguousarray(part).reshape(-1).view(np.uint8)))
            off += part.nbytes
        hdr = dict(base)
        hdr["manifest"] = manifest
        hdr["slab"] = {"shard": shard, "group_size": group_size,
                       "split": split}
        frames.append(pack_frame(hdr, parts))
    return frames


def deserialize_handle_sharded(buf, mesh, *, header: dict | None = None,
                               payload=None, counters=None) -> Handle:
    """One per-shard slab frame → a :class:`Handle` of GLOBAL arrays on
    the group's process-spanning ``mesh``.

    The inverse of :func:`split_handle_frame`, run by every shard of a
    tensor-parallel decode group on its own slab: split leaves become
    arrays sharded over the mesh's ``tensor`` axis on their split axis
    (shard ``k``'s slab lands at tensor coordinate ``k`` — the mesh is
    process-ordered), replicated leaves are rebuilt whole from each
    process's identical copy.  The group-consistent header means every
    shard reconstructs the SAME requests and admission decision.
    """
    import jax
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec

    t0 = time.perf_counter()
    if header is None:
        header, payload = unpack_frame(buf)
    view = memoryview(payload).cast("B")
    slab = header.get("slab") or {}
    group_size = int(slab.get("group_size", 1))
    split = slab.get("split") or {}
    pairs = []
    try:
        for path, dtype, shape, off, nbytes in header["manifest"]:
            local = np.ascontiguousarray(
                np.frombuffer(view[off:off + nbytes],
                              dtype=_np_dtype(dtype)).reshape(shape))
            axis = split.get(path)
            if axis is None:
                sharding = NamedSharding(mesh, PartitionSpec())
                gshape = tuple(shape)
            else:
                axis = int(axis)
                spec = [None] * len(shape)
                spec[axis] = "tensor"
                sharding = NamedSharding(mesh, PartitionSpec(*spec))
                gshape = tuple(d * group_size if i == axis else d
                               for i, d in enumerate(shape))
            pairs.append((path, jax.make_array_from_process_local_data(
                sharding, local, gshape)))
        reqs = [request_from_wire(d) for d in header["reqs"]]
        p_pad = int(header["p_pad"])
    except (KeyError, TypeError, ValueError) as e:
        raise FrameCorrupt(f"malformed slab frame header: {e}",
                           header=header) from e
    state = _unflatten_state(pairs)
    h = Handle(requests=reqs, state=state, p_pad=p_pad)
    dt = time.perf_counter() - t0
    if counters is not None:
        counters.de_s += dt
    _trace.get_tracer().add("handoff.deserialize_sharded", t0, dt,
                            uids=[r.uid for r in reqs])
    return h


def _np_dtype(name: str):
    import numpy as np

    try:
        return np.dtype(name)  # bfloat16 resolves via jax's ml_dtypes
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def deserialize_handle(buf, *, header: dict | None = None,
                       payload=None, counters=None) -> Handle:
    """One wire frame → a :class:`Handle` of fresh device arrays.

    Pass either the full frame ``buf`` or a pre-unpacked ``(header,
    payload)`` pair (the router parses headers without touching
    payloads).  Each manifest entry becomes an ``np.frombuffer`` view
    into the single received buffer — no host-side copy — and one
    batched ``device_put`` commits the tree to device, producing fresh
    buffers the decode merge can safely DONATE.
    """
    import jax
    import numpy as np

    t0 = time.perf_counter()
    if header is None:
        header, payload = unpack_frame(buf)
    view = memoryview(payload).cast("B")
    pairs = []
    try:
        for path, dtype, shape, off, nbytes in header["manifest"]:
            arr = np.frombuffer(view[off:off + nbytes],
                                dtype=_np_dtype(dtype)).reshape(shape)
            pairs.append((path, arr))
        reqs = [request_from_wire(d) for d in header["reqs"]]
        p_pad = int(header["p_pad"])
    except (KeyError, TypeError, ValueError) as e:
        raise FrameCorrupt(f"malformed handle header: {e}",
                           header=header) from e
    state = _unflatten_state(
        zip([p for p, _ in pairs],
            jax.device_put([a for _, a in pairs])))
    h = Handle(requests=reqs, state=state, p_pad=p_pad)
    dt = time.perf_counter() - t0
    if counters is not None:
        counters.de_s += dt
    _trace.get_tracer().add("handoff.deserialize", t0, dt,
                            uids=[r.uid for r in reqs])
    return h
