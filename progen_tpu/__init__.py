"""progen-tpu: a TPU-native protein language model framework.

Capability parity with the reference ProGen implementation (JAX/Haiku,
single GPU) re-designed TPU-first: one device mesh, sharding-rule
parallelism (DP/FSDP/TP/SP), bf16 MXU compute, scan-based cached decoding,
sharded checkpoints, and an SPMD tfrecord input pipeline.
"""

__version__ = "0.1.0"

from progen_tpu.models.progen import ProGen, ProGenConfig

__all__ = ["ProGen", "ProGenConfig", "__version__"]
