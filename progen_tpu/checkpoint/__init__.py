from progen_tpu.checkpoint.store import (
    CheckpointStore,
    abstract_params_like,
    abstract_state_like,
)

__all__ = ["CheckpointStore", "abstract_params_like", "abstract_state_like"]
