from progen_tpu.checkpoint.store import CheckpointStore, abstract_state_like

__all__ = ["CheckpointStore", "abstract_state_like"]
