"""Sharded checkpoint store (orbax/tensorstore).

Logical contents match the reference's cloudpickled package
(``/root/reference/train.py:202-208``): ``next_seq_index`` (data-stream
resume cursor), ``params`` + ``optimizer state`` (here inside a
``TrainState``), ``model_config``, and ``run_id`` (experiment-tracker
resume).  The reference writes UNSHARDED full-state pickles
(``checkpoint.py:30-31``); a pod-scale model cannot materialize on one
host, so this store writes each array shard from the host that owns it
(orbax -> tensorstore) and restores directly into the requested sharding.

Behavioral parity points:

* local paths and ``gs://`` both work (reference ``checkpoint.py:85-109``
  dispatches the same way; orbax handles GCS natively, no /tmp staging or
  manual timeouts needed);
* keep-last-N pruning (reference ``checkpoint.py:33-37``, default 500);
* ``reset()`` wipes the store (reference ``checkpoint.py:12-13,44-45``) —
  the y/n confirm lives in the CLI, not here;
* checkpoints are identified by TRAINING STEP (monotonic), replacing the
  reference's unix-time filenames whose lexicographic ordering breaks
  across epoch boundaries of 10^k seconds.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp
from etils import epath

from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import RetryPolicy, retry_call


class CheckpointStore:
    def __init__(self, path: str, keep_last_n: int | None = 500,
                 retry_policy: RetryPolicy | None = None):
        self._path = epath.Path(path)
        self._keep_last_n = keep_last_n
        self._mgr: ocp.CheckpointManager | None = None
        # every storage-touching operation goes through this policy: GCS
        # 503s/429s and tunnel drops are routine at pod scale, and one
        # failed periodic save must not kill a run that has a perfectly
        # good retry budget (env-tunable: PROGEN_CKPT_RETRY_*)
        self._retry = retry_policy or RetryPolicy.from_env("PROGEN_CKPT_RETRY")

    # lazily (re)create so reset() can drop the directory out from under us
    def _manager(self) -> ocp.CheckpointManager:
        if self._mgr is None:
            options = ocp.CheckpointManagerOptions(
                max_to_keep=self._keep_last_n,
                create=True,
                # async: save() returns once the arrays are copied to host;
                # the tensorstore write proceeds in the background off the
                # training critical path (orbax's device->host copy is
                # blocking, so donated step buffers are safe to reuse).
                # Readers call wait_until_finished() first.
                enable_async_checkpointing=True,
            )
            self._mgr = ocp.CheckpointManager(self._path, options=options)
        return self._mgr

    def reset(self) -> None:
        """Delete every checkpoint (reference 'reset' semantics)."""
        self.close()
        if self._path.exists():
            self._path.rmtree()

    def latest_step(self) -> int | None:
        """Newest saved step, INCLUDING an async save still in flight."""

        def _steps():
            faults.inject("ckpt.steps")
            return self._manager().latest_step()

        return retry_call(_steps, policy=self._retry, label="ckpt.steps")

    def reached_preemption(self, step: int) -> bool:
        """Cross-host-consistent preemption check (orbax rides the JAX
        coordination service, so every host agrees on the answer — a
        per-host signal flag would deadlock the cooperative save).  False
        when no distributed runtime / no preemption notice exists.

        A failing check is reported ONCE rather than silently swallowed
        forever — otherwise a misconfigured coordination service would
        quietly disable the very protection this exists to provide."""
        try:
            return bool(self._manager().reached_preemption(step))
        except Exception as e:
            if not getattr(self, "_preemption_check_warned", False):
                self._preemption_check_warned = True
                print(f"warning: preemption check unavailable ({e!r}); "
                      "relying on periodic checkpoints only")
            return False

    def save(
        self,
        step: int,
        state: Any,
        *,
        next_seq_index: int,
        model_config: dict,
        run_id: str | None = None,
        overwrite: bool = False,
    ) -> bool:
        """``state`` is a TrainState; params and opt_state are stored as
        SEPARATE items so inference can restore params without knowing the
        optimizer structure (the reference's single pickle forces sample.py
        to deserialize optimizer moments it never uses).

        Saving a step that already exists in the store is a no-op returning
        False: the trainer's exit/preemption save can land on the same step
        as the periodic hook (max_steps a multiple of checkpoint_every),
        and within one training run the state at a given step is unique, so
        the second write would be wasted IO that some orbax versions reject
        (StepAlreadyExists).  Callers whose data DOES change at the same
        step — e.g. re-converting a reference pickle into an existing
        store — pass ``overwrite=True`` to replace it instead.

        Returns True when a save was actually issued.  The write completes
        in the background; readers and :meth:`close` wait for it.
        """
        mgr = self._manager()
        meta = {
            "next_seq_index": int(next_seq_index),
            "model_config": model_config,
            "run_id": run_id,
            "train_step": int(state.step),
        }

        # the whole issue-save is one retried unit: orbax commits are
        # atomic (tmp dir + rename), so a failed attempt leaves no step
        # registered and the next attempt re-runs the membership check
        # against unchanged truth
        def _issue() -> bool:
            faults.inject("ckpt.save")
            # a still-finalizing previous async save makes orbax reject a
            # new one (AssertionError on its finalize thread); saves are
            # issued off the training critical path, so waiting here is
            # free and removes the race.  orbax only CLEARS the finalize
            # handle when the wait comes from the thread that issued that
            # save — the trainer issues each background save from a fresh
            # thread, so drop the joined-but-stale handle ourselves
            # (guarded: only when its thread is provably finished).
            mgr.wait_until_finished()
            stale = getattr(mgr, "_finalize_thread", None)
            if stale is not None and not stale.is_alive():
                lock = getattr(mgr, "_finalize_thread_lock", None)
                if lock is not None:
                    with lock:
                        if mgr._finalize_thread is stale:
                            mgr._finalize_thread = None
            # membership, not latest_step(): re-converting a reference
            # pickle into a store that has trained past step 0 collides
            # with a step that exists but is no longer the newest
            if step in mgr.all_steps():
                if not overwrite:
                    return False
                mgr.delete(step)
            mgr.save(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardSave(state.params),
                    opt_state=ocp.args.StandardSave(state.opt_state),
                    meta=ocp.args.JsonSave(meta),
                ),
            )
            return True

        return retry_call(_issue, policy=self._retry,
                          label=f"ckpt.save[{step}]")

    def wait_until_finished(self) -> None:
        """Block until any in-flight async save has committed to storage."""
        if self._mgr is not None:
            self._mgr.wait_until_finished()

    def restore_meta(self, step: int | None = None) -> dict | None:
        """Metadata only — enough to rebuild the model/config before the
        (potentially sharded) state restore."""
        mgr = self._manager()
        mgr.wait_until_finished()
        step = step if step is not None else mgr.latest_step()
        if step is None:
            return None

        def _restore():
            faults.inject("ckpt.restore")
            return mgr.restore(
                step, args=ocp.args.Composite(meta=ocp.args.JsonRestore()))

        out = retry_call(_restore, policy=self._retry,
                         label=f"ckpt.restore_meta[{step}]")
        return dict(out["meta"])

    def restore_params(self, abstract_params: Any, step: int | None = None):
        """Params only — enough for inference/sampling.

        ``abstract_params`` is a pytree of ``jax.ShapeDtypeStruct`` (with
        ``sharding`` set for a sharded restore); build it with
        ``jax.eval_shape``.
        """
        mgr = self._manager()
        mgr.wait_until_finished()
        step = step if step is not None else mgr.latest_step()
        if step is None:
            return None

        def _restore():
            faults.inject("ckpt.restore")
            return mgr.restore(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(abstract_params)),
            )

        out = retry_call(_restore, policy=self._retry,
                         label=f"ckpt.restore_params[{step}]")
        return out["params"]

    def restore_state(self, abstract_state: Any, step: int | None = None):
        """Full train state (params + optimizer moments + step counter).

        ``abstract_state`` is an abstract TrainState pytree — see
        :func:`abstract_state_like`.
        """
        mgr = self._manager()
        mgr.wait_until_finished()
        step = step if step is not None else mgr.latest_step()
        if step is None:
            return None

        def _restore():
            faults.inject("ckpt.restore")
            return mgr.restore(
                step,
                args=ocp.args.Composite(
                    params=ocp.args.StandardRestore(abstract_state.params),
                    opt_state=ocp.args.StandardRestore(
                        abstract_state.opt_state),
                    meta=ocp.args.JsonRestore(),
                ),
            )

        out = retry_call(_restore, policy=self._retry,
                         label=f"ckpt.restore_state[{step}]")
        return type(abstract_state)(
            step=jnp.asarray(out["meta"]["train_step"], jnp.int32),
            params=out["params"],
            opt_state=out["opt_state"],
        )

    def close(self) -> None:
        if self._mgr is not None:
            self._mgr.wait_until_finished()
            self._mgr.close()
            self._mgr = None


def _default_sharding():
    """Explicit single-device sharding for the unsharded restore path:
    orbax warns (and is topology-unsafe) when left to read sharding info
    from the checkpoint's own files."""
    return jax.sharding.SingleDeviceSharding(jax.devices()[0])


def _with_shardings(abstract, shardings):
    if shardings is None:
        default = _default_sharding()
        return jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=default),
            abstract,
        )
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        abstract,
        shardings,
    )


def abstract_params_like(model, sample_tokens, shardings=None):
    """Abstract params pytree for :meth:`CheckpointStore.restore_params`."""
    from progen_tpu.parallel.sharding import unbox

    abstract = jax.eval_shape(
        lambda k: unbox(model.init(k, sample_tokens))["params"],
        jax.random.key(0),
    )
    return _with_shardings(abstract, shardings)


def abstract_state_like(fns, key=None):
    """Abstract (shape/dtype/sharding) pytree for ``restore_state`` from a
    :class:`~progen_tpu.train.step.TrainFunctions` bundle."""
    key = key if key is not None else jax.random.key(0)
    abstract = jax.eval_shape(fns.init_state, key)
    return _with_shardings(abstract, fns.state_shardings)
