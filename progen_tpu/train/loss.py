"""Next-token loss with the EOS-from-padding trick.

Contract (reference ``/root/reference/progen_transformer/utils.py:42-65``):

* data rows are ``(seq_len + 1,)`` wide (BOS column prepended by the data
  pipeline); inputs are ``data[:-1]``, targets ``data[1:]``;
* token id 0 is padding; the loss mask keeps every non-pad target PLUS the
  FIRST pad position, so the model learns to emit 0 as end-of-sequence;
* loss is the masked mean of the per-token NLL within each row, then the
  plain mean over rows.

Natively batched ``(B, L)`` logits/targets — the reference gets batching
from an outer vmap (``utils.py:67``); the math per row is identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def eos_from_pad_mask(targets, ignore_index: int = 0):
    """Bool mask over targets: non-pad positions plus the first pad."""
    nonpad = targets != ignore_index
    first_pad = jnp.cumsum(~nonpad, axis=-1) == 1
    return nonpad | first_pad


def cross_entropy(logits, targets, ignore_index: int = 0):
    """Per-row masked-mean NLL: ``(B, L, V) x (B, L) -> (B,)``.

    Computed in f32 regardless of logits dtype — the log-softmax reduction
    is precision-sensitive.
    """
    logits = logits.astype(jnp.float32)
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    nll = jnp.take_along_axis(logprobs, targets[..., None].astype(jnp.int32),
                              axis=-1)[..., 0]
    mask = eos_from_pad_mask(targets, ignore_index)
    per_row = -(nll * mask).sum(axis=-1) / mask.sum(axis=-1)
    return per_row


def batch_loss(logits, targets, ignore_index: int = 0):
    """Scalar training loss: mean over rows of the per-row masked CE."""
    return cross_entropy(logits, targets, ignore_index).mean()
