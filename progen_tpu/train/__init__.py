from progen_tpu.train.loss import batch_loss, cross_entropy, eos_from_pad_mask
from progen_tpu.train.optimizer import decay_mask, make_optimizer
from progen_tpu.train.schedule import SCHEDULES, lr_at, make_lr_schedule
from progen_tpu.train.step import TrainFunctions, TrainState, make_train_functions

__all__ = [
    "batch_loss",
    "cross_entropy",
    "eos_from_pad_mask",
    "decay_mask",
    "make_optimizer",
    "SCHEDULES",
    "lr_at",
    "make_lr_schedule",
    "TrainFunctions",
    "TrainState",
    "make_train_functions",
]
