"""Training driver — the framework equivalent of the reference's
``train.py`` main loop (``/root/reference/train.py:59-228``), re-structured
for TPU:

* resume -> model/optimizer/state assembly -> epoch/step loop with
  grad-accum micro-steps, periodic validation, sampling and checkpointing
  (same cadence semantics, same resume-by-skip data contract);
* the loss is fetched to host only every ``log_every`` steps — the
  reference blocks on ``loss.item()`` EVERY step (``train.py:198``), a
  per-step device→host sync listed as a conscious drop in SURVEY.md §7;
* checkpoint step ids are global optimizer steps (monotonic across
  epochs), not the reference's per-epoch ``i`` which re-checkpoints at
  ``i == 0`` of every epoch;
* sampling uses the cached scan decoder, not O(L) full forwards;
* multi-host aware: per-host data sharding follows the mesh's batch
  shards (``core.mesh.process_batch_shards``) so inner mesh axes —
  tensor/seq — may span processes, with one writer for checkpoints/logs.
"""

from __future__ import annotations

import contextlib
import dataclasses
import math
import time
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from progen_tpu.checkpoint import CheckpointStore, abstract_state_like
from progen_tpu.parallel.sharding import (
    batch_sharding, superbatch_sharding, validate_tp_divisibility,
)
from progen_tpu.core.mesh import (
    Mesh, MeshConfig, make_mesh, process_batch_shards,
)
from progen_tpu.core.precision import make_policy
from progen_tpu.core.rng import KeySeq
from progen_tpu.data import decode_tokens, iterator_from_tfrecords_folder
from progen_tpu.data.prefetch import DevicePrefetcher, SuperbatchStager
from progen_tpu.decode import make_sampler
from progen_tpu.models import ProGen, ProGenConfig
from progen_tpu.observe import (
    ThroughputMeter,
    Tracker,
    get_registry,
    get_tracer,
    mfu,
    model_flops_per_token,
    peak_flops_per_chip,
    profile_trace,
)
from progen_tpu.resilience import faults
from progen_tpu.resilience.retry import RetryError, default_classifier
from progen_tpu.resilience.watchdog import FlightRecorder, Watchdog
from progen_tpu.train.memory import check_fits, device_hbm_bytes
from progen_tpu.train.memory import plan as memory_plan
from progen_tpu.train.optimizer import make_optimizer
from progen_tpu.train.schedule import make_lr_schedule
from progen_tpu.train.step import make_train_functions


def superstep_span(global_step: int, k_max: int, cadences: Sequence[int],
                   remaining: int) -> int:
    """Optimizer steps the next fused dispatch may cover: the distance
    from ``global_step`` to the NEAREST hook boundary among ``cadences``
    (every-N step counts; a hook fires when ``global_step % every == 0``),
    capped by ``k_max`` and the ``remaining`` epoch/max_steps budget.

    Always >= 1.  A span never crosses a boundary, and it ENDS exactly on
    the nearest boundary whenever that is within ``k_max`` steps — so
    every hook fires at the same global_step as the per-step loop, never
    skipped and never doubled."""
    span = min(k_max, remaining)
    for every in cadences:
        if every and every > 0:
            span = min(span, every - global_step % every)
    return max(1, span)


@dataclasses.dataclass
class TrainerConfig:
    # reference train.py:36-58 flags
    seed: int = 42
    batch_size: int = 4            # per-host micro-batch
    grad_accum_every: int = 4
    epochs: int = 100
    learning_rate: float = 2e-4
    weight_decay: float = 1e-3
    max_grad_norm: float = 0.5
    validate_every: int = 100
    sample_every: int = 500
    checkpoint_every: int = 1000
    checkpoint_keep_n: int = 500
    prime_length: int = 25
    mixed_precision: bool = True
    # tf.data sliding-window shuffle over the (pre-shuffled-at-prep) record
    # stream; 0 = off, matching the reference, whose only shuffle happens
    # at data prep (generate_data.py:119). Resume-by-skip is deterministic
    # even when shuffled: the skip applies to the seeded shuffle's OUTPUT
    # (data/tfrecord.py), replaying the interrupted run's record order.
    shuffle_buffer: int = 0
    # LR schedule (reference is constant-lr; warmup/decay needed >=1.2B)
    lr_schedule: str = "constant"  # "constant" | "cosine" | "linear"
    warmup_steps: int = 0
    schedule_steps: int | None = None  # decay horizon; defaults to max_steps
    lr_min_ratio: float = 0.1
    # TPU-native additions
    strategies: Sequence[str] = ("dp",)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    remat: bool = False
    remat_policy: str = "full"  # "full" | "dots" | "attn" (ProGen.remat_policy)
    attn_impl: str = "xla"  # "xla" | "pallas"
    sgu_impl: str = "xla"  # "xla" | "pallas" (blocked-causal fused SGU)
    # input-feed double buffering: batches transferred to device ahead of
    # the step that consumes them (0 = synchronous reference-style feed)
    prefetch_depth: int = 2
    # fused multi-step training: up to K optimizer steps per XLA dispatch
    # (train_multi_step's lax.scan over a staged (K, accum, B, L)
    # superbatch; 1 = classic per-step dispatch).  Spans shrink
    # automatically to land exactly on hook boundaries, so cadence
    # semantics are unchanged; costs ~2 superbatches of extra HBM
    # (train/memory.py accounts it).
    superstep: int = 1
    # checkpoint without stalling training: snapshot the state on-device
    # (one extra state-sized HBM copy) and run the device->host fetch +
    # write in a background thread.  The fetch is the dominant cost on
    # slow host links (measured 350s+ for 2.4 GB on the tunneled v5e —
    # orbax's async mode only backgrounds the DISK write, its
    # device->host copy blocks by design).  Disable when HBM headroom
    # cannot afford the snapshot copy.
    background_checkpoint: bool = True
    log_every: int = 10
    sample_top_k: int = 25         # reference hardcodes 25 (train.py:224)
    profile_dir: str | None = None
    max_steps: int | None = None   # optional hard stop (tests/benches)
    # -- resilience ---------------------------------------------------------
    # pre-loop sampler warm execution (minutes of decode compile on real
    # configs): off, a cold compile stalls the loop at the first
    # sample_every hook instead; independent of the flag, the warm-up is
    # skipped whenever no sample hook can fire in this run (e.g. a
    # preemption restart close to max_steps)
    warm_sampler: bool = True
    # total tries of the train loop: on a TRANSIENT failure (I/O retry
    # exhaustion, dropped tunnel...) the trainer re-restores from the
    # latest checkpoint and continues, up to run_attempts-1 times; fatal
    # errors always propagate immediately.  1 = fail fast (library
    # default; the train.py CLI defaults to 3).
    run_attempts: int = 1
    # seconds without a completed step before the watchdog dumps all
    # thread stacks + the flight-recorder ring to watchdog_dir and exits
    # nonzero (None = off).  Size it to several worst-case step times —
    # a hung collective never returns, a slow step does.
    watchdog_timeout: float | None = None
    watchdog_dir: str | None = None   # default: the tracker's run dir
    flight_recorder_n: int = 64       # last-N-events ring
    # live introspection: /healthz /statusz /metricsz /tracez /flightz on
    # a loopback port (0 = ephemeral, printed at startup; None = off).
    # Handlers read host-side state only — never a device sync.
    statusz_port: int | None = None


class Trainer:
    def __init__(
        self,
        model_config: ProGenConfig,
        cfg: TrainerConfig,
        data_path: str,
        checkpoint_path: str,
        tracker: Tracker | None = None,
        use_mesh: bool = True,
    ):
        self.model_config = model_config
        self.cfg = cfg
        self.data_path = data_path
        if cfg.superstep < 1:
            raise ValueError(f"superstep must be >= 1, got {cfg.superstep}")
        self.policy = make_policy(cfg.mixed_precision)
        self.mesh: Mesh | None = make_mesh(cfg.mesh) if use_mesh else None
        if (
            self.mesh is not None
            and self.mesh.shape.get("seq", 1) > 1
            and "sp" not in cfg.strategies
        ):
            raise ValueError(
                "mesh has seq axis "
                f"{self.mesh.shape['seq']} but 'sp' is not in strategies "
                f"{tuple(cfg.strategies)} — the seq devices would replicate "
                "work; add 'sp' or set MeshConfig(seq=1)"
            )
        if (
            self.mesh is not None
            and self.mesh.shape.get("tensor", 1) > 1
            and "tp" not in cfg.strategies
        ):
            raise ValueError(
                "mesh has tensor axis "
                f"{self.mesh.shape['tensor']} but 'tp' is not in strategies "
                f"{tuple(cfg.strategies)} — the tensor devices would "
                "replicate work; add 'tp' or set MeshConfig(tensor=1)"
            )
        if self.mesh is not None:
            # a tensor size that can't divide the model dims fails GSPMD
            # deep inside partitioning; fail here with the actual mistake
            validate_tp_divisibility(
                model_config, self.mesh.shape.get("tensor", 1),
                cfg.strategies)
        # Data-loading topology: the batch dim shards over ('data','fsdp')
        # only, so on a process-SPANNING tensor/seq axis several processes
        # sit at the same batch coordinates and must load IDENTICAL rows.
        # All per-process batch math below keys off the number of distinct
        # batch shards across processes — NOT jax.process_count(), which
        # over-counts whenever an inner axis spans processes.
        if self.mesh is not None and jax.process_count() > 1:
            self.data_shard_count, self.data_shard_index = (
                process_batch_shards(self.mesh))
        else:
            self.data_shard_count = jax.process_count()
            self.data_shard_index = jax.process_index()
        # The model needs the mesh when sequence mixing must be explicit:
        # sp routes attention/SGU through the context-parallel ops, and
        # pallas attention/SGU always run full-manual inside shard_map on a
        # mesh (pallas_call has no GSPMD partitioning rule).
        cp_mesh = (
            self.mesh
            if self.mesh is not None
            and ("sp" in cfg.strategies
                 or cfg.attn_impl == "pallas"
                 or cfg.sgu_impl == "pallas")
            else None
        )
        self.model = ProGen(config=model_config, policy=self.policy,
                            remat=cfg.remat, remat_policy=cfg.remat_policy,
                            attn_impl=cfg.attn_impl, sgu_impl=cfg.sgu_impl,
                            mesh=cp_mesh)
        self.lr_schedule = make_lr_schedule(
            cfg.lr_schedule,
            cfg.learning_rate,
            warmup_steps=cfg.warmup_steps,
            decay_steps=cfg.schedule_steps or cfg.max_steps,
            min_lr_ratio=cfg.lr_min_ratio,
        )
        self.optimizer = make_optimizer(
            learning_rate=self.lr_schedule,
            weight_decay=cfg.weight_decay,
            max_grad_norm=cfg.max_grad_norm,
            grad_accum_every=cfg.grad_accum_every,
        )
        # fail fast on configurations that cannot fit the chip — the
        # planner is calibrated to ~1% of XLA's buffer assignment
        # (progen_tpu/train/memory.py), so this replaces a many-minute
        # compile ending in RESOURCE_EXHAUSTED with an instant, actionable
        # error.  PROGEN_SKIP_MEMORY_CHECK=1 overrides.
        import os as _os

        if _os.environ.get("PROGEN_SKIP_MEMORY_CHECK") != "1":
            self.memory_plan = memory_plan(
                model_config,
                batch_size=cfg.batch_size * self.data_shard_count,
                mesh_shape=dict(self.mesh.shape) if self.mesh else None,
                strategies=cfg.strategies,
                remat=cfg.remat,
                remat_policy=cfg.remat_policy,
                attn_impl=cfg.attn_impl,
                sgu_impl=cfg.sgu_impl,
                mixed_precision=cfg.mixed_precision,
                grad_accum_every=cfg.grad_accum_every,
                checkpoint_snapshot=(cfg.background_checkpoint
                                     and jax.process_count() == 1),
                superstep_k=cfg.superstep,
            )
            gate_device = jax.local_devices()[0]
            err = check_fits(self.memory_plan, device_hbm_bytes(gate_device),
                             device_kind=gate_device.device_kind)
            if err is not None:
                raise ValueError(err)

        sample_tokens = jnp.zeros(
            (cfg.batch_size, model_config.seq_len), jnp.int32
        )
        self.fns = make_train_functions(
            self.model, self.optimizer, sample_tokens,
            mesh=self.mesh, strategies=cfg.strategies,
            grad_accum_every=cfg.grad_accum_every,
            lr_schedule=self.lr_schedule,
        )
        self.data_sharding = (
            batch_sharding(self.mesh) if self.mesh is not None else None
        )
        self.super_sharding = (
            superbatch_sharding(self.mesh) if self.mesh is not None else None
        )
        self.store = CheckpointStore(checkpoint_path, cfg.checkpoint_keep_n)
        self.tracker = tracker or Tracker(disabled=True)
        # in-training sampling runs against the params IN their training
        # shardings — they are never gathered to one chip
        self.sampler = make_sampler(
            model_config, self.policy, mesh=self.mesh,
            strategies=cfg.strategies,
            params_shardings=(
                self.fns.state_shardings.params
                if self.fns.state_shardings is not None else None
            ),
        )
        self.keys = KeySeq(cfg.seed)
        # 12 sync intervals (~300 steps at log_every 25): long enough to
        # be "sustained", short enough that the logged rate actually
        # slides past cold-start artifacts instead of averaging over the
        # whole run forever
        self.meter = ThroughputMeter(window=12)
        # Preemption safety (TPU VMs are preemptible; the reference's only
        # fault story is its periodic checkpoint): single-process runs get
        # a SIGTERM handler that requests a checkpoint at the next step
        # boundary; multi-host runs use orbax's coordination-service-backed
        # reached_preemption so all hosts agree (a per-host signal flag
        # would desync the cooperative save).
        self._preempt_requested = False
        self._ckpt_thread = None
        # flight recorder always on (O(1) dict appends); the watchdog
        # only when configured.  The recorder outlives run() attempts so
        # a post-retry dump still shows the pre-failure history.
        self._recorder = FlightRecorder(cfg.flight_recorder_n)
        # span ring shares the process tracer (enabled via
        # configure_tracing by the entry point); every trainer span also
        # lands in the flight recorder so a watchdog trip shows the
        # loop's recent phases even when tracing is off
        self._tracer = get_tracer()
        self._watchdog: Watchdog | None = None
        # live introspection plane: health/status read the flight
        # recorder and registry (host floats published at the loop's one
        # batched device_get) — an enabled trainer runs the identical
        # step sequence, the plane never syncs the device
        self._statusz = None
        if cfg.statusz_port is not None and jax.process_index() == 0:
            from progen_tpu.observe.statusz import StatuszServer

            self._statusz = StatuszServer(
                role="trainer", port=cfg.statusz_port,
                providers={"health": self._statusz_health,
                           "status": self._statusz_status,
                           "flight": self._recorder.snapshot})
            port = self._statusz.start()
            print(f"trainer statusz on http://127.0.0.1:{port}",
                  flush=True)
        if jax.process_count() == 1:
            import signal

            try:
                signal.signal(signal.SIGTERM, self._request_preempt_checkpoint)
            except ValueError:
                pass  # not the main thread (e.g. under a test runner)

    def _request_preempt_checkpoint(self, signum=None, frame=None) -> None:
        self._preempt_requested = True

    def _note_phase(self, name: str, t0: float, **fields: Any) -> None:
        """One loop phase -> a trace span AND a flight-recorder event, so
        a watchdog trip shows the recent phase history whether or not the
        process is tracing (the recorder is always on)."""
        dur = time.perf_counter() - t0
        self._tracer.add(name, t0, dur, **fields)
        self._recorder.record(name, dur_s=round(dur, 6), **fields)

    def _statusz_health(self) -> dict:
        events = self._recorder.snapshot()
        last_step = None
        for e in reversed(events):
            if e.get("kind") == "step":
                last_step = e
                break
        return {"last_step": last_step,
                "watchdog": self._watchdog is not None,
                "preempt_requested": self._preempt_requested}

    def _statusz_status(self) -> dict:
        return {"model": self.model_config.to_dict(),
                "superstep": self.cfg.superstep,
                "batch_size": self.cfg.batch_size,
                "max_steps": self.cfg.max_steps,
                "recent": self._recorder.snapshot()[-16:]}

    def _publish_train_health(self, log: dict, step: int) -> None:
        """Training-health sentinels into the shared registry: the
        trainer's /statusz shows training health, not just serving.
        ``log`` holds host floats from the loop's one batched
        ``jax.device_get`` — this publishes them without any extra
        device sync."""
        registry = get_registry()
        registry.gauge("train.step").set(step)
        registry.gauge("train.loss").set(log["loss"])
        registry.gauge("train.grad_norm").set(log["grad_norm"])
        registry.gauge("train.lr").set(log["lr"])
        if not (math.isfinite(log["loss"])
                and math.isfinite(log["grad_norm"])):
            registry.counter("train.nonfinite_steps").inc()

    def _to_device(self, np_batch) -> jax.Array:
        """Host batch -> device array for the jitted step.

        Multi-process (one controller per host): every host holds only ITS
        data shard's rows of the global batch (processes sharing a batch
        coordinate — e.g. the members of a process-spanning tensor axis —
        hold identical copies); ``make_array_from_process_local_data``
        assembles the global sharded array without any host ever
        materializing the full batch.  The global shape is passed
        explicitly: with replication across tensor-axis processes the
        per-dimension inference would over-scale the batch dim.  Single
        process: a plain transfer (jit's in_shardings lay it out)."""
        if self.mesh is not None and jax.process_count() > 1:
            local = np.asarray(np_batch)
            return jax.make_array_from_process_local_data(
                self.data_sharding, local,
                (local.shape[0] * self.data_shard_count,) + local.shape[1:],
            )
        return jnp.asarray(np_batch)

    def _super_to_device(self, np_superbatch) -> jax.Array:
        """Host ``(K, accum, B, L)`` superbatch -> device array for the
        fused step; multi-process, every host contributes its data shard's
        rows of the batch dim (axis 2) — K and accum are replicated scan
        axes, and tensor-axis processes contribute identical copies."""
        if self.mesh is not None and jax.process_count() > 1:
            local = np.asarray(np_superbatch)
            gshape = (local.shape[0], local.shape[1],
                      local.shape[2] * self.data_shard_count, local.shape[3])
            return jax.make_array_from_process_local_data(
                self.super_sharding, local, gshape
            )
        return jnp.asarray(np_superbatch)

    def _warm_compiles(self, state, global_step: int = 0) -> None:
        """AOT-compile every jitted program the loop will call, BEFORE the
        throughput meter starts — the decode scan alone is minutes of
        compile cold, and paying it mid-loop stalls training (measured: a
        ~5.5-minute sampler compile at the first sample_every hook of the
        round-3 run).  Only active when the persistent XLA cache is on
        (the CLIs enable it): ``lower().compile()`` populates the on-disk
        cache the later jit call reads, but without that cache the warm
        work could not be reused and would just double compile time."""
        cfg = self.cfg
        try:
            have_disk_cache = bool(jax.config.jax_compilation_cache_dir)
        except AttributeError:
            have_disk_cache = False

        def abstract(tree):
            return jax.tree.map(
                lambda x: jax.ShapeDtypeStruct(
                    jnp.shape(x), x.dtype, sharding=getattr(x, "sharding", None)
                ),
                tree,
            )

        st = abstract(state)
        # the REAL batch is global — cfg.batch_size rows per data shard
        # assembled via make_array_from_process_local_data (_to_device) —
        # so the warm program must match that shape+sharding or multi-host
        # runs (the ones that compile slowest) still compile cold at step 1
        batch = jax.ShapeDtypeStruct(
            (cfg.batch_size * self.data_shard_count,
             self.model_config.seq_len + 1),
            jnp.int32,
            sharding=self.data_sharding,
        )
        # the real sampler call feeds prime/key REPLICATED over the global
        # mesh (_replicated_prime_and_key); the warm program must carry the
        # same shardings or the multi-host compile-cache entry never
        # matches the mid-loop call and step-1 still compiles cold
        repl = None
        if self.mesh is not None and jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
        prime = jax.ShapeDtypeStruct((1, cfg.prime_length), jnp.int32,
                                     sharding=repl)
        key0 = jax.random.key(0)
        key_abstract = jax.ShapeDtypeStruct(key0.shape, key0.dtype,
                                            sharding=repl)

        # a hook that cannot fire between here and the end of the run
        # (resume near max_steps, or a cadence past the horizon) buys
        # nothing from warming — notably the sampler's minutes-long decode
        # compile on a preemption restart
        ms = cfg.max_steps  # None = epochs-bounded: assume hooks fire

        def hook_due(every: int) -> bool:
            next_hook = (global_step // every + 1) * every
            return ms is None or next_hook <= ms

        validate_due = hook_due(cfg.validate_every)
        sample_due = cfg.warm_sampler and hook_due(cfg.sample_every)

        if cfg.superstep > 1:
            # the superstep loop dispatches exactly two program shapes:
            # the full-K fused scan and the K=1 residual used to walk up
            # to hook boundaries (_run_loop_superstep)
            def super_abstract(k):
                return jax.ShapeDtypeStruct(
                    (k, max(1, cfg.grad_accum_every),
                     cfg.batch_size * self.data_shard_count,
                     self.model_config.seq_len + 1),
                    jnp.int32,
                    sharding=self.super_sharding,
                )

            programs = [
                ("train_multi_step", lambda: self.fns.train_multi_step.lower(
                    st, super_abstract(cfg.superstep))),
                ("train_multi_step[k=1]",
                 lambda: self.fns.train_multi_step.lower(
                     st, super_abstract(1))),
            ]
        else:
            programs = [
                ("train_step", lambda: self.fns.train_step.lower(st, batch)),
            ]
        if validate_due:
            programs.append(
                ("eval_step", lambda: self.fns.eval_step.lower(st, batch)))
        if sample_due:
            programs.append(
                ("sampler", lambda: self.sampler.lower(
                    {"params": st.params}, key_abstract, prime,
                    length=self.model_config.seq_len,
                    top_k=cfg.sample_top_k,
                )))
        if have_disk_cache:
            # without the persistent cache, lower().compile() work could
            # not be reused by the later jit calls and would just double
            # compile time; the execution warm-up below covers that case
            for name, lower in programs:
                try:
                    lower().compile()
                except Exception as e:
                    # warming is an optimization; the loop compiles on
                    # demand
                    if jax.process_index() == 0:
                        print(f"warning: {name} precompile failed ({e!r})")

        # lower().compile() fills the DISK cache, but the loop's jit calls
        # still pay a fresh trace + cache deserialization the first time
        # they run — measured ~20s at the first validate_every hook of a
        # small-config run, a mid-loop stall the throughput window eats.
        # Execute the two NON-DONATING programs once here so their
        # in-memory executables exist before the meter starts (train_step
        # donates its state buffers, so its first-call load stays at step
        # 1, inside the startup ramp).  Runs with or without the disk
        # cache; skipped for hooks the run can provably never reach.
        # separate try blocks: a failed eval warm-up must not skip the
        # sampler warm-up (whose mid-loop stall is the larger one)
        if validate_due:
            try:
                dummy = self._to_device(np.zeros(
                    (cfg.batch_size, self.model_config.seq_len + 1),
                    np.int32))
                jax.block_until_ready(self.fns.eval_step(state, dummy))
            except Exception as e:
                if jax.process_index() == 0:
                    print(f"warning: eval warm execution failed ({e!r})")
        if sample_due:
            try:
                prime_arr, key = self._replicated_prime_and_key(
                    np.zeros((1, cfg.prime_length), np.int32),
                    jax.random.key(0))
                jax.block_until_ready(self.sampler(
                    {"params": state.params}, key, prime_arr,
                    length=self.model_config.seq_len, top_k=cfg.sample_top_k,
                ))
            except Exception as e:
                if jax.process_index() == 0:
                    print(f"warning: sampler warm execution failed ({e!r})")

    # -- state ---------------------------------------------------------------

    def restore_or_init(self):
        """Returns (state, start_seq_index, run_id). Restores the latest
        checkpoint when one exists (model config in the checkpoint wins —
        reference train.py:101-102)."""
        meta = self.store.restore_meta()
        if meta is None:
            state = self.fns.init_state(next(self.keys))
            return state, 0, None
        stored_cfg = ProGenConfig.from_dict(meta["model_config"])
        if stored_cfg != self.model_config:
            raise ValueError(
                "checkpoint model config differs from requested config; "
                "rebuild the Trainer with the stored config: "
                f"{stored_cfg}"
            )
        state = self.store.restore_state(abstract_state_like(self.fns))
        return state, meta["next_seq_index"], meta.get("run_id")

    # -- loop ----------------------------------------------------------------

    def run(self) -> dict[str, Any]:
        """Crash-safe driver: up to ``cfg.run_attempts`` tries of the train
        loop.  A TRANSIENT failure (I/O retry exhaustion, dropped tunnel,
        injected fault) re-restores from the latest checkpoint — at worst
        replaying the steps since the last save — and continues; fatal
        errors (and exhaustion of the attempt budget) propagate."""
        attempts = max(1, self.cfg.run_attempts)
        for attempt in range(1, attempts + 1):
            try:
                return self._run_attempt()
            except Exception as e:
                # RetryError means the I/O layer already burned its finer-
                # grained budget on something transient; the coarse answer
                # is a re-restore, not a crash
                transient = isinstance(e, RetryError) or default_classifier(e)
                if attempt >= attempts or not transient:
                    raise
                self._recorder.record("run-retry", attempt=attempt,
                                      error=repr(e))
                if jax.process_index() == 0:
                    print(
                        f"transient training failure (attempt "
                        f"{attempt}/{attempts}): {e!r}; re-restoring from "
                        "the latest checkpoint",
                        flush=True,
                    )
                try:
                    # let any in-flight background save commit so the
                    # re-restore starts from the newest durable step
                    self._join_checkpoint_thread()
                    self.store.wait_until_finished()
                except Exception:
                    pass  # the save that failed is why we are here

    def _run_attempt(self) -> dict[str, Any]:
        cfg = self.cfg
        seq_len = self.model_config.seq_len
        # data sharding follows the mesh's batch shards, not raw process
        # counts: tensor/seq-axis processes share a shard (identical rows)
        shard_count = self.data_shard_count
        shard_index = self.data_shard_index

        total_train, get_train = iterator_from_tfrecords_folder(
            self.data_path, "train")
        total_valid, get_valid = iterator_from_tfrecords_folder(
            self.data_path, "valid")
        assert total_train > 0, "no protein sequences found for training"
        assert total_valid > 0, "no protein sequences found for validation"

        state, start_seq_index, _ = self.restore_or_init()
        # The stored cursor is UN-WRAPPED (monotonic across epochs).  A
        # shuffled stream orders each corpus pass differently (the sliding
        # buffer mixes across epoch boundaries), so resuming a multi-epoch
        # run must skip the interrupted stream's full OUTPUT count — the
        # wrapped first-pass position would replay epoch-1 record order.
        # Unshuffled passes are identical, so the cheap wrapped skip is
        # exact there and avoids decompressing whole skipped epochs.
        # (Skip past-the-end is safe either way: the reader repeats the
        # record stream BEFORE skipping, data/tfrecord.py.)
        epoch_position = start_seq_index % total_train
        skip = start_seq_index if cfg.shuffle_buffer else epoch_position

        # global effective batch: all data shards' micro-batches x accum
        effective_batch = cfg.batch_size * cfg.grad_accum_every * shard_count

        train_it = get_train(
            seq_len=seq_len, batch_size=cfg.batch_size, skip=skip,
            loop=True, process_count=shard_count, process_index=shard_index,
            shuffle_buffer=cfg.shuffle_buffer, seed=cfg.seed,
        )
        stager = None
        if cfg.superstep > 1:
            # fused loop: the stager owns the iterator and assembles
            # (K, accum, B, L) superbatches, transferring the next one
            # while the current superstep executes
            stager = SuperbatchStager(
                train_it, self._super_to_device,
                accum=cfg.grad_accum_every, k_max=cfg.superstep,
                depth=max(1, cfg.prefetch_depth),
            )
        elif cfg.prefetch_depth > 0:
            train_it = DevicePrefetcher(
                train_it, self._to_device, depth=cfg.prefetch_depth
            )
        valid_it = get_valid(
            seq_len=seq_len, batch_size=cfg.batch_size, loop=True,
            process_count=shard_count, process_index=shard_index,
        )

        num_params = sum(x.size for x in jax.tree.leaves(state.params))
        if jax.process_index() == 0:
            print(f"params: {num_params:,}")
            print(f"sequence length: {seq_len}")
            print(f"num sequences: {total_train}")
            print(f"starting from sequence {start_seq_index}")

        # TrainState.step counts MICRO-steps (one per train_step call);
        # the driver's global_step counts optimizer-effective steps.
        global_step = int(state.step) // cfg.grad_accum_every
        seq_cursor = start_seq_index
        last_loss = None
        pending_tokens = 0

        self._warm_compiles(state, global_step)

        watchdog = None
        if cfg.watchdog_timeout:
            out_dir = cfg.watchdog_dir or str(
                getattr(self.tracker, "_dir", None) or ".")
            watchdog = Watchdog(
                cfg.watchdog_timeout, out_dir=out_dir,
                recorder=self._recorder,
                label=f"train from step {global_step}",
            )
            watchdog.start()
        self._watchdog = watchdog

        try:
            if stager is not None:
                return self._run_loop_superstep(
                    state, stager, valid_it, total_train, epoch_position,
                    effective_batch, global_step, seq_cursor, last_loss,
                    pending_tokens,
                )
            return self._run_loop(
                state, train_it, valid_it, total_train, epoch_position,
                effective_batch, global_step, seq_cursor, last_loss,
                pending_tokens,
            )
        finally:
            if watchdog is not None:
                watchdog.stop()
            self._watchdog = None
            if stager is not None:
                stager.close()
            elif isinstance(train_it, DevicePrefetcher):
                train_it.close()
            # an exception/KeyboardInterrupt must not kill the daemon
            # checkpoint thread mid-write and lose the last save
            self._join_checkpoint_thread()
            self.store.wait_until_finished()

    def _run_loop(self, state, train_it, valid_it, total_train,
                  epoch_position, effective_batch, global_step, seq_cursor,
                  last_loss, pending_tokens):
        cfg = self.cfg
        seq_len = self.model_config.seq_len
        process_index = jax.process_index()
        num_params = sum(x.size for x in jax.tree.leaves(state.params))
        flops_per_token = model_flops_per_token(self.model_config, num_params,
                                                sgu_impl=cfg.sgu_impl)
        peak = peak_flops_per_chip()  # None off-TPU -> mfu not logged
        # the prefetcher already returns device arrays
        prefetched = isinstance(train_it, DevicePrefetcher)
        watchdog = self._watchdog

        with profile_trace(cfg.profile_dir):
            for epoch in range(1, cfg.epochs + 1):
                if process_index == 0:
                    print(f"==== starting epoch: {epoch} ====")
                epoch_start = epoch_position if epoch == 1 else 0
                steps_per_epoch = max(
                    1, (total_train - epoch_start) // effective_batch
                )
                for i in range(steps_per_epoch):
                    if watchdog is not None:
                        watchdog.beat(f"step {global_step + 1}")
                    faults.inject("train.step")
                    # the attempt's FIRST step compiles train_step inline
                    # (its donated buffers keep it out of _warm_compiles'
                    # execution warm-up) — minutes of legitimate stall the
                    # watchdog must not book as a hang
                    grace = (
                        watchdog.paused()
                        if watchdog is not None and epoch == 1 and i == 0
                        else contextlib.nullcontext()
                    )
                    t0 = time.perf_counter()
                    with grace:
                        for _ in range(cfg.grad_accum_every):
                            batch = (next(train_it) if prefetched
                                     else self._to_device(next(train_it)))
                            state, metrics = self.fns.train_step(state, batch)
                    global_step += 1
                    # dispatch time only (the step runs async on device);
                    # a long span here means input starvation or a compile
                    self._note_phase("train.step_dispatch", t0,
                                     step=global_step)
                    # monotonic, never wrapped: the checkpointed cursor must
                    # identify the position in the multi-epoch STREAM
                    seq_cursor = seq_cursor + effective_batch
                    pending_tokens += effective_batch * seq_len

                    will_hook = (
                        global_step % cfg.checkpoint_every == 0
                        or global_step % cfg.validate_every == 0
                        or global_step % cfg.sample_every == 0
                    )
                    if global_step % cfg.log_every == 0:
                        # one batched transfer blocks until the step chain
                        # is executed — the only trustworthy sync point, so
                        # the meter ticks HERE with the tokens since the
                        # last sync (one device_get, not one per metric)
                        t0 = time.perf_counter()
                        host_metrics = jax.device_get(metrics)  # graftcheck: disable=host-sync
                        last_loss = float(host_metrics["loss"])
                        self.meter.tick(pending_tokens)
                        pending_tokens = 0
                        log = {
                            "loss": last_loss,
                            "grad_norm": float(host_metrics["grad_norm"]),
                            # computed on device by the step itself: the
                            # schedule value this update was actually
                            # scaled with (no host-side reconstruction
                            # from global_step)
                            "lr": float(host_metrics["lr"]),
                        }
                        tps = self.meter.tokens_per_sec_per_chip
                        if tps is not None:
                            log["tokens_per_sec_per_chip"] = tps
                            util = mfu(tps, flops_per_token, peak)
                            if util is not None:
                                log["mfu"] = util
                        self.tracker.log(log, global_step)
                        self._recorder.record("step", step=global_step, **log)
                        # log span covers the device_get sync + metric
                        # assembly — the loop's only blocking point
                        self._note_phase("train.log", t0, step=global_step)
                        self.meter.publish(get_registry())
                        self._publish_train_health(log, global_step)
                        if process_index == 0:
                            print(f"step {global_step} loss: {last_loss:.4f}")

                    if will_hook and pending_tokens:
                        # hook cadences need not align with log_every: sync
                        # and tick BEFORE the hooks so their wall time is
                        # never rated against these steps' tokens (and the
                        # hook's own blocking never absorbs them)
                        # a pure barrier: no value is needed, so don't pay
                        # for a transfer on top of the wait
                        jax.block_until_ready(metrics["grad_norm"])  # graftcheck: disable=host-sync
                        self.meter.tick(pending_tokens)
                        pending_tokens = 0

                    hooks_ran = False
                    if global_step % cfg.checkpoint_every == 0:
                        t0 = time.perf_counter()
                        self._checkpoint(state, seq_cursor)
                        self._note_phase("train.checkpoint", t0,
                                         step=global_step)
                        hooks_ran = True

                    if global_step % cfg.validate_every == 0:
                        t0 = time.perf_counter()
                        vbatch = self._to_device(next(valid_it))
                        vmetrics = self.fns.eval_step(state, vbatch)
                        vloss = float(jax.device_get(vmetrics["loss"]))  # graftcheck: disable=host-sync
                        self.tracker.log({"valid_loss": vloss}, global_step)
                        self._note_phase("train.validate", t0,
                                         step=global_step, loss=vloss)
                        if process_index == 0:
                            print(f"valid_loss: {vloss:.4f}")
                        hooks_ran = True

                    if global_step % cfg.sample_every == 0:
                        self._sample_and_log(state, next(valid_it), global_step)
                        hooks_ran = True

                    if hooks_ran:
                        # hook time (eval/sampling/checkpoint IO) is not
                        # training time; drop it from the meter's window
                        self.meter.rebase()
                        # ...nor is it a stall: re-arm the watchdog clock
                        if watchdog is not None:
                            watchdog.beat(f"hooks at step {global_step}")

                    if (self._preempt_requested
                            or self.store.reached_preemption(global_step)):
                        # the process exits right after: the save must
                        # fully commit before we let it
                        self._checkpoint(state, seq_cursor, wait=True)
                        if process_index == 0:
                            print(
                                f"preemption checkpoint at step {global_step}; "
                                "exiting (resume restarts here)"
                            )
                        return {"state": state, "loss": last_loss,
                                "step": global_step, "preempted": True}

                    if cfg.max_steps is not None and global_step >= cfg.max_steps:
                        self._checkpoint(state, seq_cursor, wait=True)
                        return self._finish(state, last_loss, global_step)
        return self._finish(state, last_loss, global_step)

    def _run_loop_superstep(self, state, stager, valid_it, total_train,
                            epoch_position, effective_batch, global_step,
                            seq_cursor, last_loss, pending_tokens):
        """Fused-superstep variant of :meth:`_run_loop` (cfg.superstep > 1).

        Each iteration advances a SPAN of optimizer steps with
        ``train_multi_step`` dispatches: :func:`superstep_span` sizes the
        span to land exactly on the nearest hook boundary, so every
        log/checkpoint/validate/sample/epoch boundary fires at the same
        global_step as the per-step loop.  A full span is ONE K=superstep
        dispatch; a residual span (boundary closer than K) walks up with
        the K=1 program instead of compiling one XLA program per distinct
        span length — the loop only ever compiles two shapes."""
        cfg = self.cfg
        seq_len = self.model_config.seq_len
        process_index = jax.process_index()
        num_params = sum(x.size for x in jax.tree.leaves(state.params))
        flops_per_token = model_flops_per_token(self.model_config, num_params,
                                                sgu_impl=cfg.sgu_impl)
        peak = peak_flops_per_chip()
        watchdog = self._watchdog
        k_max = cfg.superstep
        cadences = (cfg.log_every, cfg.checkpoint_every, cfg.validate_every,
                    cfg.sample_every)
        pending_steps = 0
        compiled_ks: set = set()

        with profile_trace(cfg.profile_dir):
            for epoch in range(1, cfg.epochs + 1):
                if process_index == 0:
                    print(f"==== starting epoch: {epoch} ====")
                epoch_start = epoch_position if epoch == 1 else 0
                steps_per_epoch = max(
                    1, (total_train - epoch_start) // effective_batch
                )
                done = 0
                while done < steps_per_epoch:
                    remaining = steps_per_epoch - done
                    if cfg.max_steps is not None:
                        remaining = min(remaining,
                                        cfg.max_steps - global_step)
                    span = superstep_span(global_step, k_max, cadences,
                                          remaining)
                    if watchdog is not None:
                        watchdog.beat(
                            f"steps {global_step + 1}..{global_step + span}")
                    # one inject per optimizer step: a fault plan's at=N
                    # fires before step N runs, as in the per-step loop
                    for _ in range(span):
                        faults.inject("train.step")
                    k = k_max if span == k_max else 1
                    # each of the two program shapes compiles inline on
                    # its first dispatch (donated buffers keep them out of
                    # _warm_compiles' execution warm-up) — legitimate
                    # stall the watchdog must not book as a hang
                    grace = (
                        watchdog.paused()
                        if watchdog is not None and k not in compiled_ks
                        else contextlib.nullcontext()
                    )
                    compiled_ks.add(k)
                    t0 = time.perf_counter()
                    with grace:
                        for _ in range(span // k):
                            state, metrics = self.fns.train_multi_step(
                                state, stager.get(k))
                    done += span
                    global_step += span
                    self._note_phase("train.step_dispatch", t0,
                                     step=global_step, span=span)
                    seq_cursor = seq_cursor + effective_batch * span
                    pending_tokens += effective_batch * seq_len * span
                    pending_steps += span

                    will_hook = (
                        global_step % cfg.checkpoint_every == 0
                        or global_step % cfg.validate_every == 0
                        or global_step % cfg.sample_every == 0
                    )
                    if global_step % cfg.log_every == 0:
                        # ONE batched transfer fetches the whole span's
                        # K-stacked metrics — the sync point the meter
                        # ticks at, now rating K steps per sync
                        t0 = time.perf_counter()
                        host_metrics = jax.device_get(metrics)  # graftcheck: disable=host-sync
                        last_loss = float(host_metrics["loss"][-1, -1])
                        self.meter.tick(pending_tokens, steps=pending_steps)
                        pending_tokens = 0
                        pending_steps = 0
                        log = {
                            "loss": last_loss,
                            "grad_norm": float(
                                host_metrics["grad_norm"][-1, -1]),
                            # computed on device by the step itself: the
                            # schedule value the final update in the span
                            # was actually scaled with
                            "lr": float(host_metrics["lr"][-1]),
                        }
                        tps = self.meter.tokens_per_sec_per_chip
                        if tps is not None:
                            log["tokens_per_sec_per_chip"] = tps
                            util = mfu(tps, flops_per_token, peak)
                            if util is not None:
                                log["mfu"] = util
                        sps = self.meter.steps_per_sec
                        if sps is not None:
                            log["steps_per_sec"] = sps
                        self.tracker.log(log, global_step)
                        self._recorder.record("step", step=global_step, **log)
                        # log span covers the device_get sync + metric
                        # assembly — the loop's only blocking point
                        self._note_phase("train.log", t0, step=global_step)
                        self.meter.publish(get_registry())
                        self._publish_train_health(log, global_step)
                        if process_index == 0:
                            print(f"step {global_step} loss: {last_loss:.4f}")

                    if will_hook and pending_tokens:
                        # hook cadences need not align with log_every:
                        # sync and tick BEFORE the hooks so their wall
                        # time is never rated against these steps' tokens
                        jax.block_until_ready(metrics["grad_norm"])  # graftcheck: disable=host-sync
                        self.meter.tick(pending_tokens, steps=pending_steps)
                        pending_tokens = 0
                        pending_steps = 0

                    hooks_ran = False
                    if global_step % cfg.checkpoint_every == 0:
                        t0 = time.perf_counter()
                        self._checkpoint(state, seq_cursor)
                        self._note_phase("train.checkpoint", t0,
                                         step=global_step)
                        hooks_ran = True

                    if global_step % cfg.validate_every == 0:
                        t0 = time.perf_counter()
                        vbatch = self._to_device(next(valid_it))
                        vmetrics = self.fns.eval_step(state, vbatch)
                        vloss = float(jax.device_get(vmetrics["loss"]))  # graftcheck: disable=host-sync
                        self.tracker.log({"valid_loss": vloss}, global_step)
                        self._note_phase("train.validate", t0,
                                         step=global_step, loss=vloss)
                        if process_index == 0:
                            print(f"valid_loss: {vloss:.4f}")
                        hooks_ran = True

                    if global_step % cfg.sample_every == 0:
                        self._sample_and_log(state, next(valid_it),
                                             global_step)
                        hooks_ran = True

                    if hooks_ran:
                        # hook time (eval/sampling/checkpoint IO) is not
                        # training time; drop it from the meter's window
                        self.meter.rebase()
                        if watchdog is not None:
                            watchdog.beat(f"hooks at step {global_step}")

                    if (self._preempt_requested
                            or self.store.reached_preemption(global_step)):
                        self._checkpoint(state, seq_cursor, wait=True)
                        if process_index == 0:
                            print(
                                f"preemption checkpoint at step "
                                f"{global_step}; exiting (resume restarts "
                                "here)"
                            )
                        return {"state": state, "loss": last_loss,
                                "step": global_step, "preempted": True}

                    if (cfg.max_steps is not None
                            and global_step >= cfg.max_steps):
                        self._checkpoint(state, seq_cursor, wait=True)
                        return self._finish(state, last_loss, global_step)
        return self._finish(state, last_loss, global_step)

    def _finish(self, state, last_loss, global_step: int) -> dict[str, Any]:
        """Full-validation eval loss (BASELINE.md's second metric) at the
        end of training, logged and returned."""
        self._join_checkpoint_thread()
        self.store.wait_until_finished()  # commit any in-flight async save
        valid_loss = self.evaluate(state)
        if valid_loss is not None:
            self.tracker.log({"full_valid_loss": valid_loss}, global_step)
            if jax.process_index() == 0:
                print(f"full valid loss: {valid_loss:.4f}")
        return {"state": state, "loss": last_loss, "step": global_step,
                "valid_loss": valid_loss}

    def evaluate(self, state, max_batches: int | None = None) -> float | None:
        """Mean per-row loss over the ENTIRE validation split, one pass —
        the honest "eval loss" number for BASELINE.md (the in-loop
        ``validate_every`` probe times a single batch, matching the
        reference ``train.py:213-217``).

        The final partial batch is zero-padded up to the static batch shape
        (no jit retrace) and the pad rows are masked out via the step's
        ``real_rows`` output, so the mean is exact over all records.
        Multi-host: every host feeds its shard; outputs are replicated, so
        all hosts return the same number.
        """
        cfg = self.cfg
        total_valid, get_valid = iterator_from_tfrecords_folder(
            self.data_path, "valid")
        if total_valid == 0:
            return None
        shard_count = self.data_shard_count
        it = get_valid(
            seq_len=self.model_config.seq_len, batch_size=cfg.batch_size,
            loop=False, process_count=shard_count,
            process_index=self.data_shard_index,
        )
        # every host must run the SAME number of eval_step calls (SPMD);
        # round-robin sharding leaves data shards with up to 1 extra
        # record, so the count comes from the largest shard, and exhausted
        # shards feed all-pad batches (masked out by real_rows).
        width = self.model_config.seq_len + 1
        max_host_records = -(-total_valid // shard_count)
        n_batches = -(-max_host_records // cfg.batch_size)
        if max_batches is not None:
            n_batches = min(n_batches, max_batches)
        loss_sum, rows = 0.0, 0
        for _ in range(n_batches):
            np_batch = next(it, None)
            if np_batch is None:
                np_batch = np.zeros((cfg.batch_size, width), np.int32)
            elif np_batch.shape[0] < cfg.batch_size:
                pad = np.zeros(
                    (cfg.batch_size - np_batch.shape[0], np_batch.shape[1]),
                    np_batch.dtype,
                )
                np_batch = np.concatenate([np_batch, pad])
            metrics = self.fns.eval_step(state, self._to_device(np_batch))
            # one transfer for both reductions instead of two np.asarray
            # syncs plus two scalar pulls
            host = jax.device_get(metrics)  # graftcheck: disable=host-sync
            per_row = np.asarray(host["per_row_loss"])
            real = np.asarray(host["real_rows"])
            loss_sum += float((per_row * real).sum())
            rows += int(real.sum())
        return loss_sum / rows if rows else None

    # -- hooks ---------------------------------------------------------------

    def _join_checkpoint_thread(self) -> None:
        if self._ckpt_thread is not None:
            self._ckpt_thread.join()
            self._ckpt_thread = None

    def _checkpoint(self, state, next_seq_index: int, wait: bool = False) -> None:
        step = int(state.step)
        run_id = self.tracker.run_id
        model_config = self.model_config.to_dict()

        def do_save(snapshot) -> None:
            # save() skips steps already in the store, so the
            # exit/preemption save after a same-step periodic hook costs
            # nothing
            self._recorder.record("checkpoint-start", step=step,
                                  next_seq_index=next_seq_index)
            saved = self.store.save(
                step, snapshot,
                next_seq_index=next_seq_index,
                model_config=model_config,
                run_id=run_id,
            )
            self._recorder.record("checkpoint-done", step=step,
                                  saved=bool(saved))
            if saved and jax.process_index() == 0:
                print(
                    f"checkpoint to start at sequence index of {next_seq_index}"
                )

        if not self.cfg.background_checkpoint or jax.process_count() > 1:
            # multi-host: the cooperative orbax save is a collective —
            # every host must enter it in lockstep, so keep it on the
            # main thread
            do_save(state)
            if wait:
                self.store.wait_until_finished()
            return

        # one save in flight at a time (bounds the extra HBM to one
        # state-sized snapshot and keeps store calls single-threaded).
        # A PERIODIC save that lands while the previous one is still
        # draining is SKIPPED, not queued: on slow host links the fetch
        # (~300s for 2.4 GB on the tunneled v5e) can exceed the
        # checkpoint cadence, and blocking training to wait would
        # reintroduce the very stall this path removes — you cannot
        # durably checkpoint faster than the link drains.  Exit and
        # preemption saves (wait=True) always join and write.
        if self._ckpt_thread is not None and self._ckpt_thread.is_alive():
            if not wait:
                if jax.process_index() == 0:
                    print(f"checkpoint at step {step} skipped: previous "
                          "save still writing")
                return
        self._join_checkpoint_thread()
        # on-device copy: O(ms), and donation of `state` by the next
        # train_step cannot invalidate it (XLA sequences the copy before
        # the donated buffers are reused)
        snapshot = jax.tree.map(jnp.copy, state)
        import threading

        self._ckpt_thread = threading.Thread(
            target=do_save, args=(snapshot,), name="progen-checkpoint",
            daemon=True,
        )
        self._ckpt_thread.start()
        if wait:
            self._join_checkpoint_thread()
            self.store.wait_until_finished()

    def _replicated_prime_and_key(self, prime_np, key):
        """Sampler inputs for the global mesh: in multi-process runs both
        the prime and the rng key must be re-materialized replicated over
        ALL devices — a host-local array is rejected by jit as an
        incompatible device set.  (KeySeq is seeded identically on every
        host, so replicating the key VALUE is sound.)  Single process:
        plain transfers."""
        if self.mesh is not None and jax.process_count() > 1:
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.mesh, PartitionSpec())
            prime = jax.make_array_from_process_local_data(
                repl, np.asarray(prime_np, np.int32))
            key_data = jax.make_array_from_process_local_data(
                repl, np.asarray(jax.random.key_data(key)))
            key = jax.random.wrap_key_data(key_data)
            return prime, key
        return jnp.asarray(prime_np), key

    def _sample_and_log(self, state, valid_batch, step: int) -> None:
        """In-training sampling (reference train.py:219-228): prime with the
        first ``prime_length`` tokens of a validation row, decode, log.

        Multi-host: the per-host valid streams are disjoint, so process 0's
        prime row is broadcast to every host and placed replicated over the
        global mesh (the sampler then runs as one SPMD program against the
        globally-sharded params — a host-local prime would be rejected by
        jit as an incompatible device set)."""
        cfg = self.cfg
        prime_np = np.asarray(valid_batch[:1, : cfg.prime_length], np.int32)
        if self.mesh is not None and jax.process_count() > 1:
            from jax.experimental import multihost_utils

            prime_np = multihost_utils.broadcast_one_to_all(prime_np)
        prime, key = self._replicated_prime_and_key(prime_np, next(self.keys))
        sampled = self.sampler(
            {"params": state.params}, key, prime,
            length=self.model_config.seq_len, top_k=cfg.sample_top_k,
        )
        prime_str = decode_tokens(np.asarray(prime[0]))
        sampled_str = decode_tokens(np.asarray(sampled[0, cfg.prime_length:]))
        if jax.process_index() == 0:
            print(prime_str, "\n", "*" * 40, "\n", sampled_str)
        self.tracker.log_sample(prime_str, sampled_str, step)
